"""Central registry of every `RAY_TPU_*` environment knob.

71 env knobs existed across 30 files before this registry, each read
site re-stating its own default and parse — RAY_TPU_STORE_BYTES was
read with two different defaults, a misspelled knob name was silently
inert, and none of it was documented. One `_declare` per knob now
states the type, canonical default, and doc string; every read in the
package goes through the typed getters here (raylint RT005 enforces
it), and `docs/CONFIG.md` is generated from this table
(`python -m ray_tpu.util.knobs > docs/CONFIG.md`; a tier-1 test keeps
it in sync).

Getter semantics, uniform across the package:

  * the environment is read at CALL time (tests monkeypatch env vars
    after import; values must not be baked in at module load);
  * unset OR empty-string values fall back to the default;
  * a malformed value (e.g. `RAY_TPU_LEASE_SLOTS=lots`) falls back to
    the default instead of crashing whatever process read it;
  * `get_bool` treats `0 / false / no / off / ""` (any case) as False,
    everything else as True;
  * a site may pass `default=` to override the declared default when
    the real default is dynamic (the node agent's smaller store arena,
    death timeout derived from the heartbeat timeout) — the declared
    default documents the common case;
  * reading an UNDECLARED knob raises KeyError — declare it here
    first, with a doc string.

Knobs marked "wiring" are set by the runtime for its child processes
(worker/agent env), not by operators; they are declared so the one
table is complete and RT005 has no carve-outs.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

_UNSET = object()

_FALSEY = ("0", "false", "no", "off", "")


@dataclass(frozen=True)
class Knob:
    name: str
    type: str          # "int" | "float" | "bool" | "str"
    default: Any       # canonical default; None = unset
    doc: str
    subsystem: str
    wiring: bool = False   # set by the runtime for child processes


REGISTRY: Dict[str, Knob] = {}


def _declare(name: str, type_: str, default: Any, doc: str,
             subsystem: str, wiring: bool = False) -> None:
    assert name.startswith("RAY_TPU_"), name
    assert type_ in ("int", "float", "bool", "str"), type_
    assert name not in REGISTRY, f"duplicate knob {name}"
    REGISTRY[name] = Knob(name, type_, default, doc, subsystem, wiring)


# ---------------------------------------------------------------------------
# core: dispatch plane (docs/SCHEDULING.md)

_declare("RAY_TPU_BATCH", "bool", True,
         "Batched control-plane messaging: driver-side submit "
         "coalescing and the worker completion batcher. 0 forces one "
         "frame per message (the pre-PR-8 wire).", "core dispatch")
_declare("RAY_TPU_BATCH_FLUSH_N", "int", 64,
         "Messages coalesced into one batch frame before a size "
         "flush.", "core dispatch")
_declare("RAY_TPU_BATCH_FLUSH_S", "float", 0.001,
         "Batch flush window in seconds (time flush).",
         "core dispatch")
_declare("RAY_TPU_LEASE_SLOTS", "int", 32,
         "Queued tasks granted to one worker in a multi-slot lease "
         "frame.", "core dispatch")
_declare("RAY_TPU_ACTOR_PIPELINE", "int", 32,
         "Actor-call slots dispatched to a worker beyond each lane's "
         "concurrency limit (pipelining; the worker enforces the "
         "execution bound).", "core dispatch")
_declare("RAY_TPU_LEASE_HEAD_S", "float", 1.0,
         "Seconds a leased FIFO head may stay parked in get()/wait() "
         "before the driver reclaims its unstarted slots "
         "(0 disables).", "core dispatch")
_declare("RAY_TPU_NODE_LEASES", "bool", True,
         "Two-level scheduling (docs/SCHEDULING.md): the driver grants "
         "whole batches of compatible queued tasks to a remote node "
         "agent in one frame, and the agent fans them across its local "
         "workers without driver round trips. 0 falls back to "
         "per-worker leases.", "core dispatch")
_declare("RAY_TPU_NODE_LEASE_SLOTS", "int", 128,
         "Per-worker queue depth inside a node-level bulk lease (the "
         "lease budget is leased-workers x this). Deep on purpose: "
         "the agent owns its backlog, and a shallow budget starves it "
         "into per-completion ack/extend chatter.", "core dispatch")
_declare("RAY_TPU_NODE_LEASE_DEPTH", "int", 8,
         "Tasks a node agent keeps in flight per local worker within "
         "a bulk lease (FIFO at the worker). Depth >1 pipelines the "
         "dispatch round trip so sub-millisecond tasks never leave a "
         "worker idle; only the FIFO head can have started, so spill "
         "accounting stays exact.", "core dispatch")
_declare("RAY_TPU_NODE_LEASE_SPILL_S", "float", 5.0,
         "Seconds a node agent may hold an unplaceable leased task "
         "(all local workers busy/dead) before spilling it back to "
         "the driver queue.", "core dispatch")
_declare("RAY_TPU_NODE_LEASE_IDLE_S", "float", 2.0,
         "Linger for a drained standing node lease (agent-local "
         "nested submissions): workers release back to the driver "
         "after this long with no agent-local traffic.",
         "core dispatch")
_declare("RAY_TPU_AGENT_ADDR", "str", "",
         "Agent-local dispatch socket a node agent passes to the "
         "workers it spawns (internal wiring).", "core dispatch",
         wiring=True)
_declare("RAY_TPU_DIRECT_CALLS", "bool", True,
         "Direct worker->worker actor-call channels (zero driver "
         "messages steady-state). 0 pins every call to the driver "
         "path.", "core dispatch")
_declare("RAY_TPU_WIRE", "bool", True,
         "Compact msgpack codec for hot control-frame kinds. 0 forces "
         "legacy all-pickle framing.", "core dispatch")
_declare("RAY_TPU_COMPILED_DAGS", "bool", True,
         "Compiled-DAG pipelined execution (docs/DAG.md): compile "
         "resolves placement once, pins a worker per stage and "
         "pre-opens reusable channels; execute() pushes input with "
         "zero driver control messages. 0 falls back to the "
         "level-batched dynamic path (one submit_many per level).",
         "core dispatch")
_declare("RAY_TPU_DAG_CHANNEL_BYTES", "int", 1 << 20,
         "Initial capacity of a compiled-DAG same-node channel "
         "segment. A payload larger than the current capacity grows "
         "the channel into a fresh generation-suffixed segment (the "
         "old one is unlinked); cross-node edges are unaffected (they "
         "ride the peer socket frame).", "core dispatch")
_declare("RAY_TPU_DAG_CHANNEL_DEPTH", "int", 16,
         "Ack window of a compiled-DAG channel for inline payloads: a "
         "writer may run this many seqnos ahead of its reader before "
         "blocking, which is what lets pipeline stages overlap. "
         "Shared-memory segment payloads always gate at depth 1 (the "
         "segment is rewritten in place, so the previous payload must "
         "be consumed first).", "core dispatch")
_declare("RAY_TPU_DAG_COMPILE_TIMEOUT_S", "float", 30.0,
         "Deadline for a compiled DAG's placement + channel install "
         "handshake. Expiry raises CompiledDagError and releases any "
         "partially pinned workers.", "core dispatch")

# ---------------------------------------------------------------------------
# core: runtime + object store

_declare("RAY_TPU_MAX_WORKERS", "int", 16,
         "Driver-local worker-pool size cap.", "core runtime")
_declare("RAY_TPU_STORE_BYTES", "int", 8 << 30,
         "Shared-memory object-store arena capacity in bytes (node "
         "agents default to 2 GiB).", "core runtime")
_declare("RAY_TPU_SPILL_THRESHOLD", "float", 0.6,
         "Arena-fullness watermark where the spiller starts copying "
         "segments to disk.", "core runtime")
_declare("RAY_TPU_SPILL_DIR", "str", None,
         "Spill directory. The driver/agent sets it for its workers; "
         "operators may pre-set it to pick the disk.", "core runtime")
_declare("RAY_TPU_FETCH_CHUNK", "int", 64 << 20,
         "Max bytes per relay/fetch stream frame on the driver-relay "
         "path.", "core runtime")
_declare("RAY_TPU_LISTEN", "str", None,
         "tcp://host:port control listener enabling multi-host "
         "clusters (unset = unix socket only).", "core runtime")
_declare("RAY_TPU_LOG_DIR", "str", None,
         "Per-job worker log directory (enables output redirection "
         "and per-task log attribution).", "core runtime")
_declare("RAY_TPU_LOG_TAIL_BYTES", "int", 4 << 20,
         "Trailing bytes read per worker log file when building "
         "task-attributed tails.", "core runtime")
_declare("RAY_TPU_DEVICE_OBJECTS", "bool", True,
         "Device-resident object store (TPU buffers stay in HBM "
         "between tasks).", "core runtime")
_declare("RAY_TPU_DEVICE_OBJECTS_MAX", "int", 256,
         "Max device-resident object entries before LRU eviction to "
         "host.", "core runtime")
_declare("RAY_TPU_NODE_ID", "str", None,
         "This process's node id.", "core runtime", wiring=True)
_declare("RAY_TPU_JOB_ID", "str", "job-default",
         "Job id stamped on work from this process.", "core runtime",
         wiring=True)
_declare("RAY_TPU_ARENA_NAME", "str", None,
         "Shared-memory arena name workers attach to (native store "
         "backend).", "core runtime", wiring=True)

# ---------------------------------------------------------------------------
# core: fault tolerance (docs/FAULT_TOLERANCE.md)

_declare("RAY_TPU_LINEAGE", "bool", True,
         "Lineage-based object reconstruction (0 = lost objects are "
         "errors, never re-executions).", "fault tolerance")
_declare("RAY_TPU_LINEAGE_BYTES", "int", 64 << 20,
         "Byte budget for retained finished TaskSpecs in the lineage "
         "table.", "fault tolerance")
_declare("RAY_TPU_MAX_RECONSTRUCTION_DEPTH", "int", 16,
         "Max producer-chain depth one reconstruction may re-execute.",
         "fault tolerance")
_declare("RAY_TPU_MAX_RECONSTRUCTIONS", "int", 20,
         "Per-task cap on reconstruction re-runs (repeat-loss "
         "breaker).", "fault tolerance")
_declare("RAY_TPU_RECONSTRUCTION_WAIT_S", "float", 60,
         "How long a reader blocks for a reconstruction it "
         "triggered.", "fault tolerance")
_declare("RAY_TPU_METRICS_INTERVAL_S", "float", 1.0,
         "Telemetry ship interval for workers and node agents "
         "(metrics/spans/events deltas; <= 0 disables).",
         "fault tolerance")
_declare("RAY_TPU_NODE_HEARTBEAT_S", "float", 2.0,
         "Node-agent heartbeat interval (<= 0 disables heartbeats "
         "AND the agent-side driver-silence watchdog).",
         "fault tolerance")
_declare("RAY_TPU_NODE_HEARTBEAT_TIMEOUT_S", "float", 10,
         "Heartbeat silence after which the driver flags "
         "node.heartbeat_miss.", "fault tolerance")
_declare("RAY_TPU_NODE_DEATH_TIMEOUT_S", "float", None,
         "Heartbeat silence after which the driver DECLARES the node "
         "dead without waiting for the socket to close (default: 2x "
         "the heartbeat timeout; 0 disables heartbeat-declared "
         "death).", "fault tolerance")
_declare("RAY_TPU_DRIVER_SILENCE_S", "float", 30,
         "Agent-side mirror of heartbeat-declared death: total driver "
         "silence (no frames, no heartbeat acks) past this long makes "
         "the agent treat the connection as half-open-dead and enter "
         "its rejoin loop instead of parking in recv() for the ~15min "
         "TCP retransmit timeout (<= 0 disables).", "fault tolerance")
_declare("RAY_TPU_NODE_REJOIN_S", "float", 30,
         "Window an agent that lost its driver connection keeps "
         "trying to re-register under a new incarnation "
         "(0 disables).", "fault tolerance")
_declare("RAY_TPU_ACTOR_CHECKPOINT_INTERVAL_S", "float", 0,
         "Cluster-wide default throttle between actor __ray_save__ "
         "checkpoints (per-actor checkpoint_interval_s option wins; "
         "0 = checkpoint after every completed call).",
         "fault tolerance")
_declare("RAY_TPU_PG_INFEASIBLE_GRACE_S", "float", 10,
         "How long a pending placement group may be infeasible "
         "against the live topology before it is declared "
         "impossible.", "fault tolerance")

# ---------------------------------------------------------------------------
# core: peer-to-peer object transfer (docs/OBJECT_TRANSFER.md)

_declare("RAY_TPU_TRANSFER_CHUNK", "int", 4 << 20,
         "Chunk size for peer-to-peer object streaming.",
         "object transfer")
_declare("RAY_TPU_TRANSFER_RETRIES", "int", 3,
         "Pull retry rounds across candidate holders.",
         "object transfer")
_declare("RAY_TPU_TRANSFER_TIMEOUT_S", "float", 20,
         "Socket timeout for one transfer attempt.",
         "object transfer")
_declare("RAY_TPU_TRANSFER_BACKOFF_S", "float", 0.05,
         "Base backoff between pull retry rounds (jittered, "
         "doubling).", "object transfer")
_declare("RAY_TPU_PULL_DEADLINE_S", "float", 30,
         "Total wall-clock budget for one pull across all retries "
         "and holders.", "object transfer")

# ---------------------------------------------------------------------------
# core: control-plane persistence (docs/FAULT_TOLERANCE.md)

_declare("RAY_TPU_STATE_DIR", "str", None,
         "Directory for the GCS WAL + snapshots; setting it makes "
         "driver state durable and enables init(resume=True).",
         "persistence")
_declare("RAY_TPU_WAL_FSYNC", "bool", False,
         "fsync every WAL append (durability over throughput).",
         "persistence")
_declare("RAY_TPU_GCS_SNAPSHOT_INTERVAL_S", "float", 30,
         "Seconds between control-plane snapshots (each rotates the "
         "WAL).", "persistence")
_declare("RAY_TPU_GCS_SNAPSHOT_WAL_BYTES", "int", 32 << 20,
         "WAL size that forces a snapshot before the interval "
         "elapses.", "persistence")
_declare("RAY_TPU_RESUME_REATTACH_GRACE_S", "float", None,
         "How long a resumed driver parks restored remote-held "
         "objects awaiting their agent's reattach before falling "
         "back to lineage reconstruction (default: the rejoin "
         "window).", "persistence")

# ---------------------------------------------------------------------------
# telemetry (docs/OBSERVABILITY.md)

_declare("RAY_TPU_EVENTS", "bool", True,
         "Structured event plane (0 disables all emit()s).",
         "telemetry")
_declare("RAY_TPU_EVENT_BUFFER", "int", 4096,
         "Per-process event ring size between telemetry flushes "
         "(overflow counts surface as events.dropped).", "telemetry")
_declare("RAY_TPU_EVENT_STORE", "int", 16384,
         "Driver-side cluster event store ring size.", "telemetry")
_declare("RAY_TPU_FASTPATH_SPANS", "bool", True,
         "Trace spans on the zero-driver fast paths (direct "
         "worker->worker calls, task leases, compiled-DAG stages); "
         "spans ride the existing telemetry heartbeat, never the "
         "control plane.", "telemetry")
_declare("RAY_TPU_PROFILE_HZ", "float", 0,
         "Always-on sampling profiler rate per worker (stack samples "
         "per second; 0 disables the sampler thread). Can be raised "
         "per worker at runtime via the profile control plane.",
         "telemetry")
_declare("RAY_TPU_PROFILE_MAX_STACKS", "int", 2048,
         "Distinct folded stacks a worker aggregates between "
         "telemetry flushes; overflow collapses into a single "
         "'(overflow)' bucket so profiler memory stays bounded.",
         "telemetry")
_declare("RAY_TPU_PROFILE_DEPTH", "int", 24,
         "Max frames kept per sampled stack (deepest frames beyond "
         "this are truncated).", "telemetry")
_declare("RAY_TPU_WAITS", "bool", True,
         "Wait-state plane (docs/OBSERVABILITY.md): every blocking "
         "edge registers a WaitRecord; the driver folds them into the "
         "cluster wait graph behind `ray_tpu stuck`, hang/deadlock/"
         "straggler detection, and /api/waitgraph. 0 makes park a "
         "no-op and disables the watchdog.", "telemetry")
_declare("RAY_TPU_HANG_PROBE_S", "float", 5.0,
         "Wait-graph watchdog cadence: the driver assembles the "
         "cluster wait graph and probes it for cycles, stale waits, "
         "and collective stragglers this often (<= 0 disables the "
         "watchdog; the wait plane itself stays on).", "telemetry")
_declare("RAY_TPU_HANG_WARN_S", "float", 30.0,
         "Age past which a wait is flagged sched.hang.suspected with "
         "its live root cause attached (deadlock cycles and "
         "straggler detection do not wait for this).", "telemetry")

# ---------------------------------------------------------------------------
# serve plane (docs/SERVING.md)

_declare("RAY_TPU_SERVE_HEALTH_PERIOD_S", "float", None,
         "Cluster-wide health-probe period override (unset: each "
         "deployment's health_check_period_s wins).", "serve")
_declare("RAY_TPU_SERVE_HEALTH_TIMEOUT_S", "float", None,
         "Cluster-wide health-probe timeout override.", "serve")
_declare("RAY_TPU_SERVE_HEALTH_THRESHOLD", "float", None,
         "Cluster-wide consecutive-failure threshold override.",
         "serve")
_declare("RAY_TPU_SERVE_REQUEST_TIMEOUT_S", "float", 60,
         "Per-request budget when the client supplies no deadline "
         "(HTTP X-Serve-Timeout-S / gRPC deadline).", "serve")
_declare("RAY_TPU_ENGINE_WATCHDOG_S", "float", 30,
         "LLM engine no-forward-progress watchdog; in-dispatch "
         "stalls get 10x grace for first-use jit compiles "
         "(<= 0 disables).", "serve")
_declare("RAY_TPU_SERVE_AFFINITY_BOUND", "float", 2.0,
         "Consistent-hash bounded-load factor c: an affinity home "
         "over c*(mean+1) in-flight diverts to the ring walk.",
         "serve")
_declare("RAY_TPU_SERVE_AFFINITY_SESSIONS", "int", 4096,
         "Session/prefix bindings kept per handle (LRU beyond it).",
         "serve")

# ---------------------------------------------------------------------------
# train plane (docs/FAULT_TOLERANCE.md, elastic gangs)

_declare("RAY_TPU_GANG_PROBE_S", "float", 0.25,
         "Gang supervisor poll interval over the rank actors' GCS "
         "state.", "train")
_declare("RAY_TPU_GANG_REFORM_TIMEOUT_S", "float", 120,
         "Total budget for one gang reform (capacity wait + re-gang "
         "+ join).", "train")
_declare("RAY_TPU_GANG_REPLACE_WAIT_S", "float", 5,
         "How long reform waits for FULL replacement capacity before "
         "settling for a resharded (smaller) world.", "train")
_declare("RAY_TPU_TRAIN_MAX_FAILURES", "int", 8,
         "Gang failures an elastic fit() survives before giving up.",
         "train")
_declare("RAY_TPU_ELASTIC_TRACE", "str", None,
         "Path for the elastic trainer's debug trace log (unset "
         "disables).", "train")
_declare("RAY_TPU_TRAIN_RANK", "int", 0,
         "This rank process's index in the SPMD world.", "train",
         wiring=True)
_declare("RAY_TPU_TRAIN_WORLD", "int", 1,
         "SPMD world size for this rank process.", "train",
         wiring=True)
_declare("RAY_TPU_COORDINATOR", "str", None,
         "jax.distributed coordinator address for multi-host "
         "worlds.", "train", wiring=True)

# ---------------------------------------------------------------------------
# data plane

_declare("RAY_TPU_DATA_INFLIGHT_BYTES", "int", 256 << 20,
         "Streaming-executor backpressure budget: bytes of blocks in "
         "flight per stage.", "data")

_declare("RAY_TPU_DATA_PREFETCH_DEPTH", "int", 2,
         "device_put_iterator prefetch depth: host batches staged "
         "into device memory ahead of the consumer.", "data")

_declare("RAY_TPU_DATA_SERVICE_MIN_WORKERS", "int", 1,
         "Data service: minimum data-worker actors kept alive per "
         "service.", "data")

_declare("RAY_TPU_DATA_SERVICE_MAX_WORKERS", "int", 4,
         "Data service: maximum data-worker actors per service; also "
         "the default slice count for registered datasets.", "data")

_declare("RAY_TPU_DATA_SERVICE_LEASE_S", "float", 10.0,
         "Data service: consumer lease duration. A consumer silent "
         "longer than this is fenced and its outstanding shard grants "
         "are revoked back to the pool.", "data")

_declare("RAY_TPU_DATA_SERVICE_TICK_S", "float", 0.2,
         "Data service: dispatcher housekeeping period (autoscaling, "
         "worker liveness, lease expiry, metrics).", "data")

_declare("RAY_TPU_DATA_SERVICE_PRODUCE_AHEAD", "int", 64,
         "Data service: per-worker produce-ahead bound — a data worker "
         "pauses when this many of its blocks sit unconsumed.", "data")

_declare("RAY_TPU_DATA_SERVICE_POLL_S", "float", 0.05,
         "Data service: consumer-side poll interval while waiting for "
         "a shard grant (epoch barrier / production lag).", "data")

# ---------------------------------------------------------------------------
# ops / TPU topology

_declare("RAY_TPU_ATTN_IMPL", "str", "auto",
         "Attention kernel selection (auto | pallas | xla | ...).",
         "ops")
_declare("RAY_TPU_PAGED_ATTN_IMPL", "str", "auto",
         "Paged-attention kernel selection (auto | gather | ...).",
         "ops")
_declare("RAY_TPU_FLASH_BLOCK_Q", "int", 128,
         "Flash-attention query block size.", "ops")
_declare("RAY_TPU_FLASH_BLOCK_K", "int", 128,
         "Flash-attention key block size.", "ops")
_declare("RAY_TPU_POD_TYPE", "str", None,
         "TPU pod/accelerator type override (else "
         "TPU_ACCELERATOR_TYPE).", "topology")
_declare("RAY_TPU_SLICE", "str", None,
         "TPU slice name override (else TPU_NAME).", "topology")
_declare("RAY_TPU_WORKER_ID", "str", None,
         "TPU pod worker index override (else TPU_WORKER_ID).",
         "topology")
_declare("RAY_TPU_CHIPS", "int", None,
         "Local TPU chip count override (else detected).", "topology")
_declare("RAY_TPU_NODE_TYPE", "str", None,
         "Autoscaler node-type label this agent registers with.",
         "topology")


# ---------------------------------------------------------------------------
# typed getters


def _resolve(name: str, default: Any) -> Any:
    try:
        spec = REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"{name!r} is not a declared knob; declare it in "
            "ray_tpu/util/knobs.py (default, type, doc) first") \
            from None
    return spec.default if default is _UNSET else default


def get_raw(name: str) -> Optional[str]:
    """The raw env value ("" treated as unset), or None."""
    _resolve(name, _UNSET)   # declaration teeth
    raw = os.environ.get(name)
    return raw if raw not in (None, "") else None


def get_str(name: str, default: Any = _UNSET) -> Optional[str]:
    raw = os.environ.get(name)
    if raw in (None, ""):
        return _resolve(name, default)
    _resolve(name, default)
    return raw


def get_int(name: str, default: Any = _UNSET) -> Optional[int]:
    fallback = _resolve(name, default)
    raw = os.environ.get(name)
    if raw in (None, ""):
        return fallback
    try:
        return int(raw)
    except ValueError:
        return fallback


def get_float(name: str, default: Any = _UNSET) -> Optional[float]:
    fallback = _resolve(name, default)
    raw = os.environ.get(name)
    if raw in (None, ""):
        return fallback
    try:
        return float(raw)
    except ValueError:
        return fallback


def get_bool(name: str, default: Any = _UNSET) -> bool:
    fallback = _resolve(name, default)
    raw = os.environ.get(name)
    if raw in (None, ""):
        return bool(fallback)
    return raw.strip().lower() not in _FALSEY


def declared(name: str) -> bool:
    return name in REGISTRY


# ---------------------------------------------------------------------------
# docs generation


def _display_default(k: Knob) -> str:
    if k.default is None:
        return "(unset)"
    if k.type == "bool":
        return "1" if k.default else "0"
    return str(k.default)


def render_markdown() -> str:
    """The docs/CONFIG.md body. Regenerate with
    `python -m ray_tpu.util.knobs > docs/CONFIG.md`."""
    lines: List[str] = [
        "# Configuration knobs",
        "",
        "<!-- GENERATED from ray_tpu/util/knobs.py — do not edit by "
        "hand. -->",
        "<!-- Regenerate: python -m ray_tpu.util.knobs > "
        "docs/CONFIG.md -->",
        "",
        "Every `RAY_TPU_*` environment knob, generated from the "
        "central registry in `ray_tpu/util/knobs.py`. All reads go "
        "through the registry's typed getters (enforced by raylint "
        "check RT005 — see `docs/STATIC_ANALYSIS.md`); unset or "
        "malformed values fall back to the default shown. Knobs "
        "marked *(wiring)* are set by the runtime for its child "
        "processes, not by operators.",
    ]
    order: List[str] = []
    for k in REGISTRY.values():
        if k.subsystem not in order:
            order.append(k.subsystem)
    for subsystem in order:
        lines += ["", f"## {subsystem}", "",
                  "| knob | type | default | description |",
                  "| --- | --- | --- | --- |"]
        for k in REGISTRY.values():
            if k.subsystem != subsystem:
                continue
            doc = k.doc.replace("|", "\\|")
            if k.wiring:
                doc = "*(wiring)* " + doc
            lines.append(f"| `{k.name}` | {k.type} | "
                         f"`{_display_default(k)}` | {doc} |")
    return "\n".join(lines) + "\n"


if __name__ == "__main__":
    print(render_markdown(), end="")
