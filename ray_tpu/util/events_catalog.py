"""Catalog of every built-in cluster lifecycle event type.

One place declares type / default severity / help for the structured
event plane (`util/events.py`), mirroring `metrics_catalog.py` for
metrics: docs/OBSERVABILITY.md renders this table and a tier-1 lint
test asserts every event type emitted by package code is cataloged and
follows the `<subsystem>.<event>` naming rule.

Reference counterpart: the task-event/export subsystem behind
`ray list tasks --detail` (src/ray/gcs task events + the export API) —
collapsed to a single catalog because the single-controller driver is
the only consumer-facing store.
"""
from __future__ import annotations

import re
from typing import Dict, Tuple

# type -> (default_severity, help)
_SPEC = Tuple[str, str]

SEVERITIES = ("info", "warning", "error")

# <subsystem>.<event> up to <subsystem>.<service>.<object>.<event>
# (the serve plane namespaces per object: serve.replica.*; the data
# service namespaces per verb: data.service.shard.grant)
NAME_RE = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+){1,3}$")

BUILTIN: Dict[str, _SPEC] = {
    # ---- task lifecycle (driver dispatcher) ----
    "task.submit": (
        "info", "task registered with the scheduler"),
    "task.sched": (
        "info", "task dispatched to a worker (submit -> running)"),
    "task.retry": (
        "warning", "task re-queued after a worker/node death or "
        "lineage reconstruction (message holds the cause)"),
    "task.finish": (
        "info", "task completed successfully"),
    "task.lease.grant": (
        "info", "a worker was granted a multi-slot task lease (one "
        "dispatch frame carrying several queued tasks; attrs carry the "
        "slot count)"),
    "task.lease.revoke": (
        "warning", "a task lease ended before every slot ran: the "
        "worker died, or its running head blocked in get() and the "
        "unstarted slots were reclaimed for other workers (zero lost "
        "tasks either way — unstarted slots re-queue without burning "
        "a retry)"),
    "task.lease.node_grant": (
        "info", "a node AGENT was granted a bulk lease (two-level "
        "scheduling, docs/SCHEDULING.md): one frame carrying a worker "
        "set plus a task batch; the agent fans the batch across its "
        "local workers and refills them without driver round trips "
        "(attrs carry worker and slot counts)"),
    "task.spillback": (
        "warning", "a node agent handed granted tasks back to the "
        "driver queue (placement timeout, worker death, or a fenced "
        "lease); unstarted tasks re-queue without burning a retry "
        "(attrs carry the reason and count)"),
    "task.dispatch.local": (
        "info", "a direct worker->worker call channel was established "
        "via the sys.actor_addr directory; steady-state calls on it "
        "bypass the driver entirely"),
    "task.fail": (
        "error", "task reached FAILED (message holds the error)"),
    "task.cancel": (
        "warning", "task cancelled"),
    # ---- actor lifecycle ----
    "actor.create": (
        "info", "actor creation registered"),
    "actor.alive": (
        "info", "actor constructor finished; actor is ALIVE"),
    "actor.restart": (
        "warning", "actor worker died; restart scheduled "
        "(restart budget remaining)"),
    "actor.death": (
        "error", "actor reached DEAD (message holds death_cause)"),
    "actor.checkpoint": (
        "info", "actor shipped a __ray_save__ state checkpoint to the "
        "driver (restored via __ray_restore__ around a restart)"),
    "actor.restore": (
        "info", "restarted actor resumed from its last __ray_save__ "
        "checkpoint via __ray_restore__"),
    # ---- object lifecycle ----
    "object.seal": (
        "info", "object payload sealed into a store"),
    "object.spill": (
        "info", "object copied to disk by the watermark spiller"),
    "object.transfer": (
        "info", "object copy landed on another node "
        "(peer pull or relay re-host)"),
    "object.free": (
        "info", "object freed and its payloads reclaimed"),
    "object.lost": (
        "error", "object payload lost with no live copy (severity "
        "warning when lineage reconstruction follows; error when the "
        "producer is not re-executable)"),
    "object.reconstruct": (
        "warning", "lost object's producing task re-queued from the "
        "driver's lineage table (the Ray-paper availability trick: a "
        "lost object is a re-execution, not an error)"),
    # ---- driver / control-plane persistence ----
    "driver.restart": (
        "warning", "a driver resumed from persisted state "
        "(RAY_TPU_STATE_DIR) under a bumped incarnation; attrs carry "
        "replayed WAL record count and torn-tail/clean flags"),
    "gcs.snapshot": (
        "info", "control-plane snapshot written and WAL rotated "
        "(bounds replay time after a driver crash)"),
    # ---- node lifecycle ----
    "node.register": (
        "info", "node agent joined the cluster"),
    "node.reattach": (
        "info", "a node agent (with its surviving object store) "
        "re-registered with a RESTARTED driver; its restored objects "
        "were re-sealed and are ready again"),
    "node.heartbeat_miss": (
        "warning", "node stopped heartbeating (stale or connection "
        "lost); death determination may follow"),
    "node.death": (
        "error", "node declared dead (socket close or heartbeat "
        "silence past RAY_TPU_NODE_DEATH_TIMEOUT_S); its work fails "
        "over and its object copies are pruned"),
    "node.rejoin": (
        "info", "a dead-declared node re-registered under a new "
        "incarnation; queued work may flow to it again"),
    "node.fence": (
        "warning", "traffic from a superseded node incarnation dropped "
        "(stalled agent recovered after its death determination)"),
    "node.memory_pressure": (
        "warning", "host available memory crossed the pressure "
        "threshold (the RSS watchdog may kill a worker next)"),
    # ---- worker pool ----
    "worker.start": (
        "info", "worker process spawned"),
    "worker.death": (
        "warning", "worker process died or was terminated"),
    "worker.profile.start": (
        "info", "a worker's sampling profiler started (or changed "
        "rate) via RAY_TPU_PROFILE_HZ or an on-demand profile_ctl "
        "request; attrs carry the hz"),
    "worker.profile.stop": (
        "info", "a worker's sampling profiler stopped via an "
        "on-demand profile_ctl request"),
    # ---- compiled DAGs (docs/DAG.md) ----
    "dag.compile": (
        "info", "compiled-DAG pipeline placed and wired: attrs carry "
        "stage, pinned-worker, and channel counts (schedule once — "
        "every execute() after this costs zero driver messages)"),
    "dag.channel.open": (
        "info", "one reusable object channel connected (same-node: "
        "rewritten shm segment; cross-node: persistent socket)"),
    "dag.channel.close": (
        "info", "a compiled DAG's channels released at teardown "
        "(segments unlinked, sockets closed)"),
    "dag.teardown": (
        "info", "compiled-DAG pipeline released its pinned workers; "
        "attrs carry the reason (close(), failure cause, shutdown)"),
    "dag.fail": (
        "error", "compiled-DAG pipeline infrastructure failed "
        "(participant death / channel loss); in-flight executions got "
        "CompiledDagError and the next execute() re-compiles"),
    "dag.exec.fallback": (
        "info", "compiled DAG running on the dynamic level-batched "
        "path (RAY_TPU_COMPILED_DAGS=0 or a pipeline-ineligible graph "
        "shape; attrs carry the reason)"),
    # ---- scheduler ----
    "scheduler.backpressure": (
        "warning", "task/actor pending past the stuck-warning window "
        "with nowhere to place it"),
    # ---- wait-graph hang detection (observability/waitgraph.py) ----
    "sched.deadlock.detected": (
        "error", "the wait-graph watchdog found a cycle (e.g. two "
        "actors ray.get-ing each other's pending calls): attrs name "
        "every participant and edge of the cycle; the workload cannot "
        "make progress without intervention"),
    "sched.hang.suspected": (
        "warning", "a wait older than RAY_TPU_HANG_WARN_S with its "
        "live root cause attached (the far end of the wait chain), or "
        "an existing hang mitigation firing (consumer-stall TTL, "
        "driver-silence watchdog)"),
    "sched.hang.resolved": (
        "info", "a previously suspected hang's wait chain drained — "
        "on its own, or via a mitigation like the consumer-stall TTL "
        "(attrs carry how long it was stuck)"),
    # ---- serve LLM engine ----
    "llm_engine.request_admit": (
        "info", "request took a decode slot (prefill dispatching)"),
    "llm_engine.request_preempt": (
        "warning", "request held back at admission (KV page pool "
        "exhausted); re-admitted when pages free"),
    "llm_engine.request_finish": (
        "info", "request released its slot (finished or errored)"),
    "llm_engine.request_abort": (
        "warning", "request aborted by the client"),
    "llm_engine.wedged": (
        "error", "generation loop made no forward progress past "
        "RAY_TPU_ENGINE_WATCHDOG_S with requests admitted; in-flight "
        "requests aborted with EngineWedgedError and the replica's "
        "health check fails with a `wedged` cause"),
    # ---- serve fault-tolerance plane ----
    "serve.replica.unhealthy": (
        "error", "replica failed RAY_TPU_SERVE_HEALTH_THRESHOLD "
        "consecutive controller health probes (message holds the "
        "cause, e.g. wedged / timeout / ActorDiedError); it is killed "
        "and replaced"),
    "serve.replica.replaced": (
        "warning", "controller started a replacement replica for one "
        "that died or went unhealthy (attrs link old -> new ids)"),
    "serve.replica.drain": (
        "info", "replica finished (or timed out) its graceful drain on "
        "rolling update / scale-down / shutdown and was stopped"),
    "serve.request.failover": (
        "warning", "a request was resubmitted to a different replica "
        "after its serving replica died, wedged, or started draining"),
    "serve.request.shed": (
        "warning", "a request was shed instead of executed (propagated "
        "deadline expired before admission, or the replica is "
        "draining); the proxy surfaces 503 + Retry-After"),
    # ---- serve scale-out plane (router + autoscaler) ----
    "serve.router.affinity_hit": (
        "info", "a session/prefix-keyed request reached its warm bound "
        "replica; emitted at binding creation (per-request hits are "
        "counted by ray_tpu_serve_router_requests_total)"),
    "serve.router.affinity_miss": (
        "warning", "a session/prefix-keyed request could not reach its "
        "warm replica (suspect / draining / over the bounded-load cap "
        "/ gone) and was re-bound to another replica (cold prefill, "
        "never an error)"),
    "serve.autoscaler.scale_up": (
        "info", "the serve autoscaler raised a deployment's replica "
        "target from live engine metrics (attrs: from/to, reason, "
        "bin-packed feasible_now, placement group when reserved)"),
    "serve.autoscaler.scale_down": (
        "info", "the serve autoscaler lowered a deployment's replica "
        "target; the controller gracefully drains the least-busy "
        "replicas first"),
    # ---- elastic training fault tolerance ----
    "train.gang.rank_death": (
        "error", "a rank actor of a supervised SPMD gang died "
        "(preempted host, killed worker); the supervisor fails parked "
        "collective rounds fast (CollectiveRankDiedError) and arms a "
        "gang reform"),
    "train.gang.reform": (
        "warning", "the gang tore down its doomed jax.distributed "
        "world and re-ganged under a bumped generation (attrs: "
        "old_world -> world, seconds; kind replaced|resharded)"),
    "train.gang.reshard": (
        "warning", "no replacement capacity for the requested gang "
        "size: the gang reformed RESHARDED onto the surviving world "
        "(dp axis shrunk; mesh layout is a function of the surviving "
        "world, not fixed job state)"),
    "train.restore": (
        "info", "a (re)formed gang restored the last committed "
        "checkpoint onto its mesh and resumed from state.step (attrs: "
        "step, world, generation, seconds)"),
    # ---- event plane itself ----
    "events.dropped": (
        "warning", "a process's local event buffer overflowed between "
        "flushes; this many events were lost before shipping"),
    # ---- data executor ----
    "data.executor_stall": (
        "warning", "streaming stage producer stalled on the in-flight "
        "backpressure budget"),
    # ---- data service (shared data plane) ----
    "data.service.register": (
        "info", "dataset plan or consumer job registered with the "
        "data-service dispatcher (also emitted on dispatcher restore)"),
    "data.service.epoch": (
        "info", "epoch lifecycle: production complete for an epoch, or "
        "a job's consumers crossed the epoch barrier"),
    "data.service.shard.grant": (
        "info", "a produced block was leased to a consumer (at-most-"
        "once handout; consumer acks retire the grant)"),
    "data.service.shard.revoke": (
        "warning", "outstanding shard grants returned to the pool "
        "(lease expiry, consumer re-attach, or data-worker death)"),
    "data.service.worker.scale": (
        "info", "data-worker pool scaled up or down by the dispatcher "
        "autoscaler"),
}


def spec(event_type: str) -> Tuple[str, str]:
    """(default_severity, help) for a cataloged type; uncataloged user
    types default to ("info", "")."""
    return BUILTIN.get(event_type, ("info", ""))
