"""Structured cluster event plane: bounded buffers + driver-side store.

Reference counterpart: the reference runtime's task-event subsystem
(task_event_buffer.cc shipping worker-side lifecycle transitions to the
GCS, surfaced by `ray list tasks --detail` and the export API). Shape
here mirrors the metrics plane (util/metrics.py):

* every process appends lifecycle events to a bounded in-process
  `EventBuffer` via `emit()` — task submit/sched/retry/finish/fail,
  actor create/restart/death, object seal/spill/transfer/free, node
  register/heartbeat-miss/death, engine admit/preempt/finish, ... —
  each typed against the catalog (`util/events_catalog.py`);
* workers and node agents drain delta batches to the driver over the
  existing telemetry channels (report channel `sys.events`, node msg
  `"events"`), exactly like `sys.metrics`;
* the driver merges them into a `ClusterEventStore`, indexed by
  task/actor/object/node id, queried by `util.state.list_events`, the
  `events` CLI, `GET /api/events`, and the post-mortem bundler
  (observability/forensics.py).

Emission must never fail or slow user work: `emit` is a dict build and
a deque append under a lock, and the whole plane can be switched off
with RAY_TPU_EVENTS=0 (bench.py --phase events measures the on/off
task-throughput delta).
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import events_catalog
from . import knobs

# Fields promoted to top-level columns (everything else lands in attrs).
ID_KEYS = ("task_id", "actor_id", "object_id", "node_id", "worker_id")

_enabled = knobs.get_bool("RAY_TPU_EVENTS")


def set_enabled(on: bool) -> None:
    """Flip the whole plane (bench overhead A/B; emit becomes a no-op)."""
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


class EventBuffer:
    """Bounded per-process event buffer. Oldest events drop first once
    past maxlen (RAY_TPU_EVENT_BUFFER, default 4096); `dropped` counts
    them so a saturated buffer is visible, never silent."""

    def __init__(self, maxlen: Optional[int] = None):
        self.maxlen = maxlen or knobs.get_int("RAY_TPU_EVENT_BUFFER")
        self._events: collections.deque = collections.deque(
            maxlen=self.maxlen)
        self._lock = threading.Lock()
        self._seq = 0
        self.dropped = 0
        self._dropped_reported = 0

    def emit(self, event_type: str, message: str = "",
             severity: Optional[str] = None, **fields: Any) -> None:
        if not _enabled:
            return
        if severity is None:
            severity = events_catalog.spec(event_type)[0]
        ev: Dict[str, Any] = {"type": event_type, "ts": time.time(),
                              "severity": severity, "message": message}
        if fields:
            attrs = None
            for k, v in fields.items():
                if v is None:
                    continue
                if k in ID_KEYS:
                    ev[k] = v
                elif attrs is None:
                    attrs = ev["attrs"] = {k: v}
                else:
                    attrs[k] = v
        with self._lock:
            self._seq += 1
            ev["src_seq"] = self._seq
            if len(self._events) >= self.maxlen:
                self.dropped += 1
            self._events.append(ev)

    def drain(self) -> List[Dict[str, Any]]:
        """Take everything buffered so far (the shipping delta). Local
        overflow since the last drain ships as a synthetic
        `events.dropped` record, so buffer loss in a worker is visible
        at the driver, not just in this process."""
        with self._lock:
            out = list(self._events)
            self._events.clear()
            newly_dropped = self.dropped - self._dropped_reported
            self._dropped_reported = self.dropped
        if newly_dropped:
            out.append({"type": "events.dropped", "ts": time.time(),
                        "severity": "warning",
                        "message": f"local event buffer overflowed; "
                                   f"{newly_dropped} events dropped "
                                   "since the last flush",
                        "attrs": {"dropped": newly_dropped},
                        "src_seq": 0})
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)


# The process-wide buffer every instrumentation site writes to.
_buffer = EventBuffer()


def emit(event_type: str, message: str = "",
         severity: Optional[str] = None, **fields: Any) -> None:
    _buffer.emit(event_type, message, severity=severity, **fields)


def emit_safe(event_type: Optional[str] = None, message: str = "",
              counter: Optional[str] = None,
              counter_tags: Optional[Dict[str, str]] = None,
              **fields: Any) -> None:
    """Never-fail telemetry: emit an event and/or bump a cataloged
    counter, swallowing every exception — instrumentation must not
    fail the work it observes. One shared helper so the serve plane's
    event+counter sites don't each re-copy the try/except pattern."""
    try:
        if event_type is not None:
            emit(event_type, message, **fields)
        if counter is not None:
            from . import metrics_catalog as mcat  # noqa: PLC0415
            mcat.get(counter).inc(1.0, tags=counter_tags or {})
    except Exception:  # noqa: BLE001
        pass


def drain() -> List[Dict[str, Any]]:
    return _buffer.drain()


def buffer() -> EventBuffer:
    return _buffer


class ClusterEventStore:
    """Driver-side merge of event batches from every process, indexed
    by task/actor/object/node/worker id for causal-chain queries.

    Bounds: the main log keeps the newest RAY_TPU_EVENT_STORE events
    (default 16384); per-id index deques keep the newest 512 references
    each, and the id-key universe itself is capped so unbounded id churn
    (millions of objects) cannot grow the index forever. Evicted counts
    surface in summarize() — truncation is reported, never silent."""

    _PER_ID_CAP = 512
    _ID_KEY_CAP = 8192

    def __init__(self, maxlen: Optional[int] = None):
        self.maxlen = maxlen or knobs.get_int("RAY_TPU_EVENT_STORE")
        self._events: collections.deque = collections.deque(
            maxlen=self.maxlen)
        # id value -> deque of event dicts referencing it (insertion
        # ordered across ids via the "ordered dict as LRU" idiom)
        self._by_id: "collections.OrderedDict[str, collections.deque]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        self._seq = 0
        self.dropped = 0

    def ingest(self, source_tags: Optional[Dict[str, str]],
               batch: Sequence[Dict[str, Any]]) -> None:
        if not batch:
            return
        src = list((source_tags or {}).items())
        with self._lock:
            for ev in batch:
                if not isinstance(ev, dict) or "type" not in ev:
                    continue
                # ingest OWNS the batch (drain()/decode hand the dicts
                # over), so tags stamp in place — no per-event copy
                for k, v in src:
                    if k not in ev:
                        ev[k] = v
                self._seq += 1
                ev["seq"] = self._seq
                if len(self._events) >= self.maxlen:
                    self.dropped += 1
                self._events.append(ev)
                for key in ID_KEYS:
                    idv = ev.get(key)
                    if not idv:
                        continue
                    dq = self._by_id.get(idv)
                    if dq is None:
                        dq = self._by_id[idv] = collections.deque(
                            maxlen=self._PER_ID_CAP)
                        while len(self._by_id) > self._ID_KEY_CAP:
                            self._by_id.popitem(last=False)
                    else:
                        # true LRU: a long-lived hot id (the head
                        # node, "driver") must outlive the one-shot
                        # object-id churn that fills the key cap
                        self._by_id.move_to_end(idv)
                    dq.append(ev)

    # ---- queries (any thread) ----
    def for_id(self, idv: str) -> List[Dict[str, Any]]:
        """Events referencing `idv` in any id column, oldest first."""
        with self._lock:
            return list(self._by_id.get(idv, ()))

    def query(self, ids: Optional[Sequence[str]] = None,
              types: Optional[Sequence[str]] = None,
              severities: Optional[Sequence[str]] = None,
              since_seq: int = 0,
              limit: int = 100) -> Tuple[List[Dict[str, Any]], int]:
        """(rows, total_matched): newest-biased slice of matching
        events, oldest first. total_matched > len(rows) means the limit
        clipped the result."""
        with self._lock:
            if ids:
                seen: Dict[int, Dict[str, Any]] = {}
                for idv in ids:
                    for ev in self._by_id.get(idv, ()):
                        seen[ev["seq"]] = ev
                pool: List[Dict[str, Any]] = [seen[s]
                                              for s in sorted(seen)]
            elif (types is None and severities is None
                    and since_seq == 0 and limit):
                # fast path for the dashboard/CLI poll: keep only the
                # newest window instead of materializing the whole log
                total = len(self._events)
                tail: collections.deque = collections.deque(
                    self._events, maxlen=limit)
                return list(tail), total
            else:
                pool = list(self._events)
        tset = set(types) if types else None
        sset = set(severities) if severities else None
        matched = [ev for ev in pool
                   if ev.get("seq", 0) > since_seq
                   and (tset is None or ev.get("type") in tset)
                   and (sset is None or ev.get("severity") in sset)]
        total = len(matched)
        if limit and total > limit:
            matched = matched[-limit:]     # the newest window
        return matched, total

    def summarize(self) -> Dict[str, Any]:
        with self._lock:
            pool = list(self._events)
            dropped = self.dropped
            last_seq = self._seq
        by_type: Dict[str, int] = {}
        by_sev: Dict[str, int] = {}
        for ev in pool:
            by_type[ev.get("type", "?")] = \
                by_type.get(ev.get("type", "?"), 0) + 1
            sev = ev.get("severity", "info")
            by_sev[sev] = by_sev.get(sev, 0) + 1
        return {"total": len(pool), "last_seq": last_seq,
                "dropped": dropped, "by_type": by_type,
                "by_severity": by_sev}
