"""Version-compat shims for jax API drift.

The kernels and parallel ops are written against current jax names;
this module maps them onto older releases (this image ships a jax where
shard_map still lives in jax.experimental and Pallas' TPU compiler
params are TPUCompilerParams) so one rename is fixed in ONE place.
"""
from __future__ import annotations

import jax


def shard_map(*args, **kwargs):
    """jax.shard_map on new releases (replication check spelled
    check_vma); jax.experimental.shard_map with check_rep on old."""
    fn = getattr(jax, "shard_map", None)
    if fn is None:
        from jax.experimental.shard_map import shard_map as fn
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
    return fn(*args, **kwargs)


def pallas_tpu_compiler_params(**kwargs):
    """pltpu.CompilerParams (new name) / TPUCompilerParams (old name) —
    identical fields either way."""
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None) \
        or pltpu.TPUCompilerParams
    return cls(**kwargs)
