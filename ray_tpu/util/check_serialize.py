"""Serializability inspection (reference parity:
python/ray/util/check_serialize.py inspect_serializability): walk an
object that fails cloudpickle and report WHICH nested members are the
problem, instead of one opaque pickling error."""
from __future__ import annotations

import inspect
from typing import Any, Set, Tuple

from ..core import serialization


class FailureTuple:
    """One unserializable leaf: the object, its name, and its parent."""

    def __init__(self, obj: Any, name: str, parent: Any):
        self.obj = obj
        self.name = name
        self.parent = parent

    def __repr__(self):
        return f"FailureTuple(obj={self.obj!r}, name={self.name})"


def _serializable(obj: Any) -> bool:
    try:
        serialization.dumps_call(obj)
        return True
    except Exception:
        return False


def inspect_serializability(
        base_obj: Any, name: str = None,
        depth: int = 3) -> Tuple[bool, Set[FailureTuple]]:
    """Returns (serializable, failures). failures holds the deepest
    reachable unserializable members (closures, attributes, globals)."""
    name = name or getattr(base_obj, "__name__", repr(base_obj)[:40])
    failures: Set[FailureTuple] = set()
    _inspect(base_obj, name, None, depth, failures, seen=set())
    return (not failures), failures


def _inspect(obj, name, parent, depth, failures, seen):
    if id(obj) in seen:
        return
    seen.add(id(obj))
    if _serializable(obj):
        return
    n_before = len(failures)
    if depth > 0:
        for child_name, child in _children(obj):
            if not _serializable(child):
                _inspect(child, f"{name}.{child_name}", obj, depth - 1,
                         failures, seen)
    # Blame this object unless a descendant was blamed: counting actual
    # recorded failures (not merely "recursed") keeps reference cycles of
    # unserializable members from escaping blame entirely.
    if len(failures) == n_before:
        failures.add(FailureTuple(obj, name, parent))


def _children(obj):
    if inspect.isfunction(obj):
        if obj.__closure__:
            for var, cell in zip(obj.__code__.co_freevars, obj.__closure__):
                try:
                    yield var, cell.cell_contents
                except ValueError:
                    pass
        for gname in obj.__code__.co_names:
            if gname in (obj.__globals__ or {}):
                yield gname, obj.__globals__[gname]
    elif hasattr(obj, "__dict__"):
        yield from list(vars(obj).items())


__all__ = ["inspect_serializability", "FailureTuple"]
