"""State API: live introspection of the running cluster.

Reference counterpart: python/ray/util/state (list_actors/list_tasks/
list_objects/list_nodes/list_workers/list_events, summarize_*) backed by
python/ray/_private/state.py. Here the driver IS the control store, so
these read GCS tables directly and return plain dicts.

Filters are (key, op, value) triples; supported ops: "=", "==", "!=",
numeric "<", "<=", ">", ">=", and substring "contains". Every list_*
returns a `ListResult` (a list subclass): when `limit` clips rows,
`.truncated` is True and `.total` holds the full match count instead of
rows silently disappearing.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from ..core.runtime import get_runtime


class ListResult(list):
    """A plain list plus truncation metadata (`.truncated`, `.total`).
    Serializes like a list, so HTTP/JSON consumers are unchanged."""

    def __init__(self, rows, total: Optional[int] = None):
        super().__init__(rows)
        self.total = len(self) if total is None else total
        self.truncated = self.total > len(self)


def _numeric(op: str, have: Any, val: Any) -> bool:
    try:
        a, b = float(have), float(val)
    except (TypeError, ValueError):
        return False
    if op == "<":
        return a < b
    if op == "<=":
        return a <= b
    if op == ">":
        return a > b
    return a >= b


def _match(row: Dict[str, Any], filters) -> bool:
    for f in filters or ():
        key, op, val = f
        have = row.get(key)
        if op in ("=", "=="):
            if str(have) != str(val):
                return False
        elif op == "!=":
            if str(have) == str(val):
                return False
        elif op in ("<", "<=", ">", ">="):
            if not _numeric(op, have, val):
                return False
        elif op == "contains":
            if have is None or str(val) not in str(have):
                return False
        else:
            raise ValueError(f"unsupported filter op {op!r}")
    return True


def _clip(rows, filters, limit: int) -> ListResult:
    matched = [r for r in rows if _match(r, filters)]
    return ListResult(matched[:limit], total=len(matched))


def list_actors(filters=None, limit: int = 100) -> ListResult:
    rt = get_runtime()
    rows = []
    for ae in list(rt.gcs.actors.values()):
        rows.append({
            "actor_id": ae.actor_id, "class_name": ae.class_name,
            "state": ae.state, "name": ae.name or "",
            "namespace": ae.namespace, "worker_id": ae.worker_id,
            "num_restarts": ae.num_restarts,
            "death_cause": ae.death_cause,
            "resources": dict(ae.resources),
        })
    return _clip(rows, filters, limit)


def list_tasks(filters=None, limit: int = 100) -> ListResult:
    rt = get_runtime()
    rows = []
    for te in list(rt.gcs.tasks.values()):
        rows.append({
            "task_id": te.task_id, "name": te.name, "state": te.state,
            "worker_id": te.worker_id, "actor_id": te.actor_id,
            "submitted_at": te.submitted_at, "started_at": te.started_at,
            "finished_at": te.finished_at,
            "duration_s": (te.finished_at - te.started_at
                           if te.finished_at and te.started_at else None),
        })
    return _clip(rows, filters, limit)


def list_objects(filters=None, limit: int = 100) -> ListResult:
    rt = get_runtime()
    rows = []
    for oe in list(rt.gcs.objects.values()):
        loc = oe.loc
        rows.append({
            "object_id": oe.object_id, "state": oe.state,
            "owner_task": oe.owner_task,
            "size_bytes": getattr(loc, "size", None),
            "store_kind": getattr(loc, "kind", None),
            "created_at": oe.created_at,
        })
    return _clip(rows, filters, limit)


def list_nodes(filters=None, limit: int = 100) -> ListResult:
    rt = get_runtime()
    rows = []
    for ne in list(rt.gcs.nodes.values()):
        ns = getattr(rt, "cluster_nodes", {}).get(ne.node_id)
        rows.append({
            "node_id": ne.node_id, "hostname": ne.hostname,
            "alive": ne.alive, "resources": dict(ne.resources),
            "resources_available": dict(ns.avail) if ns else {},
            "labels": dict(ne.labels),
            "is_driver": ne.node_id == rt.node_id,
        })
    return _clip(rows, filters, limit)


def list_workers(filters=None, limit: int = 100) -> ListResult:
    rt = get_runtime()
    rows = []
    for w in list(rt.workers.values()):
        rows.append({
            "worker_id": w.worker_id, "pid": w.pid, "state": w.state,
            "current_task": w.current_task, "actor_id": w.actor_id,
            "tpu_capable": w.tpu_capable,
            "uptime_s": time.time() - w.started_at,
        })
    return _clip(rows, filters, limit)


def list_placement_groups(filters=None, limit: int = 100) -> ListResult:
    rt = get_runtime()
    rows = []
    for pg in list(rt.placement_groups.values()):
        rows.append({"placement_group_id": pg.pg_id, "name": pg.name,
                     "strategy": pg.strategy, "state": pg.state,
                     "bundles": list(pg.bundles)})
    return _clip(rows, filters, limit)


def list_events(filters=None, limit: int = 100,
                ids: Optional[List[str]] = None,
                types: Optional[List[str]] = None,
                severities: Optional[List[str]] = None,
                since_seq: int = 0) -> ListResult:
    """Cluster lifecycle events from the driver's ClusterEventStore
    (util/events.py), oldest first. `ids` restricts to events that
    reference any of the given task/actor/object/node/worker ids via
    the store's causal index; `filters` then applies the generic
    (key, op, value) predicates on the event rows (attrs are flattened
    into the row for filtering)."""
    rt = get_runtime()
    rt.drain_local_events()   # just-emitted driver events are queryable
    # no generic filters -> the store's own newest-window clip serves
    # directly (no full-log copy per dashboard/CLI poll); with filters
    # the clip must happen after them, so fetch everything matching
    rows, total = rt.cluster_events.query(
        ids=ids, types=types, severities=severities,
        since_seq=since_seq, limit=0 if filters else limit)
    if filters:
        flat = []
        for ev in rows:
            r = dict(ev)
            for k, v in (ev.get("attrs") or {}).items():
                r.setdefault(k, v)
            flat.append((r, ev))
        rows = [ev for r, ev in flat if _match(r, filters)]
        total = len(rows)
        if limit and len(rows) > limit:
            rows = rows[-limit:]   # the newest window
    return ListResult(rows, total=total)


def summarize_events() -> Dict[str, Any]:
    rt = get_runtime()
    rt.drain_local_events()
    return rt.cluster_events.summarize()


def wait_chains(subject_id: Optional[str] = None,
                min_age_s: float = 0.0) -> List[Dict[str, Any]]:
    """Every in-progress wait the cluster knows about, each annotated
    with its waits-on chain and a resolved root cause — the answer to
    "why is X stuck" (`ray_tpu stuck`). `subject_id` restricts to
    chains touching that task/actor/worker/object id."""
    from ..observability import waitgraph as wg_mod
    rt = get_runtime()
    now = time.time()
    records = wg_mod.gather_records(rt)
    g = wg_mod.build_graph(records, rt.gcs, now=now)
    rows: List[Dict[str, Any]] = []
    for i, r in enumerate(records):
        age = now - float(r.get("ts", now))
        if age < min_age_s:
            continue
        chain = g.chain(i)
        if subject_id is not None and not any(
                k.split(":", 1)[-1].startswith(subject_id)
                for k in chain):
            continue
        rows.append({
            "kind": r.get("kind"), "rid": r.get("rid"),
            "waiter": g.waiter_of.get(i),
            "worker_id": r.get("worker_id"),
            "node_id": r.get("node_id"),
            "task_id": r.get("task_id"),
            "age_s": round(age, 1),
            "ctx": r.get("ctx") or {},
            "chain": [g.label(k) for k in chain],
            "root_cause": g.root_cause(i),
        })
    rows.sort(key=lambda r: -r["age_s"])
    return rows


def waitgraph() -> Dict[str, Any]:
    """The folded cluster waits-on graph plus the watchdog's latest
    findings (deadlocks / suspected hangs / stragglers)."""
    from ..observability import waitgraph as wg_mod
    rt = get_runtime()
    records = wg_mod.gather_records(rt)
    g = wg_mod.build_graph(records, rt.gcs)
    out = g.to_dict()
    out["sources"] = rt.cluster_waits.sources()
    out["cycles"] = g.cycles()
    mon = getattr(rt, "_hang_monitor", None)
    out["last_probe"] = dict(mon.last_probe) if mon is not None else {}
    return out


def summarize_tasks() -> Dict[str, Any]:
    """Reference: `ray summary tasks` — counts per (name, state)."""
    rt = get_runtime()
    summary: Dict[str, Dict[str, int]] = {}
    for te in list(rt.gcs.tasks.values()):
        per = summary.setdefault(te.name, {})
        per[te.state] = per.get(te.state, 0) + 1
    return {"by_func_name": summary,
            "total": len(rt.gcs.tasks)}


def summarize_actors() -> Dict[str, Any]:
    rt = get_runtime()
    summary: Dict[str, Dict[str, int]] = {}
    for ae in list(rt.gcs.actors.values()):
        per = summary.setdefault(ae.class_name, {})
        per[ae.state] = per.get(ae.state, 0) + 1
    return {"by_class_name": summary, "total": len(rt.gcs.actors)}


def summarize_objects() -> Dict[str, Any]:
    rt = get_runtime()
    counts: Dict[str, int] = {}
    total_bytes = 0
    for oe in list(rt.gcs.objects.values()):
        counts[oe.state] = counts.get(oe.state, 0) + 1
        total_bytes += getattr(oe.loc, "size", 0) or 0
    return {"by_state": counts, "total": len(rt.gcs.objects),
            "total_size_bytes": total_bytes,
            "store_used_bytes": rt.store.used_bytes(),
            "store_capacity_bytes": getattr(rt.store, "capacity",
                                            None)}


def dispatch_summary() -> Dict[str, Any]:
    """Batched-dispatch plane health (docs/SCHEDULING.md): submit
    coalescing, worker-lease lifecycle, direct-call counters, and the
    control-plane message/frame counts the batching exists to shrink.
    Also folds in worker-reported direct-call series from the cluster
    metrics store when present."""
    rt = get_runtime()
    out: Dict[str, Any] = {"enabled": True}
    fn = getattr(rt, "dispatch_stats", None)
    if callable(fn):
        out.update(fn())
    else:   # thin client / worker runtime: no dispatcher-side stats
        out["enabled"] = False
    try:
        from . import metrics as metrics_mod  # noqa: PLC0415
        expo = metrics_mod.cluster_exposition(rt.cluster_metrics)
        direct = 0
        fallbacks = 0
        for line in expo.splitlines():
            if line.startswith("ray_tpu_direct_actor_calls_total"):
                direct += int(float(line.rsplit(" ", 1)[-1]))
            elif line.startswith("ray_tpu_direct_call_fallbacks_total"):
                fallbacks += int(float(line.rsplit(" ", 1)[-1]))
        out["direct_actor_calls"] = direct
        out["direct_call_fallbacks"] = fallbacks
    except Exception:
        pass
    return out


def persistence_summary() -> Dict[str, Any]:
    """Control-plane persistence health (core/persistence.py): driver
    incarnation, WAL length/bytes, last-snapshot age, and — after a
    resume — replayed-record count. `enabled` False when no
    RAY_TPU_STATE_DIR / init(state_dir=...) is configured."""
    rt = get_runtime()
    stats = None
    fn = getattr(rt, "persistence_stats", None)
    if callable(fn):
        stats = fn()
    if stats is None:
        return {"enabled": False,
                "driver_incarnation": getattr(rt, "incarnation", 0),
                "resumed": bool(getattr(rt, "resumed", False))}
    stats["enabled"] = True
    return stats


def _serve_controller(timeout: float = 0.2):
    import ray_tpu
    from ..serve.controller import CONTROLLER_NAME
    return ray_tpu.get_actor(CONTROLLER_NAME, timeout=timeout)


def serve_router_table() -> Dict[str, Any]:
    """Scale-out router view per deployment: RUNNING replica ids (the
    affinity hash-ring membership), registered prefixes with their
    current ring owner, and the recent sticky session bindings handles
    reported. {"running": False} when no serve controller exists."""
    import ray_tpu
    try:
        ctrl = _serve_controller()
    except Exception:  # noqa: BLE001  controller not running
        return {"running": False, "deployments": {}}
    return {"running": True,
            "deployments": ray_tpu.get(ctrl.get_router_table.remote(),
                                       timeout=5.0)}


def serve_autoscaler_status() -> Dict[str, Any]:
    """Serve autoscaler targets + recent decision log (scale_up /
    scale_down rows with reasons and placement annotations)."""
    import ray_tpu
    try:
        ctrl = _serve_controller()
    except Exception:  # noqa: BLE001
        return {"running": False, "deployments": {}, "decisions": []}
    out = ray_tpu.get(ctrl.get_autoscaler_status.remote(), timeout=5.0)
    out["running"] = True
    return out


def cluster_summary() -> Dict[str, Any]:
    rt = get_runtime()
    return {
        "job_id": rt.job_id,
        "namespace": rt.namespace,
        "driver_incarnation": getattr(rt, "incarnation", 0),
        "persistence": persistence_summary(),
        "nodes": len(rt.gcs.nodes),
        "workers": {s: sum(1 for w in list(rt.workers.values()) if w.state == s)
                    for s in ("starting", "idle", "busy", "actor", "dead")},
        "resources_total": rt.get_resources(),
        "resources_available": rt.available_resources(),
        "tasks": summarize_tasks()["total"],
        "actors": summarize_actors()["total"],
        "objects": summarize_objects()["total"],
    }
