"""Host-level collectives: barrier / broadcast / allreduce / allgather.

Reference counterpart: python/ray/util/collective (NCCL/GLOO process
groups). TPU-first split: ON-MESH tensor collectives are XLA's job
(psum/all_gather over ICI inside jit — see ray_tpu/parallel); this
module covers the CONTROL-PLANE case — host numpy arrays synchronized
across worker processes (e.g. data-loader coordination, eval metric
reduction) — via a named rendezvous actor, no NCCL.

NOT a training-step data path: every round funnels all ranks' payloads
through one actor (O(world) serialized hops + full copies of each
payload). Gradient/parameter tensors belong inside jit on the mesh;
allreduce() warns once past _PAYLOAD_WARN_BYTES to catch misuse.
"""
from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..exceptions import (CollectiveRankDiedError,
                          CollectiveStaleGenerationError, TaskError,
                          error_cause_is)


def _driver_path_cm():
    """Rendezvous verbs must ride the DRIVER dispatch path, not the
    direct worker->worker channel: the driver lends a worker's CPU
    while it parks in get() (and reclaims leased slots), which is what
    lets the remaining ranks of a gang schedule when the cluster is at
    capacity. A rank polling over fast direct calls never parks past
    the dwait grace, so its slot would stay held and the gang would
    deadlock until the round timed out (WorkerRuntime.force_driver_path)."""
    from ray_tpu.core import runtime as runtime_mod
    rt = runtime_mod.get_runtime()
    fn = getattr(rt, "force_driver_path", None)
    return fn() if fn is not None else contextlib.nullcontext()

_OPS = {
    "sum": lambda xs: np.sum(xs, axis=0),
    "mean": lambda xs: np.mean(xs, axis=0),
    "max": lambda xs: np.max(xs, axis=0),
    "min": lambda xs: np.min(xs, axis=0),
    "product": lambda xs: np.prod(xs, axis=0),
}


class _CollectiveActor:
    """Rendezvous state per (group, sequence-number round).

    Gang-aware: the elastic layer (train/elastic.py) reports member
    deaths via `mark_rank_dead`, which fails every parked poller of an
    incomplete round with a typed CollectiveRankDiedError instead of
    letting it spin out the round timeout; gang reforms advance a
    GENERATION number that fences contributions/polls from ranks of
    the superseded world (CollectiveStaleGenerationError)."""

    def __init__(self, world_size: int):
        self.world = world_size
        self._rounds: Dict[tuple, Dict[int, Any]] = {}
        self._results: Dict[tuple, Any] = {}
        self._fetched: Dict[tuple, set] = {}
        self._epochs: Dict[int, int] = {}
        self._dead: Dict[int, str] = {}        # rank -> death cause
        self._members: Dict[int, str] = {}     # rank -> actor id (info)
        self._generation = 0

    def _sync_generation(self, generation: Optional[int],
                         world_size: Optional[int] = None) -> None:
        """Fence OLD generations; ADOPT newer ones. A rank arriving
        with a newer generation than this actor knows means the gang
        reformed but the advance never landed here — e.g. the previous
        rendezvous actor died with the preempted host and this is its
        fresh (generation-0) replacement. Fencing that rank would lock
        the NEW world out forever; adopting (and clearing the dead
        world's rounds) is always safe because a newer generation is
        authoritative by construction."""
        if generation is None:
            return
        if generation > self._generation:
            self.advance_generation(generation, world_size)
        elif generation != self._generation:
            raise CollectiveStaleGenerationError(
                f"collective generation {generation} superseded by "
                f"{self._generation}: the gang reformed; this rank "
                "belongs to a dead world")

    def join(self, rank: int, world_size: int,
             actor_id: Optional[str] = None,
             generation: Optional[int] = None) -> int:
        """Per-rank init counter. Each CollectiveGroup handle gets its own
        epoch, namespacing its round keys so a re-created group for the
        same name never collides with cached results of the previous one.
        Symmetric usage (every rank re-inits together) keeps epochs equal."""
        self._sync_generation(generation, world_size)
        if world_size != self.world:
            raise ValueError(
                f"collective group has world_size={self.world}, "
                f"got {world_size}")
        if actor_id:
            self._members[rank] = actor_id
        self._dead.pop(rank, None)   # a re-joined rank is alive again
        e = self._epochs.get(rank, 0)
        self._epochs[rank] = e + 1
        return e

    def mark_rank_dead(self, rank: int, cause: str = "") -> None:
        """Driver-side supervision reports a member death. Every parked
        poller of a round still missing this rank fails fast on its next
        poll (sub-poll-interval, not the 60 s round timeout)."""
        self._dead[rank] = cause or "rank actor died"

    def advance_generation(self, generation: int,
                           world_size: Optional[int] = None) -> None:
        """The gang reformed (possibly resharded to a smaller world):
        fence the old world's rounds and accept the new membership."""
        if generation <= self._generation:
            return
        self._generation = generation
        if world_size is not None:
            self.world = world_size
        self._rounds.clear()
        self._results.clear()
        self._fetched.clear()
        self._dead.clear()
        self._members.clear()

    def members(self) -> Dict[str, Any]:
        return {"world": self.world, "generation": self._generation,
                "members": dict(self._members), "dead": dict(self._dead)}

    def contribute(self, key: tuple, rank: int, payload,
                   generation: Optional[int] = None) -> None:
        self._sync_generation(generation)
        self._rounds.setdefault(key, {})[rank] = payload

    def poll(self, key: tuple, op: Optional[str], rank: int = -1,
             generation: Optional[int] = None):
        """Returns (ready, result). Result computed once per round, then
        retained until every rank has fetched it (a result evicted before
        a slow rank polls would strand that rank in a timeout spin)."""
        self._sync_generation(generation)
        if key in self._results:
            result = self._results[key]
            self._mark_fetched(key, rank)
            return True, result
        room = self._rounds.get(key, {})
        if len(room) < self.world:
            missing_dead = [r for r in range(self.world)
                            if r not in room and r in self._dead]
            if missing_dead:
                r = missing_dead[0]
                raise CollectiveRankDiedError(
                    f"rank {r} died during collective round {key}: "
                    f"{self._dead[r]}", rank=r, round_key=key)
            return False, None
        ordered = [room[r] for r in sorted(room)]
        if op is None:                     # allgather
            result = ordered
        elif op == "broadcast":
            # payloads are (is_src, value): select by src flag, so
            # broadcasting None works and stray non-src values are ignored
            result = next(v for flag, v in ordered if flag)
        elif op == "barrier":
            result = True
        else:
            result = _OPS[op]([np.asarray(v) for v in ordered])
        self._results[key] = result
        self._rounds.pop(key, None)
        self._mark_fetched(key, rank)
        return True, result

    def _mark_fetched(self, key: tuple, rank: int) -> None:
        fetched = self._fetched.setdefault(key, set())
        fetched.add(rank)
        if len(fetched - {-1}) >= self.world:
            self._results.pop(key, None)
            self._fetched.pop(key, None)
            return
        # Size cap only as a fallback for abandoned rounds (a rank died
        # between contribute and poll): evict the oldest fully-computed
        # result, preferring ones nobody is still waiting on is
        # impossible to know, so cap generously.
        if len(self._results) > 1024:
            oldest = next(iter(self._results))
            self._results.pop(oldest)
            self._fetched.pop(oldest, None)


def _raise_typed(exc: BaseException):
    """Re-raise actor-boundary-wrapped gang failures as their typed
    forms (TaskError carries only the repr; error_cause_is matches by
    class name like the serve plane does)."""
    if isinstance(exc, (CollectiveRankDiedError,
                        CollectiveStaleGenerationError)):
        raise exc
    if error_cause_is(exc, "CollectiveRankDiedError"):
        raise CollectiveRankDiedError(
            getattr(exc, "cause_repr", "") or str(exc)) from exc
    if error_cause_is(exc, "CollectiveStaleGenerationError"):
        raise CollectiveStaleGenerationError(
            getattr(exc, "cause_repr", "") or str(exc)) from exc
    raise exc


class CollectiveGroup:
    """One rank's handle; ranks coordinate via the shared named actor.

    `generation` (optional) stamps every verb with the elastic gang
    generation: after a reform, verbs from ranks of the old world fail
    with CollectiveStaleGenerationError instead of corrupting the new
    world's rounds. A rank whose gang-mate dies mid-round gets
    CollectiveRankDiedError from the parked verb within the poll
    interval (the elastic supervisor calls mark_rank_dead)."""

    def __init__(self, group_name: str, world_size: int, rank: int,
                 *, generation: Optional[int] = None):
        import ray_tpu
        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        self.generation = generation
        self._seq: Dict[str, int] = {}
        name = f"rtpu_collective:{group_name}"
        try:
            self.actor = ray_tpu.get_actor(name, timeout=0.0)
        except ValueError:
            cls = ray_tpu.remote(_CollectiveActor).options(
                name=name, get_if_exists=True)
            cls.remote(world_size)
            # canonicalize through the name registry: if two ranks raced,
            # the loser's actor died on the name collision and lookup
            # returns the winner for everyone.
            self.actor = ray_tpu.get_actor(name)
        try:
            actor_id = ray_tpu.get_runtime_context().get_actor_id()
        except Exception:  # noqa: BLE001 — plain-task ranks have none
            actor_id = None
        with _driver_path_cm():
            try:
                self.epoch = ray_tpu.get(
                    self.actor.join.remote(rank, world_size, actor_id,
                                           generation))
            except TaskError as e:
                _raise_typed(e)

    def _round(self, kind: str, payload, op: Optional[str],
               timeout: float = 60.0):
        import ray_tpu
        seq = self._seq.get(kind, 0)
        self._seq[kind] = seq + 1
        key = (self.epoch, kind, seq)
        # one park spans the whole round (contribute + poll loop): a
        # stuck round surfaces as an aged "collective-round" record
        # carrying group/rank/world/seq — the straggler detector
        # compares these across ranks and names the missing ones
        from . import waits as waits_mod  # noqa: PLC0415
        wtok = waits_mod.park(
            "collective-round", f"{self.group_name}:{kind}:{seq}",
            group=self.group_name, rank=self.rank,
            world=self.world_size, round=kind, seq=seq,
            epoch=self.epoch, generation=self.generation)
        try:
            with _driver_path_cm():
                try:
                    ray_tpu.get(
                        self.actor.contribute.remote(
                            key, self.rank, payload, self.generation))
                    deadline = time.monotonic() + timeout
                    delay = 0.001
                    while True:
                        ready, result = ray_tpu.get(
                            self.actor.poll.remote(key, op, self.rank,
                                                   self.generation))
                        if ready:
                            return result
                        if time.monotonic() >= deadline:
                            raise TimeoutError(
                                f"collective {kind}#{seq} timed out "
                                f"({self.world_size} ranks expected)")
                        time.sleep(delay)
                        delay = min(delay * 2, 0.02)
                except TaskError as e:
                    _raise_typed(e)
        finally:
            waits_mod.unpark(wtok)

    def barrier(self, timeout: float = 60.0) -> None:
        self._round("barrier", None, "barrier", timeout)

    # beyond this, the single-actor rendezvous is the wrong tool — the
    # tensor belongs on the mesh where XLA reduces it over ICI
    _PAYLOAD_WARN_BYTES = 16 * 1024 * 1024
    _size_warned = False

    def allreduce(self, array, op: str = "sum", timeout: float = 60.0):
        array = np.asarray(array)
        if (array.nbytes > self._PAYLOAD_WARN_BYTES
                and not CollectiveGroup._size_warned):
            CollectiveGroup._size_warned = True
            import warnings
            warnings.warn(
                f"collective.allreduce of {array.nbytes >> 20} MiB "
                f"through the control-plane rendezvous actor (O(world) "
                f"serialized hops); large tensors belong in jitted "
                f"mesh collectives (ray_tpu.parallel)", stacklevel=2)
        return self._round("allreduce", array, op, timeout)

    def allgather(self, value, timeout: float = 60.0) -> List[Any]:
        return self._round("allgather", value, None, timeout)

    def broadcast(self, value=None, src: int = 0, timeout: float = 60.0):
        return self._round("broadcast", (self.rank == src, value),
                           "broadcast", timeout)


def init_collective_group(world_size: int, rank: int,
                          group_name: str = "default") -> CollectiveGroup:
    """Reference: ray.util.collective.init_collective_group."""
    return CollectiveGroup(group_name, world_size, rank)


def notify_rank_death(group_name: str, rank: int, cause: str = "",
                      timeout: float = 10.0) -> bool:
    """Tell a group's rendezvous actor that a member rank died, failing
    its parked pollers fast with CollectiveRankDiedError. Called by the
    gang supervisor (train/elastic.py) from its death watch; best-effort
    (False when the group doesn't exist or the actor itself is gone —
    e.g. it lived on the dead node, in which case pollers already get
    ActorDiedError)."""
    import ray_tpu
    try:
        actor = ray_tpu.get_actor(f"rtpu_collective:{group_name}",
                                  timeout=0.0)
        ray_tpu.get(actor.mark_rank_dead.remote(rank, cause),
                    timeout=timeout)
        return True
    except Exception:  # noqa: BLE001 — supervision must not fail on this
        return False


def advance_group_generation(group_name: str, generation: int,
                             world_size: Optional[int] = None,
                             timeout: float = 10.0) -> bool:
    """Fence a group's previous world after a gang reform: rounds from
    generations < `generation` fail with CollectiveStaleGenerationError
    and the (possibly resharded) world size takes effect. Best-effort,
    same contract as notify_rank_death."""
    import ray_tpu
    try:
        actor = ray_tpu.get_actor(f"rtpu_collective:{group_name}",
                                  timeout=0.0)
        ray_tpu.get(actor.advance_generation.remote(generation, world_size),
                    timeout=timeout)
        return True
    except Exception:  # noqa: BLE001
        return False


def destroy_collective_group(group_name: str = "default") -> None:
    """Kill the rendezvous actor (reference:
    ray.util.collective.destroy_collective_group)."""
    import ray_tpu
    try:
        ray_tpu.kill(ray_tpu.get_actor(f"rtpu_collective:{group_name}",
                                       timeout=0.0))
    except ValueError:
        pass
