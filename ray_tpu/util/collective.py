"""Host-level collectives: barrier / broadcast / allreduce / allgather.

Reference counterpart: python/ray/util/collective (NCCL/GLOO process
groups). TPU-first split: ON-MESH tensor collectives are XLA's job
(psum/all_gather over ICI inside jit — see ray_tpu/parallel); this
module covers the CONTROL-PLANE case — host numpy arrays synchronized
across worker processes (e.g. data-loader coordination, eval metric
reduction) — via a named rendezvous actor, no NCCL.

NOT a training-step data path: every round funnels all ranks' payloads
through one actor (O(world) serialized hops + full copies of each
payload). Gradient/parameter tensors belong inside jit on the mesh;
allreduce() warns once past _PAYLOAD_WARN_BYTES to catch misuse.
"""
from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, List, Optional

import numpy as np


def _driver_path_cm():
    """Rendezvous verbs must ride the DRIVER dispatch path, not the
    direct worker->worker channel: the driver lends a worker's CPU
    while it parks in get() (and reclaims leased slots), which is what
    lets the remaining ranks of a gang schedule when the cluster is at
    capacity. A rank polling over fast direct calls never parks past
    the dwait grace, so its slot would stay held and the gang would
    deadlock until the round timed out (WorkerRuntime.force_driver_path)."""
    from ray_tpu.core import runtime as runtime_mod
    rt = runtime_mod.get_runtime()
    fn = getattr(rt, "force_driver_path", None)
    return fn() if fn is not None else contextlib.nullcontext()

_OPS = {
    "sum": lambda xs: np.sum(xs, axis=0),
    "mean": lambda xs: np.mean(xs, axis=0),
    "max": lambda xs: np.max(xs, axis=0),
    "min": lambda xs: np.min(xs, axis=0),
    "product": lambda xs: np.prod(xs, axis=0),
}


class _CollectiveActor:
    """Rendezvous state per (group, sequence-number round)."""

    def __init__(self, world_size: int):
        self.world = world_size
        self._rounds: Dict[tuple, Dict[int, Any]] = {}
        self._results: Dict[tuple, Any] = {}
        self._fetched: Dict[tuple, set] = {}
        self._epochs: Dict[int, int] = {}

    def join(self, rank: int, world_size: int) -> int:
        """Per-rank init counter. Each CollectiveGroup handle gets its own
        epoch, namespacing its round keys so a re-created group for the
        same name never collides with cached results of the previous one.
        Symmetric usage (every rank re-inits together) keeps epochs equal."""
        if world_size != self.world:
            raise ValueError(
                f"collective group has world_size={self.world}, "
                f"got {world_size}")
        e = self._epochs.get(rank, 0)
        self._epochs[rank] = e + 1
        return e

    def contribute(self, key: tuple, rank: int, payload) -> None:
        self._rounds.setdefault(key, {})[rank] = payload

    def poll(self, key: tuple, op: Optional[str], rank: int = -1):
        """Returns (ready, result). Result computed once per round, then
        retained until every rank has fetched it (a result evicted before
        a slow rank polls would strand that rank in a timeout spin)."""
        if key in self._results:
            result = self._results[key]
            self._mark_fetched(key, rank)
            return True, result
        room = self._rounds.get(key, {})
        if len(room) < self.world:
            return False, None
        ordered = [room[r] for r in sorted(room)]
        if op is None:                     # allgather
            result = ordered
        elif op == "broadcast":
            # payloads are (is_src, value): select by src flag, so
            # broadcasting None works and stray non-src values are ignored
            result = next(v for flag, v in ordered if flag)
        elif op == "barrier":
            result = True
        else:
            result = _OPS[op]([np.asarray(v) for v in ordered])
        self._results[key] = result
        self._rounds.pop(key, None)
        self._mark_fetched(key, rank)
        return True, result

    def _mark_fetched(self, key: tuple, rank: int) -> None:
        fetched = self._fetched.setdefault(key, set())
        fetched.add(rank)
        if len(fetched - {-1}) >= self.world:
            self._results.pop(key, None)
            self._fetched.pop(key, None)
            return
        # Size cap only as a fallback for abandoned rounds (a rank died
        # between contribute and poll): evict the oldest fully-computed
        # result, preferring ones nobody is still waiting on is
        # impossible to know, so cap generously.
        if len(self._results) > 1024:
            oldest = next(iter(self._results))
            self._results.pop(oldest)
            self._fetched.pop(oldest, None)


class CollectiveGroup:
    """One rank's handle; ranks coordinate via the shared named actor."""

    def __init__(self, group_name: str, world_size: int, rank: int):
        import ray_tpu
        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        self._seq: Dict[str, int] = {}
        name = f"rtpu_collective:{group_name}"
        try:
            self.actor = ray_tpu.get_actor(name, timeout=0.0)
        except ValueError:
            cls = ray_tpu.remote(_CollectiveActor).options(
                name=name, get_if_exists=True)
            cls.remote(world_size)
            # canonicalize through the name registry: if two ranks raced,
            # the loser's actor died on the name collision and lookup
            # returns the winner for everyone.
            self.actor = ray_tpu.get_actor(name)
        with _driver_path_cm():
            self.epoch = ray_tpu.get(
                self.actor.join.remote(rank, world_size))

    def _round(self, kind: str, payload, op: Optional[str],
               timeout: float = 60.0):
        import ray_tpu
        seq = self._seq.get(kind, 0)
        self._seq[kind] = seq + 1
        key = (self.epoch, kind, seq)
        with _driver_path_cm():
            ray_tpu.get(
                self.actor.contribute.remote(key, self.rank, payload))
            deadline = time.monotonic() + timeout
            delay = 0.001
            while True:
                ready, result = ray_tpu.get(
                    self.actor.poll.remote(key, op, self.rank))
                if ready:
                    return result
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"collective {kind}#{seq} timed out "
                        f"({self.world_size} ranks expected)")
                time.sleep(delay)
                delay = min(delay * 2, 0.02)

    def barrier(self, timeout: float = 60.0) -> None:
        self._round("barrier", None, "barrier", timeout)

    # beyond this, the single-actor rendezvous is the wrong tool — the
    # tensor belongs on the mesh where XLA reduces it over ICI
    _PAYLOAD_WARN_BYTES = 16 * 1024 * 1024
    _size_warned = False

    def allreduce(self, array, op: str = "sum", timeout: float = 60.0):
        array = np.asarray(array)
        if (array.nbytes > self._PAYLOAD_WARN_BYTES
                and not CollectiveGroup._size_warned):
            CollectiveGroup._size_warned = True
            import warnings
            warnings.warn(
                f"collective.allreduce of {array.nbytes >> 20} MiB "
                f"through the control-plane rendezvous actor (O(world) "
                f"serialized hops); large tensors belong in jitted "
                f"mesh collectives (ray_tpu.parallel)", stacklevel=2)
        return self._round("allreduce", array, op, timeout)

    def allgather(self, value, timeout: float = 60.0) -> List[Any]:
        return self._round("allgather", value, None, timeout)

    def broadcast(self, value=None, src: int = 0, timeout: float = 60.0):
        return self._round("broadcast", (self.rank == src, value),
                           "broadcast", timeout)


def init_collective_group(world_size: int, rank: int,
                          group_name: str = "default") -> CollectiveGroup:
    """Reference: ray.util.collective.init_collective_group."""
    return CollectiveGroup(group_name, world_size, rank)


def destroy_collective_group(group_name: str = "default") -> None:
    """Kill the rendezvous actor (reference:
    ray.util.collective.destroy_collective_group)."""
    import ray_tpu
    try:
        ray_tpu.kill(ray_tpu.get_actor(f"rtpu_collective:{group_name}",
                                       timeout=0.0))
    except ValueError:
        pass
