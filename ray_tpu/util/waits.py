"""Wait-state plane: every blocking edge registers a WaitRecord.

The event plane (util/events.py) answers "what happened"; this plane
answers "why is nothing happening right now". Every park site in the
package — `get()`/`wait()` reply settles, direct-call waits,
collective round polls, compiled-DAG ack-window and read-barrier
stalls, node-agent lease-queue heads, data-service grant polls —
registers a structured record in a bounded per-process `WaitTable`:

    token = waits.park("object", oid, target_actor=aid)
    try:
        ... block ...
    finally:
        waits.unpark(token)

Cost discipline (the plane is always on): park is one dict build and
one dict store under a lock, unpark one pop — no syscalls, no
telemetry frames. Shipping rides the existing 1s telemetry heartbeat
(report channel `sys.waits`, node msg `"waits"`) and ships ONLY waits
older than `SHIP_MIN_AGE_S`, and only when that aged set changed
since the last flush: a healthy pipeline whose waits are all
micro-waits ships zero frames, so steady-state control traffic is
unchanged (counter-asserted in tests/test_waits.py). Each shipped
payload is a full snapshot per source — idempotent, so a dropped
frame self-heals on the next change.

The driver folds every source's snapshot (plus its own local table)
into `ClusterWaitStore`, which `observability/waitgraph.py` walks at
`RAY_TPU_HANG_PROBE_S` cadence for cycles, stale waits, and
stragglers. `RAY_TPU_WAITS=0` is the kill switch (park becomes a
no-op returning 0).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from . import knobs

# Resource kinds a waiter can block on (the record's `kind` field).
RESOURCE_KINDS = ("object", "actor-call", "collective-round",
                  "dag-channel", "lease-slot", "data-grant",
                  "serve-stream", "other")

# Waits younger than this never ship: the telemetry flush skips them,
# so a healthy pipeline's micro-waits cost zero frames. Anything the
# hang watchdog could care about is orders of magnitude older.
SHIP_MIN_AGE_S = 1.0

# Hot-path park sites (compiled-DAG channel hops, slot settles) defer
# the park until the caller has already blocked this long: steady-state
# pipeline waits are microseconds, so the grace makes them literally
# free, while anything the watchdog could flag (>= SHIP_MIN_AGE_S) is
# recorded with at most this much start-time skew.
PARK_GRACE_S = 0.05

_enabled = knobs.get_bool("RAY_TPU_WAITS")


def set_enabled(on: bool) -> None:
    """Flip the whole plane (kill switch / bench A/B)."""
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


def current_task_id() -> Optional[str]:
    """The task id attributed to the calling thread (the same
    thread→task map the sampling profiler uses, stamped by
    core/logging.mark_current_task)."""
    from ..observability import sampling_profiler  # noqa: PLC0415
    return sampling_profiler._marks.get(threading.get_ident())


class WaitTable:
    """Bounded per-process table of in-progress waits, keyed by an
    opaque int token. Overflow past maxlen drops the record (park
    still returns a token; unpark of a dropped token is a no-op) and
    counts it, so saturation is visible, never silent."""

    def __init__(self, maxlen: int = 4096):
        self.maxlen = maxlen
        self._recs: Dict[int, Dict[str, Any]] = {}
        self._lock = threading.Lock()
        self._seq = 0
        self.dropped = 0
        # per-resource-kind completed-wait seconds, flushed to the
        # `ray_tpu_wait_seconds` counter at collect cadence
        self._secs: Dict[str, float] = {}
        # aged-set fingerprint from the last ship: payloads go out
        # only when this changes. Starts EMPTY (not None) so a fresh
        # process with no aged waits ships nothing at all.
        self._last_shipped: frozenset = frozenset()

    def park(self, kind: str, resource_id: str = "",
             waiter: Optional[str] = None,
             **ctx: Any) -> int:
        """Register a wait; returns the token for unpark. `waiter`
        overrides thread-mark task attribution (driver-side callers,
        synthesized queue records)."""
        if not _enabled:
            return 0
        if waiter is None:
            waiter = current_task_id()
        rec: Dict[str, Any] = {"kind": kind, "rid": resource_id,
                               "ts": time.time()}
        if waiter:
            rec["task_id"] = waiter
        if ctx:
            rec["ctx"] = {k: v for k, v in ctx.items() if v is not None}
        with self._lock:
            self._seq += 1
            tok = self._seq
            if len(self._recs) >= self.maxlen:
                self.dropped += 1
                return tok
            rec["tok"] = tok
            self._recs[tok] = rec
        return tok

    def unpark(self, token: int) -> None:
        if not token:
            return
        with self._lock:
            rec = self._recs.pop(token, None)
            if rec is not None:
                kind = rec["kind"]
                self._secs[kind] = self._secs.get(kind, 0.0) + \
                    (time.time() - rec["ts"])

    def touch(self, token: int, **ctx: Any) -> None:
        """Update a parked record's context in place (e.g. a
        collective poller advancing through rounds keeps one park
        across rounds but refreshes the round key)."""
        if not token:
            return
        with self._lock:
            rec = self._recs.get(token)
            if rec is not None:
                rec.setdefault("ctx", {}).update(ctx)
                rec["v"] = rec.get("v", 0) + 1

    def snapshot(self) -> List[Dict[str, Any]]:
        """Copies of every in-progress wait (driver-local reads)."""
        with self._lock:
            return [dict(r) for r in self._recs.values()]

    def replace_synth(self, prefix: str,
                      recs: List[Tuple[str, str, float, Dict]]) -> None:
        """Replace the synthesized records under `prefix` (node-agent
        lease queues are data structures, not parked threads: the
        agent re-synthesizes their wait records each metrics tick as
        (kind, rid, start_ts, ctx) tuples)."""
        if not _enabled:
            return
        with self._lock:
            for tok in [t for t in self._recs
                        if isinstance(t, str) and t.startswith(prefix)]:
                del self._recs[tok]
            for i, (kind, rid, ts, ctx) in enumerate(recs):
                tok = f"{prefix}{kind}:{rid}:{i}"
                rec = {"kind": kind, "rid": rid, "ts": ts, "tok": tok}
                if ctx:
                    rec["ctx"] = ctx
                self._recs[tok] = rec

    def collect(self, min_age: float = SHIP_MIN_AGE_S
                ) -> Optional[Dict[str, Any]]:
        """The telemetry-flush delta: a full snapshot of waits older
        than `min_age`, or None when that set is unchanged since the
        last ship (including the steady state of "no aged waits", so
        healthy processes ship nothing). Also flushes completed-wait
        seconds into the metrics plane, which piggybacks the
        sys.metrics channel it already rides."""
        now = time.time()
        with self._lock:
            secs, self._secs = self._secs, {}
            aged = [r for r in self._recs.values()
                    if now - r["ts"] >= min_age]
            fp = frozenset((r["tok"], r.get("v", 0)) for r in aged)
            changed = fp != self._last_shipped
            if changed:
                self._last_shipped = fp
                out = [dict(r) for r in aged]
            n_recs = len(self._recs)
        if secs:
            try:
                from . import metrics_catalog as mcat  # noqa: PLC0415
                for kind, s in secs.items():
                    mcat.get("ray_tpu_wait_seconds").inc(
                        s, tags={"kind": kind})
            except Exception:  # noqa: BLE001
                pass
        if n_recs:
            try:
                from . import metrics_catalog as mcat  # noqa: PLC0415
                mcat.get("ray_tpu_wait_records").set(float(n_recs))
            except Exception:  # noqa: BLE001
                pass
        if not changed:
            return None
        return {"records": out, "dropped": self.dropped}

    def __len__(self) -> int:
        with self._lock:
            return len(self._recs)


# The process-wide table every park site writes to.
_table = WaitTable()


def park(kind: str, resource_id: str = "",
         waiter: Optional[str] = None, **ctx: Any) -> int:
    return _table.park(kind, resource_id, waiter=waiter, **ctx)


def unpark(token: int) -> None:
    _table.unpark(token)


def touch(token: int, **ctx: Any) -> None:
    _table.touch(token, **ctx)


def collect(min_age: float = SHIP_MIN_AGE_S) -> Optional[Dict[str, Any]]:
    return _table.collect(min_age)


def snapshot() -> List[Dict[str, Any]]:
    return _table.snapshot()


def table() -> WaitTable:
    return _table


class ClusterWaitStore:
    """Driver-side fold of per-source wait snapshots. Each source's
    payload REPLACES its previous one (full-snapshot semantics: a
    dropped frame self-heals on the next change; an unparked wait
    disappears on the next ship). Sources are dropped when their
    worker/node dies so ghost waits cannot poison the graph."""

    def __init__(self):
        self._by_source: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()

    def ingest(self, src: str, source_tags: Optional[Dict[str, str]],
               payload: Optional[Dict[str, Any]]) -> None:
        """`src` is the replacement key (worker id, or "agent:<nid>"
        for node agents — "node-agent" alone would collide across
        nodes); `source_tags` stamp each record for display."""
        if not isinstance(payload, dict):
            return
        tags = source_tags or {}
        recs = payload.get("records") or []
        for r in recs:
            if isinstance(r, dict):
                for k, v in tags.items():
                    if k not in r:
                        r[k] = v
        with self._lock:
            if recs:
                self._by_source[src] = {"records": recs,
                                        "recv_ts": time.time(),
                                        "dropped":
                                            payload.get("dropped", 0)}
            else:
                self._by_source.pop(src, None)

    def drop_source(self, src: str) -> None:
        with self._lock:
            self._by_source.pop(src, None)

    def snapshot(self) -> List[Dict[str, Any]]:
        """Every known remote wait record (shipped copies — safe for
        callers to annotate)."""
        with self._lock:
            out: List[Dict[str, Any]] = []
            for ent in self._by_source.values():
                out.extend(dict(r) for r in ent["records"])
            return out

    def sources(self) -> Dict[str, int]:
        with self._lock:
            return {s: len(e["records"])
                    for s, e in self._by_source.items()}
