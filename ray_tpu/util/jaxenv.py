"""Deterministic JAX platform selection under the container's TPU plugin.

The image's sitecustomize.py registers a TPU PJRT plugin at interpreter
startup and force-sets jax's `jax_platforms` config, so environment
variables alone don't decide the platform. These helpers win regardless of
registration state; call them before the first jax.devices()/jit.
"""
from __future__ import annotations

import os


def force_cpu(n_virtual_devices: int | None = None) -> None:
    """Pin this process to the CPU backend, optionally with N virtual
    devices (for testing multi-chip sharding without chips).

    Safe to call even after jax has been imported (or initialized on a
    different platform): `jax_num_cpu_devices` takes effect at client
    creation, so clearing already-created backends is sufficient — unlike
    XLA_FLAGS, which absl parses only once per process (we still set it
    for child processes that inherit the environment).
    """
    if n_virtual_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count="
                        f"{n_virtual_devices}").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    # Clear any live backends FIRST: jax refuses jax_num_cpu_devices
    # updates while a client exists, and config changes only apply at the
    # next client creation anyway.
    from jax._src import xla_bridge
    if xla_bridge.backends_are_initialized():
        from jax.extend.backend import clear_backends
        clear_backends()
    jax.config.update("jax_platforms", "cpu")
    if n_virtual_devices is not None:
        try:
            jax.config.update("jax_num_cpu_devices", n_virtual_devices)
        except Exception:
            pass  # older jax: XLA_FLAGS above covers it


def subprocess_env_cpu(env: dict) -> dict:
    """Environment for a child process that must never touch the TPU:
    blank the plugin trigger so sitecustomize skips registration (faster
    startup, no tunnel contention)."""
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    return env
