"""Distributed FIFO queue backed by an actor.

Reference counterpart: python/ray/util/queue.py (Queue over an
_QueueActor). Blocking semantics are client-side polls against a
non-blocking actor so one slow consumer never wedges the actor.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Any, List, Optional

from ..exceptions import GetTimeoutError


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int = 0):
        self.maxsize = maxsize
        self._q: deque = deque()

    def qsize(self) -> int:
        return len(self._q)

    def empty(self) -> bool:
        return not self._q

    def full(self) -> bool:
        return self.maxsize > 0 and len(self._q) >= self.maxsize

    def put_nowait(self, item) -> bool:
        if self.full():
            return False
        self._q.append(item)
        return True

    def put_nowait_batch(self, items: List[Any]) -> bool:
        if self.maxsize > 0 and len(self._q) + len(items) > self.maxsize:
            return False
        self._q.extend(items)
        return True

    def get_nowait(self):
        if not self._q:
            return False, None
        return True, self._q.popleft()

    def get_nowait_batch(self, n: int):
        n = min(n, len(self._q))
        return [self._q.popleft() for _ in range(n)]


class Queue:
    def __init__(self, maxsize: int = 0, *, actor_options=None):
        import ray_tpu
        opts = actor_options or {}
        cls = ray_tpu.remote(_QueueActor)
        if opts:
            cls = cls.options(**opts)
        self.actor = cls.remote(maxsize)
        self.maxsize = maxsize

    def __getstate__(self):
        return {"actor": self.actor, "maxsize": self.maxsize}

    def __setstate__(self, state):
        self.__dict__.update(state)

    def qsize(self) -> int:
        import ray_tpu
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        import ray_tpu
        return ray_tpu.get(self.actor.empty.remote())

    def full(self) -> bool:
        import ray_tpu
        return ray_tpu.get(self.actor.full.remote())

    def put(self, item, block: bool = True,
            timeout: Optional[float] = None) -> None:
        import ray_tpu
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = 0.001
        while True:
            if ray_tpu.get(self.actor.put_nowait.remote(item)):
                return
            if not block:
                raise Full("queue full")
            if deadline is not None and time.monotonic() >= deadline:
                raise Full(f"put timed out after {timeout}s")
            time.sleep(delay)
            delay = min(delay * 2, 0.05)

    def put_nowait(self, item) -> None:
        self.put(item, block=False)

    def put_nowait_batch(self, items: List[Any]) -> None:
        import ray_tpu
        if not ray_tpu.get(self.actor.put_nowait_batch.remote(list(items))):
            raise Full("batch does not fit")

    def get(self, block: bool = True, timeout: Optional[float] = None):
        import ray_tpu
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = 0.001
        while True:
            ok, item = ray_tpu.get(self.actor.get_nowait.remote())
            if ok:
                return item
            if not block:
                raise Empty("queue empty")
            if deadline is not None and time.monotonic() >= deadline:
                raise Empty(f"get timed out after {timeout}s")
            time.sleep(delay)
            delay = min(delay * 2, 0.05)

    def get_nowait(self):
        return self.get(block=False)

    def get_nowait_batch(self, n: int) -> List[Any]:
        import ray_tpu
        return ray_tpu.get(self.actor.get_nowait_batch.remote(n))

    def shutdown(self) -> None:
        import ray_tpu
        ray_tpu.kill(self.actor)
