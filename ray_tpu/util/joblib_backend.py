"""joblib parallel backend running batches as ray_tpu tasks.

Reference parity: ray.util.joblib (python/ray/util/joblib/__init__.py —
``register_ray()`` installs a joblib backend so scikit-learn-style
``Parallel(n_jobs=...)`` code fans out over the cluster unchanged).
Here ``register_ray_tpu()`` registers the same idea over the ray_tpu
runtime: each joblib batch (a ``BatchedCalls`` callable) becomes one
remote task; results stream back through ObjectRefs.

Usage::

    import joblib
    from ray_tpu.util.joblib_backend import register_ray_tpu

    register_ray_tpu()
    with joblib.parallel_backend("ray_tpu"):
        out = joblib.Parallel(n_jobs=8)(
            joblib.delayed(f)(x) for x in inputs)
"""
from __future__ import annotations

from typing import Any, Callable, Optional

__all__ = ["register_ray_tpu", "RayTpuJoblibBackend"]


def _make_backend_class():
    # deferred so importing ray_tpu.util never hard-requires joblib
    from joblib.parallel import AutoBatchingMixin, ParallelBackendBase

    import ray_tpu
    from .. import api

    @ray_tpu.remote
    def _run_batch(batch: Callable[[], Any]):
        return batch()

    class _RefFuture:
        """joblib future shim over an ObjectRef: supports get(timeout)."""

        def __init__(self, ref, callback: Optional[Callable]):
            self._ref = ref
            if callback is not None:
                import threading

                def waiter():
                    # wait (no value transfer: results fetch once, in
                    # retrieve_result); the callback paces joblib's
                    # dispatcher and must fire on failure too
                    try:
                        ray_tpu.wait([ref], num_returns=1, timeout=None)
                    finally:
                        callback(None)
                threading.Thread(target=waiter, daemon=True).start()

        def get(self, timeout: Optional[float] = None):
            return ray_tpu.get(self._ref, timeout=timeout)

    class RayTpuJoblibBackend(AutoBatchingMixin, ParallelBackendBase):
        supports_timeout = True
        supports_retrieve_callback = False

        def configure(self, n_jobs: int = 1, parallel=None, **_kw) -> int:
            if not api.is_initialized():
                api.init()
            self.parallel = parallel
            return self.effective_n_jobs(n_jobs)

        def effective_n_jobs(self, n_jobs: int) -> int:
            # joblib contract (cf. LokyBackend): None -> 1, 0 -> error,
            # -1 -> everything (here: the CLUSTER's CPUs, not local cores)
            if n_jobs is None or n_jobs == 1:
                return 1
            if n_jobs == 0:
                raise ValueError("n_jobs == 0 in Parallel has no meaning")
            total = api._ensure_init().get_resources().get("CPU", 1.0)
            if n_jobs < 0:
                return max(1, int(total))
            return max(1, min(int(n_jobs), int(total)))

        def submit(self, func: Callable[[], Any],
                   callback: Optional[Callable] = None) -> _RefFuture:
            return _RefFuture(_run_batch.remote(func), callback)

        # joblib < 1.4 calls apply_async; same protocol
        def apply_async(self, func: Callable[[], Any],
                        callback: Optional[Callable] = None) -> _RefFuture:
            return self.submit(func, callback)

        def abort_everything(self, ensure_ready: bool = True) -> None:
            # Tasks already dispatched run to completion (ray semantics:
            # joblib abort doesn't force-kill remote workers); nothing to
            # reclaim — the runtime owns the worker pool.
            if ensure_ready:
                self.configure(n_jobs=self.parallel.n_jobs,
                               parallel=self.parallel)

    return RayTpuJoblibBackend


_backend_cls = None


def _get_backend_class():
    global _backend_cls
    if _backend_cls is None:
        _backend_cls = _make_backend_class()
    return _backend_cls


def register_ray_tpu() -> None:
    """Register the 'ray_tpu' joblib backend (idempotent: the same class
    object is reused across calls)."""
    from joblib.parallel import BACKENDS, register_parallel_backend

    cls = _get_backend_class()
    if BACKENDS.get("ray_tpu") is not cls:
        register_parallel_backend("ray_tpu", cls)


# Resolved lazily for `from ray_tpu.util.joblib_backend import
# RayTpuJoblibBackend` introspection without forcing registration;
# identity is stable (memoized) and matches the registered class.
def __getattr__(name: str):
    if name == "RayTpuJoblibBackend":
        return _get_backend_class()
    raise AttributeError(name)
