"""Reference-parity import location for scheduling strategies
(python/ray/util/scheduling_strategies.py)."""
from ..core.scheduling import (NodeAffinitySchedulingStrategy,
                               PlacementGroupSchedulingStrategy)

__all__ = ["NodeAffinitySchedulingStrategy",
           "PlacementGroupSchedulingStrategy"]
