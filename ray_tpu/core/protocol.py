"""Length-prefixed pickle message framing over sockets.

Reference parity: src/ray/rpc (gRPC services between core_worker and raylet).
A single-host, single-controller runtime doesn't need gRPC; a Unix-domain
socket with framed pickles gives lower latency and zero deps. The Connection
class is transport-agnostic (works over TCP for multi-host drivers).

Large values never travel through these messages — only ids and small
metadata; payloads go through the shared-memory object store.
"""
from __future__ import annotations

import pickle
import socket
import struct
import threading
import traceback
from typing import Any, Optional

import cloudpickle

_HDR = struct.Struct("<I")
MAX_MSG = 1 << 30

# Marker message returned when a frame arrives intact but fails to
# deserialize (e.g. a by-reference pickle whose module only exists on the
# sender). Receivers log and continue instead of killing the read loop.
RECV_ERROR = "__recv_error__"


class ConnectionClosed(Exception):
    pass


class Connection:
    """Thread-safe framed-message duplex connection."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self._send_lock = threading.Lock()
        self._recv_lock = threading.Lock()
        try:
            self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass  # unix sockets

    def send(self, msg: Any) -> None:
        # cloudpickle, not pickle: messages carry user callables (actor task
        # args, data-stage fns) that plain pickle serializes by reference —
        # unpicklable in a worker that can't import the sender's __main__.
        data = cloudpickle.dumps(msg, protocol=5)
        with self._send_lock:
            try:
                self.sock.sendall(_HDR.pack(len(data)) + data)
            except (BrokenPipeError, ConnectionResetError, OSError) as e:
                raise ConnectionClosed(str(e)) from e

    def _recv_exact(self, n: int) -> bytes:
        return read_exact(self.sock, n)

    def recv(self) -> Any:
        with self._recv_lock:
            hdr = self._recv_exact(_HDR.size)
            (length,) = _HDR.unpack(hdr)
            if length > MAX_MSG:
                raise ConnectionClosed(f"oversized frame: {length}")
            data = self._recv_exact(length)
        try:
            return pickle.loads(data)
        except BaseException:  # noqa: BLE001 — framing is intact; keep going
            return (RECV_ERROR, traceback.format_exc())

    def close(self) -> None:
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()

    def fileno(self) -> int:
        return self.sock.fileno()


# ---------------------------------------------------------------------------
# Raw byte-frame helpers — the data-plane framing used by the peer-to-peer
# object transfer protocol (core/object_transfer.py). Unlike Connection
# messages these frames carry opaque bytes (no pickling on the payload
# path), so a multi-MB chunk costs one memcpy, not a serialize.

def read_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        try:
            chunk = sock.recv(min(n - got, 1 << 20))
        except (ConnectionResetError, OSError) as e:
            raise ConnectionClosed(str(e)) from e
        if not chunk:
            raise ConnectionClosed("peer closed")
        chunks.append(chunk)
        got += len(chunk)
    return chunks[0] if len(chunks) == 1 else b"".join(chunks)


def write_frame(sock: socket.socket, payload) -> None:
    """Length-prefixed raw frame; payload is bytes or any buffer."""
    try:
        sock.sendall(_HDR.pack(len(payload)))
        sock.sendall(payload)
    except (BrokenPipeError, ConnectionResetError, OSError) as e:
        raise ConnectionClosed(str(e)) from e


def read_frame(sock: socket.socket, max_len: int = MAX_MSG) -> bytes:
    (length,) = _HDR.unpack(read_exact(sock, _HDR.size))
    if length > max_len:
        raise ConnectionClosed(f"oversized frame: {length}")
    return read_exact(sock, length)


def write_obj(sock: socket.socket, obj: Any) -> None:
    """Small pickled control frame (transfer-plane handshakes only)."""
    write_frame(sock, cloudpickle.dumps(obj, protocol=5))


def read_obj(sock: socket.socket, max_len: int = 1 << 20) -> Any:
    return pickle.loads(read_frame(sock, max_len))


def unix_listener(path: str) -> socket.socket:
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.bind(path)
    s.listen(128)
    return s


def unix_connect(path: str, timeout: Optional[float] = 10.0) -> Connection:
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.settimeout(timeout)
    s.connect(path)
    s.settimeout(None)
    return Connection(s)


def tcp_listener(host: str = "127.0.0.1", port: int = 0) -> socket.socket:
    """Multi-host transport: remote node agents and their workers speak
    the same framed protocol over TCP (reference parity: the gRPC
    services of src/ray/gcs/gcs_server/gcs_node_manager.cc — here one
    listener serves workers AND node agents, demuxed by the first
    message)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind((host, port))
    s.listen(128)
    return s


def tcp_connect(host: str, port: int,
                timeout: Optional[float] = 10.0) -> Connection:
    s = socket.create_connection((host, port), timeout=timeout)
    if s.getsockname() == s.getpeername():
        # TCP self-connect: connecting to a loopback port in the
        # ephemeral range while nothing listens can "succeed" against
        # OURSELVES (the kernel picked source port == dest port). A
        # node agent retrying a dead driver's address would then talk
        # to its own echo and believe it rejoined — refuse, so the
        # caller's retry loop keeps waiting for the real listener
        # (observed during driver crash-restart reattach tests).
        s.close()
        raise ConnectionRefusedError(
            f"self-connect to {host}:{port} (no listener yet)")
    s.settimeout(None)
    return Connection(s)


def connect_address(address: str,
                    timeout: Optional[float] = 10.0) -> Connection:
    """Connect to "tcp://host:port" or a unix-socket path (optionally
    "unix://path")."""
    if address.startswith("tcp://"):
        host, _, port = address[len("tcp://"):].rpartition(":")
        return tcp_connect(host, int(port), timeout=timeout)
    if address.startswith("unix://"):
        address = address[len("unix://"):]
    return unix_connect(address, timeout=timeout)
