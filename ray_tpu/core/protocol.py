"""Length-prefixed pickle message framing over sockets.

Reference parity: src/ray/rpc (gRPC services between core_worker and raylet).
A single-host, single-controller runtime doesn't need gRPC; a Unix-domain
socket with framed pickles gives lower latency and zero deps. The Connection
class is transport-agnostic (works over TCP for multi-host drivers).

Large values never travel through these messages — only ids and small
metadata; payloads go through the shared-memory object store.
"""
from __future__ import annotations

import pickle
import socket
import struct
import threading
import traceback
from typing import Any, Optional

import cloudpickle

from ..util import knobs

try:
    import msgpack
except ImportError:  # pragma: no cover - image always ships msgpack
    msgpack = None

_HDR = struct.Struct("<I")
MAX_MSG = 1 << 30

# Marker message returned when a frame arrives intact but fails to
# deserialize (e.g. a by-reference pickle whose module only exists on the
# sender). Receivers log and continue instead of killing the read loop.
RECV_ERROR = "__recv_error__"


class ConnectionClosed(Exception):
    pass


class WireVersionError(Exception):
    """A frame carried the binary-wire marker family but a version this
    build does not speak. Surfaced as a RECV_ERROR drop (the connection
    survives), never silently misparsed as pickle."""


# ---------------------------------------------------------------------------
# Compact binary wire codec (v1).
#
# Control-plane frames used to be one cloudpickle per message. The hot
# kinds (task submit/finish batches, seals, heartbeats) are
# framework-pure — strings, ints, locations — so they now ride a
# versioned msgpack body: first body byte 0xB0|version discriminates
# from pickle (every pickle protocol>=2 stream starts with 0x80), the
# rest is msgpack with three extension types. User payloads (task args,
# exceptions) stay pickled, but INSIDE the envelope — mirroring the
# PR-6 WAL split that made framework-pure records 2.7x cheaper.
# RAY_TPU_WIRE=0 forces the legacy all-pickle framing.

WIRE_VERSION = 1
_WIRE_LO, _WIRE_HI = 0xB0, 0xBF          # marker family
_WIRE_BYTE = bytes([_WIRE_LO | WIRE_VERSION])

_EXT_LOC = 1      # ObjectLocation (struct of pure fields)
_EXT_PICKLE = 2   # self-contained cloudpickled object (exceptions only)
_EXT_SPEC = 3     # TaskSpec: pure fields msgpack'd + one user-arg blob

# Message kinds eligible for binary framing. A kind outside this set —
# or any payload the codec cannot express — falls back to one
# cloudpickle frame, exactly the old wire.
WIRE_KINDS = frozenset({
    # worker/agent -> driver
    "task_done", "put", "gen_item", "heartbeat", "object_unreachable",
    "get_request", "wait_request", "gen_next_request", "gen_abandon",
    "submit", "submit_many", "actor_ckpt", "batch", "actor_exit",
    "dwait",
    # driver -> worker/agent
    "exec_task", "exec_actor_task", "exec_task_many",
    "exec_actor_task_many", "cancel", "materialize", "drop_device",
    "revoke_tasks", "shutdown", "get_reply", "heartbeat_ack",
    # worker <-> worker (direct actor calls)
    "dcall", "dresult",
    # two-level scheduling (docs/SCHEDULING.md): driver <-> node agent
    # bulk lease plane, and the agent-local worker dispatch plane
    "nlease_grant", "nlease_extend", "nlease_close", "nlease_done",
    "nlease_spill", "nlease_want", "nlease_release",
    "aregister", "aexec", "adone", "asubmit", "aresult", "aspill",
    # compiled-DAG channel plane (writer -> reader data sockets)
    "ch_open", "ch_notify", "ch_ack", "ch_err",
    # telemetry reports: the sys.metrics / sys.spans / sys.events
    # payloads are framework-pure after the PR-13 delta-format change
    # (tuple-keyed series ride msgpack maps); report channels carrying
    # arbitrary user payloads fall back per-frame like any other kind
    "report",
    # on-demand profiler control (driver -> worker) and its reply
    "profile_ctl", "profile_reply",
})

# Per-kind count of frames that attempted binary framing and fell back
# to cloudpickle (payload not wire-pure). Steady-state telemetry tests
# assert the hot kinds stay at zero; also exported as the
# ray_tpu_wire_fallbacks_total metric so worker-side fallbacks surface
# in the driver's cluster view.
import collections as _collections  # noqa: E402

wire_fallbacks: "_collections.Counter" = _collections.Counter()


def _record_fallback(kind) -> None:
    try:
        wire_fallbacks[kind] += 1
        from ..util import metrics_catalog as _mcat  # noqa: PLC0415
        _mcat.get("ray_tpu_wire_fallbacks_total").inc(
            tags={"kind": str(kind)})
    except Exception:
        pass

_wire_enabled = (msgpack is not None
                 and knobs.get_bool("RAY_TPU_WIRE"))


def set_wire_enabled(on: bool) -> None:
    """Flip binary framing process-wide (bench A/B; receivers always
    understand both framings, so mixed clusters are fine)."""
    global _wire_enabled
    _wire_enabled = bool(on) and msgpack is not None


def wire_enabled() -> bool:
    return _wire_enabled


# TaskSpec fields carried as msgpack values, in envelope order. args /
# kwargs / scheduling_strategy / runtime_env are the user-payload blob.
_SPEC_PURE_FIELDS = (
    "task_id", "name", "num_returns", "return_ids", "resources",
    "max_retries", "retry_exceptions", "max_calls", "streaming",
    "actor_id", "method_name", "concurrency_group",
    "placement_group_id", "bundle_index", "func_id", "dep_object_ids",
    "reconstructions", "trace_id", "span_id", "parent_span_id",
    "tpu_ids", "lease_id",
)

_LOC_FIELDS = ("kind", "size", "data", "name", "node_id", "spill_path",
               "seal_seq")


def _loc_cls():
    from .object_store import ObjectLocation  # noqa: PLC0415
    return ObjectLocation


def _spec_cls():
    from .task import TaskSpec  # noqa: PLC0415
    return TaskSpec


def _pack_default(obj):
    """msgpack fallback hook: locations and specs get compact envelopes,
    exceptions a self-contained pickle; anything else aborts the binary
    attempt (the whole frame then ships as legacy cloudpickle)."""
    cls_name = type(obj).__name__
    if cls_name == "ObjectLocation" and isinstance(obj, _loc_cls()):
        return msgpack.ExtType(_EXT_LOC, msgpack.packb(
            [getattr(obj, f) for f in _LOC_FIELDS], use_bin_type=True))
    if cls_name == "TaskSpec" and isinstance(obj, _spec_cls()):
        if getattr(obj, "wire_error", None):
            # a poisoned spec (payload failed to unpickle on a hop) must
            # keep its error across re-encodes: the compact envelope
            # would re-ship empty args and run silently wrong — the
            # cloudpickle fallback round-trips the attribute instead
            raise TypeError("spec carries wire_error; not wire-pure")
        pure = [getattr(obj, f) for f in _SPEC_PURE_FIELDS]
        if not obj.args and not obj.kwargs \
                and obj.scheduling_strategy is None \
                and obj.runtime_env is None:
            blob = b""    # no user payload: skip the pickle entirely
        else:
            blob = cloudpickle.dumps(
                (obj.args, obj.kwargs, obj.scheduling_strategy,
                 obj.runtime_env), protocol=5)
        return msgpack.ExtType(_EXT_SPEC, msgpack.packb(
            [pure, obj.func_bytes or b"", blob],
            use_bin_type=True, default=_pack_default))
    if isinstance(obj, BaseException):
        try:
            return msgpack.ExtType(_EXT_PICKLE,
                                   cloudpickle.dumps(obj, protocol=5))
        except Exception:
            raise TypeError(f"unpicklable exception {cls_name}") from None
    raise TypeError(f"not wire-pure: {cls_name}")


def _ext_hook(code: int, data: bytes):
    if code == _EXT_LOC:
        fields = msgpack.unpackb(data, raw=False, use_list=True)
        loc = _loc_cls()(*fields[:2])
        for f, v in zip(_LOC_FIELDS, fields):
            setattr(loc, f, v)
        return loc
    if code == _EXT_SPEC:
        pure, func_bytes, blob = msgpack.unpackb(
            data, raw=False, use_list=True, strict_map_key=False,
            ext_hook=_ext_hook, object_pairs_hook=_map_hook)
        spec = _spec_cls()(**dict(zip(_SPEC_PURE_FIELDS, pure)),
                           func_bytes=func_bytes)
        spec.args, spec.kwargs = (), {}
        spec.scheduling_strategy = spec.runtime_env = None
        if blob:
            try:
                (spec.args, spec.kwargs, spec.scheduling_strategy,
                 spec.runtime_env) = pickle.loads(blob)
            except BaseException as e:  # noqa: BLE001
                # The user payload references something only importable
                # on the submitter (e.g. a driver-only module). Failing
                # the DECODE would drop the whole frame and park the
                # caller forever; instead the spec carries the error and
                # the worker fails the task with it (worker.py
                # _check_spec_payload).
                spec.wire_error = f"{type(e).__name__}: {e}"
        return spec
    if code == _EXT_PICKLE:
        return pickle.loads(data)
    raise WireVersionError(f"unknown wire extension {code}")


def _map_hook(pairs):
    """Restore tuple dict keys (msgpack arrays are unhashable lists)."""
    return {tuple(k) if isinstance(k, list) else k: v for k, v in pairs}


def encode_message(msg) -> Optional[bytes]:
    """Binary body for a hot-kind control message, or None when the
    payload is not expressible (caller falls back to cloudpickle)."""
    if not _wire_enabled or not isinstance(msg, tuple) or not msg \
            or msg[0] not in WIRE_KINDS:
        return None
    try:
        return _WIRE_BYTE + msgpack.packb(list(msg), use_bin_type=True,
                                          default=_pack_default)
    except Exception:
        _record_fallback(msg[0])
        return None


def decode_message(data) -> Any:
    """Inverse of the framing: binary-marked bodies decode through the
    codec (raising WireVersionError on a foreign version), everything
    else is a pickle frame."""
    first = data[0] if data else 0
    if _WIRE_LO <= first <= _WIRE_HI:
        if first != _WIRE_BYTE[0]:
            raise WireVersionError(
                f"wire version {first & 0x0F} not supported "
                f"(this build speaks v{WIRE_VERSION})")
        if msgpack is None:
            raise WireVersionError("binary frame but msgpack unavailable")
        out = msgpack.unpackb(bytes(data[1:]), raw=False, use_list=True,
                              strict_map_key=False, ext_hook=_ext_hook,
                              object_pairs_hook=_map_hook)
        return tuple(out) if isinstance(out, list) else out
    return pickle.loads(data)


class Connection:
    """Thread-safe framed-message duplex connection."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self._send_lock = threading.Lock()
        self._recv_lock = threading.Lock()
        try:
            self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass  # unix sockets

    def send(self, msg: Any) -> None:
        # Hot framework-pure kinds ride the compact binary codec; all
        # else is cloudpickle, not pickle: messages carry user callables
        # (actor task args, data-stage fns) that plain pickle serializes
        # by reference — unpicklable in a worker that can't import the
        # sender's __main__.
        data = encode_message(msg)
        if data is None:
            data = cloudpickle.dumps(msg, protocol=5)
        with self._send_lock:
            try:
                # raylint: disable=RT001 the send lock exists solely to
                # serialize this socket write; no other state is
                # guarded by it
                self.sock.sendall(_HDR.pack(len(data)) + data)
            except (BrokenPipeError, ConnectionResetError, OSError) as e:
                raise ConnectionClosed(str(e)) from e

    def _recv_exact(self, n: int) -> bytes:
        return read_exact(self.sock, n)

    def recv(self) -> Any:
        with self._recv_lock:
            hdr = self._recv_exact(_HDR.size)
            (length,) = _HDR.unpack(hdr)
            if length > MAX_MSG:
                raise ConnectionClosed(f"oversized frame: {length}")
            data = self._recv_exact(length)
        try:
            return decode_message(data)
        except BaseException:  # noqa: BLE001 — framing is intact; keep going
            return (RECV_ERROR, traceback.format_exc())

    def close(self) -> None:
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()

    def fileno(self) -> int:
        return self.sock.fileno()


# ---------------------------------------------------------------------------
# Raw byte-frame helpers — the data-plane framing used by the peer-to-peer
# object transfer protocol (core/object_transfer.py). Unlike Connection
# messages these frames carry opaque bytes (no pickling on the payload
# path), so a multi-MB chunk costs one memcpy, not a serialize.

def read_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        try:
            # raylint: disable=RT003 transport helper: callers own the
            # timeout discipline (settimeout/select before calling)
            chunk = sock.recv(min(n - got, 1 << 20))
        except (ConnectionResetError, OSError) as e:
            raise ConnectionClosed(str(e)) from e
        if not chunk:
            raise ConnectionClosed("peer closed")
        chunks.append(chunk)
        got += len(chunk)
    return chunks[0] if len(chunks) == 1 else b"".join(chunks)


def write_frame(sock: socket.socket, payload) -> None:
    """Length-prefixed raw frame; payload is bytes or any buffer."""
    try:
        sock.sendall(_HDR.pack(len(payload)))
        sock.sendall(payload)
    except (BrokenPipeError, ConnectionResetError, OSError) as e:
        raise ConnectionClosed(str(e)) from e


def read_frame(sock: socket.socket, max_len: int = MAX_MSG) -> bytes:
    (length,) = _HDR.unpack(read_exact(sock, _HDR.size))
    if length > max_len:
        raise ConnectionClosed(f"oversized frame: {length}")
    return read_exact(sock, length)


def write_obj(sock: socket.socket, obj: Any) -> None:
    """Small pickled control frame (transfer-plane handshakes only)."""
    write_frame(sock, cloudpickle.dumps(obj, protocol=5))


def read_obj(sock: socket.socket, max_len: int = 1 << 20) -> Any:
    return pickle.loads(read_frame(sock, max_len))


def unix_listener(path: str) -> socket.socket:
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.bind(path)
    s.listen(128)
    return s


def unix_connect(path: str, timeout: Optional[float] = 10.0) -> Connection:
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.settimeout(timeout)
    s.connect(path)
    s.settimeout(None)
    return Connection(s)


def tcp_listener(host: str = "127.0.0.1", port: int = 0) -> socket.socket:
    """Multi-host transport: remote node agents and their workers speak
    the same framed protocol over TCP (reference parity: the gRPC
    services of src/ray/gcs/gcs_server/gcs_node_manager.cc — here one
    listener serves workers AND node agents, demuxed by the first
    message)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind((host, port))
    s.listen(128)
    return s


def tcp_connect(host: str, port: int,
                timeout: Optional[float] = 10.0) -> Connection:
    s = socket.create_connection((host, port), timeout=timeout)
    if s.getsockname() == s.getpeername():
        # TCP self-connect: connecting to a loopback port in the
        # ephemeral range while nothing listens can "succeed" against
        # OURSELVES (the kernel picked source port == dest port). A
        # node agent retrying a dead driver's address would then talk
        # to its own echo and believe it rejoined — refuse, so the
        # caller's retry loop keeps waiting for the real listener
        # (observed during driver crash-restart reattach tests).
        s.close()
        raise ConnectionRefusedError(
            f"self-connect to {host}:{port} (no listener yet)")
    s.settimeout(None)
    return Connection(s)


def connect_address(address: str,
                    timeout: Optional[float] = 10.0) -> Connection:
    """Connect to "tcp://host:port" or a unix-socket path (optionally
    "unix://path")."""
    if address.startswith("tcp://"):
        host, _, port = address[len("tcp://"):].rpartition(":")
        return tcp_connect(host, int(port), timeout=timeout)
    if address.startswith("unix://"):
        address = address[len("unix://"):]
    return unix_connect(address, timeout=timeout)
