"""Value serialization for the object store.

Reference parity: python/ray/_private/serialization.py — cloudpickle for
arbitrary Python, pickle protocol 5 out-of-band buffers for zero-copy numpy.

Wire format of a sealed object:
    [u32 meta_len][meta pickle][u32 nbufs][u64 len_i ... aligned buffers]

Buffers are 64-byte aligned inside the payload so readers can map numpy
arrays directly onto shared memory with no copy.
"""
from __future__ import annotations

import pickle
import struct
from typing import Any, List, Tuple

import cloudpickle

ALIGN = 64


def _align(n: int) -> int:
    return (n + ALIGN - 1) // ALIGN * ALIGN


def serialize(value: Any) -> Tuple[bytes, List[pickle.PickleBuffer]]:
    """Serialize to (meta, out_of_band_buffers).

    numpy arrays (and anything implementing __reduce_ex__ with protocol 5
    buffer support) ship their payload out-of-band; jax.Array is converted
    to numpy by the caller before it reaches here.
    """
    buffers: List[pickle.PickleBuffer] = []
    meta = cloudpickle.dumps(value, protocol=5, buffer_callback=buffers.append)
    return meta, buffers


def pack(value: Any) -> bytes:
    """Serialize into a single contiguous payload (small-object path)."""
    meta, bufs = serialize(value)
    return pack_parts(meta, bufs)


def pack_parts(meta: bytes, bufs: List[pickle.PickleBuffer]) -> bytes:
    raws = [b.raw() for b in bufs]
    header = bytearray()
    header += struct.pack("<I", len(meta))
    header += meta
    header += struct.pack("<I", len(raws))
    for r in raws:
        header += struct.pack("<Q", r.nbytes)
    out = bytearray(header)
    for r in raws:
        pad = _align(len(out)) - len(out)
        out += b"\x00" * pad
        out += r
    return bytes(out)


def packed_size(meta: bytes, bufs: List[pickle.PickleBuffer]) -> int:
    n = 4 + len(meta) + 4 + 8 * len(bufs)
    for b in bufs:
        n = _align(n) + b.raw().nbytes
    return n


def pack_into(mv: memoryview, meta: bytes, bufs: List[pickle.PickleBuffer]) -> int:
    """Write the wire format into a writable memoryview (shm path). Returns
    bytes written."""
    off = 0
    mv[off:off + 4] = struct.pack("<I", len(meta)); off += 4
    mv[off:off + len(meta)] = meta; off += len(meta)
    raws = [b.raw() for b in bufs]
    mv[off:off + 4] = struct.pack("<I", len(raws)); off += 4
    for r in raws:
        mv[off:off + 8] = struct.pack("<Q", r.nbytes); off += 8
    for r in raws:
        aligned = _align(off)
        if aligned != off:
            mv[off:aligned] = b"\x00" * (aligned - off)
            off = aligned
        mv[off:off + r.nbytes] = r
        off += r.nbytes
    return off


def unpack(payload) -> Any:
    """Deserialize from bytes or a memoryview.

    When given a memoryview over shared memory, numpy buffers alias the shm
    pages (zero-copy); callers must keep the segment mapped while the value
    lives. bytes input always owns its data.
    """
    mv = memoryview(payload)
    off = 0
    (meta_len,) = struct.unpack_from("<I", mv, off); off += 4
    meta = bytes(mv[off:off + meta_len]); off += meta_len
    (nbufs,) = struct.unpack_from("<I", mv, off); off += 4
    sizes = []
    for _ in range(nbufs):
        (sz,) = struct.unpack_from("<Q", mv, off); off += 8
        sizes.append(sz)
    bufs = []
    for sz in sizes:
        aligned = _align(off)
        bufs.append(mv[aligned:aligned + sz])
        off = aligned + sz
    return pickle.loads(meta, buffers=bufs)


def dumps_call(obj: Any) -> bytes:
    """Serialize task functions / actor classes by value (cloudpickle)."""
    return cloudpickle.dumps(obj)


def loads_call(data: bytes) -> Any:
    return cloudpickle.loads(data)
