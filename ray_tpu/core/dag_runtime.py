"""Compiled-DAG execution engine (docs/DAG.md).

Two halves of the tentpole live here:

* `WorkerDagContext` — worker-side. `dag_install` builds the worker's
  stage list and channel endpoints once; `dag_start` launches a runner
  thread that loops forever: read one seqno from every in-channel, run
  this worker's stages in topo order (same-worker edges are plain
  in-memory handoffs — no serialization at all), write every
  out-channel. Zero driver messages in steady state.

* `DriverDagController` — driver-side. Compiles the graph plan
  produced by `dag.CompiledDAG` into placement (one pinned worker per
  function stage via `runtime.dag_acquire`, dependency-local), per-
  worker install plans, and channels; `execute()` just stamps a seqno
  and pushes the input tuples into the root channels. Terminal values
  arrive on the controller's own ChannelHost — never the control
  socket, so `ctrl_msgs` stays flat (counter-asserted in
  tests/test_dag_compiled.py).

Failure semantics: user exceptions ride the channels as TaskError
payloads and re-raise at `CompiledDagRef.get()` without disturbing the
pipeline. Infrastructure failures (participant death, channel socket
loss, install timeout) fail every in-flight execution with
`CompiledDagError`, tear the channels down, and leave the controller
dead — `CompiledDAG.execute()` then transparently re-compiles.
"""
from __future__ import annotations

import collections
import os
import threading
import time
import traceback
import uuid
from typing import Any, Dict, List, Optional, Tuple

from ..exceptions import CompiledDagError, GetTimeoutError, TaskError
from ..util import knobs
from ..util import tracing
from ..util import waits as waits_mod
from .dag_channel import (ChannelClosed, ChannelHost, ChannelReader,
                          ChannelWriter)
from .protocol import ConnectionClosed

# Bounded buffer of delivered-but-unretrieved execution results; oldest
# evict first. Refs are expected to be consumed promptly (the depth-1
# channel handshake already bounds UNdelivered executions to the
# pipeline depth).
_RESULT_BUFFER_CAP = 1024

# Flight-recorder ring capacity (per dag per process). Spans recorded
# beyond this between two telemetry flushes are dropped oldest-first
# and counted in ray_tpu_trace_spans_dropped_total — the recorder is
# always-on, so its worst case must be a bounded window, not a queue.
_SPAN_RING_CAP = 4096


def _mcat():
    from ..util import metrics_catalog  # noqa: PLC0415
    return metrics_catalog


def eval_input_expr(expr: Tuple, input_args: Tuple,
                    input_kwargs: Dict[str, Any]) -> Any:
    """Resolve an InputNode/InputAttributeNode expression against one
    execute() call's arguments (same contract as InputNode._exec)."""
    if input_kwargs or len(input_args) != 1:
        if not input_args and not input_kwargs:
            raise TypeError("DAG has an InputNode; execute() needs an "
                            "argument")
        base: Any = (input_args, input_kwargs)
    else:
        base = input_args[0]
    if expr[0] == "whole":
        return base
    if expr[0] == "attr":
        return getattr(base, expr[1])
    return base[expr[1]]


# ---------------------------------------------------------------------------
# Worker side
# ---------------------------------------------------------------------------

class _WorkerDag:
    __slots__ = ("dag_id", "stages", "readers", "in_order", "input_ch",
                 "writers", "thread", "stop", "span_ring", "span_drops")

    def __init__(self, dag_id: str):
        self.dag_id = dag_id
        self.stages: List[dict] = []
        self.readers: Dict[str, ChannelReader] = {}
        self.in_order: List[str] = []
        self.input_ch: Optional[str] = None
        self.writers: Dict[str, ChannelWriter] = {}
        self.thread: Optional[threading.Thread] = None
        self.stop = False
        # flight-recorder ring: (sid, seq, t0, t1, stall) tuples. The
        # exec loop only pays this append; span dicts, derived ids and
        # histogram observes happen at telemetry-flush cadence
        # (drain_stage_spans). Bounded so a stalled flusher can never
        # grow memory — overflow counts into
        # ray_tpu_trace_spans_dropped_total.
        self.span_ring: collections.deque = collections.deque(
            maxlen=_SPAN_RING_CAP)
        self.span_drops = 0


class WorkerDagContext:
    """Installed compiled-DAG state of one worker process."""

    def __init__(self, loop):
        self._loop = loop
        self._host: Optional[ChannelHost] = None
        self._dags: Dict[str, _WorkerDag] = {}
        self._lock = threading.Lock()

    def _ensure_host(self) -> ChannelHost:
        if self._host is None:
            prefer_tcp = str(self._loop.socket_path).startswith("tcp://")
            self._host = ChannelHost(prefer_tcp,
                                     label=self._loop.worker_id)
        return self._host

    # -- driver messages ----------------------------------------------------
    def install(self, plan: dict) -> None:
        dag_id = plan["dag_id"]
        try:
            host = self._ensure_host()
            d = _WorkerDag(dag_id)
            d.stages = plan["stages"]
            for st in d.stages:
                # static flight-recorder parent of this stage's spans:
                # first upstream-stage arg (local or channel) wins;
                # input-fed / dependency-free stages parent to the
                # driver's exec-submit span. Resolved once here so the
                # per-seqno path derives ids from a plain key.
                pkey = "drv"
                for ent in (list(st["args"])
                            + list(st["kwargs"].values())):
                    if ent[0] == "lo":
                        pkey = ent[1]
                        break
                    if ent[0] == "ch":
                        # ch_id format: "<dag_id>.<sid>.<consumer_wid>"
                        pkey = ent[1].split(".")[1]
                        break
                st["_span_parent"] = pkey
            d.in_order = list(plan["in_chans"])
            d.input_ch = plan.get("input_ch")
            for ch_id in d.in_order:
                d.readers[ch_id] = host.register(ch_id)
            for desc in plan["out_chans"]:
                d.writers[desc["ch_id"]] = ChannelWriter(
                    dag_id, desc["ch_id"], addr="",
                    same_node=desc["same_node"])
            with self._lock:
                self._dags[dag_id] = d
            self._loop.conn.send(("dag_ready", dag_id,
                                  self._loop.worker_id, host.address))
        except Exception as e:  # noqa: BLE001 — driver owns the verdict
            try:
                self._loop.conn.send(("dag_error", dag_id,
                                      self._loop.worker_id, repr(e)))
            except ConnectionClosed:
                pass

    def start(self, dag_id: str, addr_map: Dict[str, str]) -> None:
        d = self._dags.get(dag_id)
        if d is None or d.thread is not None:
            return
        for ch_id, w in d.writers.items():
            w.addr = addr_map[ch_id]
        d.thread = threading.Thread(target=self._run, args=(d,),
                                    daemon=True,
                                    name=f"dag-run-{dag_id}")
        d.thread.start()

    def teardown(self, dag_id: str) -> None:
        with self._lock:
            d = self._dags.pop(dag_id, None)
        if d is None:
            return
        d.stop = True
        for ch_id in d.in_order:
            if self._host is not None:
                self._host.unregister(ch_id)
        for w in d.writers.values():
            w.close()
        try:
            # the dag left the registry above — convert whatever its
            # ring still holds so teardown never loses recorded spans
            leftover: List[dict] = []
            self._drain_dag_spans(d, leftover)
            for sp in leftover:
                self._loop.record_span(sp)
        except Exception:
            pass

    def teardown_all(self) -> None:
        for dag_id in list(self._dags):
            self.teardown(dag_id)

    # -- stage runner -------------------------------------------------------
    def _report_down(self, d: _WorkerDag, reason: str) -> None:
        if d.stop:
            return  # orderly teardown, not a failure
        d.stop = True
        try:
            self._loop.conn.send(("dag_down", d.dag_id,
                                  self._loop.worker_id, reason))
        except ConnectionClosed:
            pass

    def _run(self, d: _WorkerDag) -> None:
        try:
            for w in d.writers.values():
                w.open()
        except CompiledDagError as e:
            self._report_down(d, repr(e))
            return
        seq = 0
        while not d.stop:
            seq += 1
            vals: Dict[Tuple, Any] = {}
            try:
                for ch_id in d.in_order:
                    s, v = d.readers[ch_id].read_value()
                    if s != seq:
                        raise ChannelClosed(
                            f"seqno skew on {ch_id}: got {s}, "
                            f"expected {seq}")
                    vals[("ch", ch_id)] = v
            except ChannelClosed as e:
                self._report_down(d, repr(e))
                return
            spans_on = knobs.get_bool("RAY_TPU_FASTPATH_SPANS")
            stage_t: Dict[int, Tuple[float, float]] = {}
            for st in d.stages:
                t0 = time.time()
                vals[("lo", st["sid"])] = self._run_stage(d, st, vals)
                stage_t[st["sid"]] = (t0, time.time())
            # per-writer stall baselines: the write loop below may block
            # on ack windows, and each stage's span attributes exactly
            # the stall its own out-channels paid this seqno
            stall0 = {ch_id: w.stall_s for ch_id, w in d.writers.items()}
            try:
                for st in d.stages:
                    for ch_id in st["outs"]:
                        d.writers[ch_id].write_value(
                            seq, vals[("lo", st["sid"])])
            except CompiledDagError as e:
                self._report_down(d, repr(e))
                return
            if spans_on:
                try:
                    # hot path records a tuple per stage, nothing more;
                    # drain_stage_spans does the expensive conversion
                    # at telemetry-flush cadence
                    ring = d.span_ring
                    for st in d.stages:
                        sid = st["sid"]
                        t0, t1 = stage_t[sid]
                        stall = sum(
                            d.writers[ch].stall_s - stall0.get(ch, 0.0)
                            for ch in st["outs"] if ch in d.writers)
                        if len(ring) == ring.maxlen:
                            d.span_drops += 1
                        ring.append((sid, seq, t0, t1, stall))
                except Exception:
                    pass   # flight recorder must never fail the pipeline

    def drain_stage_spans(self) -> List[dict]:
        """Convert buffered (sid, seq, t0, t1, stall) ring entries into
        full span dicts — OFF the per-seqno hot path, at telemetry-flush
        cadence. Span ids are DERIVED from (dag_id, sid, seqno), so the
        upstream stage — in a different process — produced the exact
        parent id this side derives locally: the cross-worker tree needs
        zero coordination and zero extra wire traffic (spans ride the
        telemetry heartbeat, keeping the steady-state ctrl counters
        flat)."""
        with self._lock:
            dags = list(self._dags.values())
        out: List[dict] = []
        for d in dags:
            self._drain_dag_spans(d, out)
        return out

    def _drain_dag_spans(self, d: _WorkerDag, out: List[dict]) -> None:
        ring = d.span_ring
        if not ring and not d.span_drops:
            return
        drops, d.span_drops = d.span_drops, 0
        if drops:
            try:
                _mcat().get(
                    "ray_tpu_trace_spans_dropped_total").inc(drops)
            except Exception:
                pass
        wid = self._loop.worker_id
        pid = os.getpid()
        node_id = knobs.get_raw("RAY_TPU_NODE_ID")
        by_sid = {st["sid"]: st for st in d.stages}
        durs: Dict[int, List[float]] = {}
        tid_cache: Dict[int, str] = {}
        while True:
            try:
                sid, seq, t0, t1, stall = ring.popleft()
            except IndexError:
                break
            st = by_sid.get(sid) or {}
            trace_id = tid_cache.get(seq)
            if trace_id is None:
                trace_id = tracing.derived_trace_id(d.dag_id, seq)
                tid_cache[seq] = trace_id
            span = {
                "trace_id": trace_id,
                "span_id": tracing.derived_span_id(
                    d.dag_id, sid, seq),
                "parent_span_id": tracing.derived_span_id(
                    d.dag_id, st.get("_span_parent", "drv"), seq),
                "task_id": f"{d.dag_id}.{sid}",
                "name": st.get("name") or f"dag_stage:{sid}",
                "cat": "dag_stage",
                "dag_id": d.dag_id, "sid": sid, "seqno": seq,
                "start": t0, "end": t1, "status": "ok",
                "pid": pid, "worker_id": wid,
                "node_id": node_id,
            }
            if stall > 0:
                span["ack_stall_s"] = stall
            out.append(span)
            durs.setdefault(sid, []).append(t1 - t0)
        for sid, vals in durs.items():
            try:
                _mcat().get(
                    "ray_tpu_dag_stage_exec_seconds").observe_many(
                    vals, tags={"dag_id": d.dag_id, "sid": str(sid)})
            except Exception:
                pass

    def _run_stage(self, d: _WorkerDag, st: dict,
                   vals: Dict[Tuple, Any]) -> Any:
        def resolve(entry):
            k = entry[0]
            if k == "c":
                return entry[1]
            if k == "in":
                return vals[("ch", d.input_ch)][entry[1]]
            if k == "ch":
                return vals[("ch", entry[1])]
            return vals[("lo", entry[1])]

        args = [resolve(e) for e in st["args"]]
        kwargs = {k: resolve(e) for k, e in st["kwargs"].items()}
        # upstream error: propagate it downstream instead of running
        for a in args:
            if isinstance(a, BaseException):
                return a
        for a in kwargs.values():
            if isinstance(a, BaseException):
                return a
        try:
            if st["kind"] == "method":
                inst = self._loop._actor_instance
                if inst is None:
                    raise RuntimeError("actor instance not constructed")
                return getattr(inst, st["method"])(*args, **kwargs)
            return st["fn"](*args, **kwargs)
        except BaseException as e:  # noqa: BLE001 — becomes a TaskError
            return TaskError(repr(e), traceback.format_exc(),
                             task_name=st.get("name", "dag_stage"))


# ---------------------------------------------------------------------------
# Driver side
# ---------------------------------------------------------------------------

class CompiledDagRef:
    """Future for one output slot of one compiled execute(). Resolved
    by `ray_tpu.get(ref)` or `.get(timeout=...)` — never convertible to
    an ObjectRef (the value lives in the controller's result buffer,
    not the object store)."""

    _is_dag_ref = True
    __slots__ = ("_ctl", "_seq", "_slot")

    def __init__(self, ctl: "DriverDagController", seq: int, slot: Tuple):
        self._ctl = ctl
        self._seq = seq
        self._slot = slot

    def get(self, timeout: Optional[float] = None) -> Any:
        return self._ctl.get_slot(self._seq, self._slot, timeout)

    def __reduce__(self):
        raise TypeError(
            "CompiledDagRef is driver-local and cannot be serialized "
            "or passed to tasks; get() it first")

    def __repr__(self):
        return f"CompiledDagRef(dag={self._ctl.dag_id}, seq={self._seq})"


class _InputWriter:
    __slots__ = ("writer", "exprs")

    def __init__(self, writer: ChannelWriter, exprs: List[Tuple]):
        self.writer = writer
        self.exprs = exprs


class DriverDagController:
    """One compiled pipeline: placement, channels, in-flight results."""

    def __init__(self, rt, cplan: dict):
        self.rt = rt
        self.dag_id = f"dag-{uuid.uuid4().hex[:8]}"
        self.dead = False
        self._failure: Optional[CompiledDagError] = None
        self._seq = 0
        self._exec_lock = threading.Lock()
        self._cond = threading.Condition()
        self._inflight: "Dict[int, dict]" = {}
        self._participants: Dict[str, dict] = {}   # wid -> {"conn","pinned"}
        self._ready: Dict[str, Optional[str]] = {}  # wid -> host addr
        self._ready_evt = threading.Event()
        self._install_err: Optional[str] = None
        self._input_writers: List[_InputWriter] = []
        self._terminal_chs: List[str] = []
        self._host: Optional[ChannelHost] = None
        self._torn_down = False
        self._drv_exprs: List[Tuple] = list(cplan.get("drv_exprs") or ())
        self._term_by_sid: Dict[int, str] = {}
        self.stats = {"execs": 0, "channels": 0, "workers": 0}
        # driver-side flight-recorder ring: execute()/_collect() append
        # bare tuples; _drain_spans converts to span dicts off the hot
        # path (on worker-span ingest and timeline export)
        self._span_ring: collections.deque = collections.deque(
            maxlen=_SPAN_RING_CAP)
        self._span_drops = 0
        timeout = knobs.get_float("RAY_TPU_DAG_COMPILE_TIMEOUT_S")
        try:
            self._compile(cplan, timeout)
        except BaseException:
            self._teardown("compile failed")
            raise
        try:
            rt._span_drains.append(self._drain_spans)
        except Exception:
            pass

    # -- compile ------------------------------------------------------------
    def _compile(self, cplan: dict, timeout: float) -> None:
        rt = self.rt
        stages = cplan["stages"]           # topo order
        reqs = [{"sid": s["sid"], "kind": s["kind"],
                 "actor_id": s.get("actor_id"),
                 "num_cpus": s.get("num_cpus") or 1,
                 "deps": s["deps"]} for s in stages]
        placement = rt.dag_acquire(self.dag_id, reqs, timeout)
        by_sid = {s["sid"]: s for s in stages}
        wid_of = {sid: p["wid"] for sid, p in placement.items()}
        node_of = {sid: p["node_id"] for sid, p in placement.items()}
        for sid, p in placement.items():
            self._participants.setdefault(
                p["wid"], {"conn": p["conn"], "pinned": p["pinned"],
                           "node_id": p["node_id"]})
        # worker partition, topo order preserved
        worker_sids: Dict[str, List[int]] = {}
        for s in stages:
            worker_sids.setdefault(wid_of[s["sid"]], []).append(s["sid"])
        # cross-worker channels: one per (producer stage, consumer worker)
        chans: Dict[Tuple[int, str], dict] = {}

        def edge_ch(sid: int, consumer_wid: str) -> str:
            key = (sid, consumer_wid)
            if key not in chans:
                chans[key] = {
                    "ch_id": f"{self.dag_id}.{sid}.{consumer_wid}",
                    "same_node": node_of[sid] == self._participants[
                        consumer_wid]["node_id"]}
            return chans[key]["ch_id"]

        # driver host first: terminal channels need its address
        prefer_tcp = any(p["node_id"] != rt.node_id
                         for p in self._participants.values())
        self._host = ChannelHost(prefer_tcp, label=self.dag_id)

        # per-worker install plans
        plans: Dict[str, dict] = {}
        input_exprs: Dict[str, List[Tuple]] = {}   # wid -> expr list
        for wid, sids in worker_sids.items():
            wstages = []
            in_chans: List[str] = []
            for sid in sids:
                s = by_sid[sid]
                entries = {"args": [], "kwargs": {}}
                for tgt, src in (("args", s["args"]),
                                 ("kwargs", s["kwargs"].items())):
                    it = src if tgt == "args" else src
                    for item in it:
                        k, aentry = (None, item) if tgt == "args" \
                            else (item[0], item[1])
                        kind = aentry[0]
                        if kind == "const":
                            ent = ("c", aentry[1])
                        elif kind == "input":
                            exprs = input_exprs.setdefault(wid, [])
                            if aentry[1] not in exprs:
                                exprs.append(aentry[1])
                            ent = ("in", exprs.index(aentry[1]))
                        else:  # ("stage", sid)
                            up = aentry[1]
                            if wid_of[up] == wid:
                                ent = ("lo", up)
                            else:
                                ch = edge_ch(up, wid)
                                if ch not in in_chans:
                                    in_chans.append(ch)
                                ent = ("ch", ch)
                        if tgt == "args":
                            entries["args"].append(ent)
                        else:
                            entries["kwargs"][k] = ent
                wstages.append({
                    "sid": sid, "kind": s["kind"], "fn": s.get("fn"),
                    "method": s.get("method"), "name": s.get("name", ""),
                    "args": entries["args"],
                    "kwargs": entries["kwargs"], "outs": []})
            plans[wid] = {"dag_id": self.dag_id, "worker_id": wid,
                          "stages": wstages, "in_chans": in_chans,
                          "input_ch": None, "out_chans": []}
        # a worker with no inbound channels still needs a per-execute
        # tick; any worker consuming the input gets its channel too
        for wid, plan in plans.items():
            if wid in input_exprs or not plan["in_chans"]:
                ch_id = f"{self.dag_id}.in.{wid}"
                plan["input_ch"] = ch_id
                plan["in_chans"].insert(0, ch_id)
                w = ChannelWriter(
                    self.dag_id, ch_id, addr="",
                    same_node=self._participants[wid]["node_id"]
                    == rt.node_id)
                self._input_writers.append(
                    _InputWriter(w, input_exprs.get(wid, [])))
        # wire producer stages to their out-channels
        consumer_wid_of_ch: Dict[str, str] = {}
        for (sid, cwid), desc in chans.items():
            ch_id = desc["ch_id"]
            consumer_wid_of_ch[ch_id] = cwid
            wid = wid_of[sid]
            for st in plans[wid]["stages"]:
                if st["sid"] == sid:
                    st["outs"].append(ch_id)
            plans[wid]["out_chans"].append(desc)
        # terminal channels: producer stage -> driver
        term_by_sid: Dict[int, str] = {}
        for slot in cplan["output_slots"]:
            if slot[0] != "stage":
                continue
            sid = slot[1]
            if sid in term_by_sid:
                continue
            ch_id = f"{self.dag_id}.{sid}.drv"
            term_by_sid[sid] = ch_id
            wid = wid_of[sid]
            for st in plans[wid]["stages"]:
                if st["sid"] == sid:
                    st["outs"].append(ch_id)
            plans[wid]["out_chans"].append(
                {"ch_id": ch_id,
                 "same_node": node_of[sid] == rt.node_id})
            self._terminal_chs.append(ch_id)
        self._term_by_sid = term_by_sid
        self.stats["channels"] = (len(chans) + len(self._terminal_chs)
                                  + len(self._input_writers))
        self.stats["workers"] = len(self._participants)

        # register terminal readers BEFORE installs (writers may
        # connect as soon as dag_start lands)
        term_readers = {ch: self._host.register(ch)
                        for ch in self._terminal_chs}
        # route dag_ready/dag_down to this controller
        rt.compiled_dags[self.dag_id] = self
        deadline = time.time() + timeout
        for wid, plan in plans.items():
            try:
                self._participants[wid]["conn"].send(("dag_install", plan))
            except ConnectionClosed as e:
                raise CompiledDagError(
                    f"participant {wid} unreachable at install",
                    cause=repr(e)) from e
        while len(self._ready) < len(plans):
            if self._install_err is not None:
                raise CompiledDagError("install failed",
                                       cause=self._install_err)
            if self.dead:
                raise self._failure
            if not self._ready_evt.wait(max(0.0, deadline - time.time())):
                raise CompiledDagError(
                    "install handshake timed out",
                    cause=f"{len(self._ready)}/{len(plans)} ready")
            self._ready_evt.clear()
        # address map: each channel's reader address
        addr_map: Dict[str, str] = {}
        for ch_id, cwid in consumer_wid_of_ch.items():
            addr_map[ch_id] = self._ready[cwid]
        for ch_id in self._terminal_chs:
            addr_map[ch_id] = self._host.address
        for wid in plans:
            self._participants[wid]["conn"].send(
                ("dag_start", self.dag_id, addr_map))
        for iw in self._input_writers:
            wid = iw.writer.ch_id.rsplit(".", 1)[1]
            iw.writer.addr = self._ready[wid]
            iw.writer.open()
        for ch_id, reader in term_readers.items():
            threading.Thread(target=self._collect,
                             args=(ch_id, reader), daemon=True,
                             name=f"dag-collect-{ch_id}").start()
        rt._emit("dag.compile", dag_id=self.dag_id,
                 stages=len(stages), workers=len(self._participants),
                 channels=self.stats["channels"])
        for ch_id in addr_map:
            rt._emit("dag.channel.open", dag_id=self.dag_id,
                     channel=ch_id)

    # -- dispatcher-thread callbacks ---------------------------------------
    def on_ready(self, wid: str, addr: Optional[str]) -> None:
        self._ready[wid] = addr
        self._ready_evt.set()

    def on_install_error(self, wid: str, reason: str) -> None:
        self._install_err = f"{wid}: {reason}"
        self._ready_evt.set()

    def on_down(self, wid: str, reason: str) -> None:
        self._fail_async(f"participant {wid} reported failure: {reason}")

    def on_worker_dead(self, wid: str) -> None:
        if wid in self._participants:
            self._fail_async(f"participant worker {wid} died")

    # -- failure / teardown -------------------------------------------------
    def _fail_async(self, cause: str) -> None:
        """Fail from the dispatcher thread without blocking it."""
        if self.dead:
            return
        threading.Thread(target=self._fail,
                         args=(CompiledDagError(
                             "compiled DAG pipeline failed", cause=cause),),
                         daemon=True, name="dag-fail").start()

    def _fail(self, err: CompiledDagError) -> None:
        with self._cond:
            if self.dead:
                return
            self.dead = True
            self._failure = err
            self._cond.notify_all()
        self._ready_evt.set()
        try:
            self.rt._emit("dag.fail", dag_id=self.dag_id,
                          cause=err.cause or str(err))
        except Exception:
            pass
        self._teardown(err.cause or "failure")

    def _drain_spans(self) -> None:
        """Convert buffered driver-side ring entries (exec submits,
        result arrivals) into span dicts on rt.trace_spans. Runs on
        worker-span ingest / timeline export — never on the execute()
        hot path."""
        ring = self._span_ring
        if not ring and not self._span_drops:
            return
        drops, self._span_drops = self._span_drops, 0
        if drops:
            try:
                _mcat().get(
                    "ray_tpu_trace_spans_dropped_total").inc(drops)
            except Exception:
                pass
        pid = os.getpid()
        node_id = getattr(self.rt, "node_id", "")
        while True:
            try:
                kind, sid, seq, t0, t1 = ring.popleft()
            except IndexError:
                break
            if kind == "drv":
                span = {
                    "trace_id": tracing.derived_trace_id(
                        self.dag_id, seq),
                    "span_id": tracing.derived_span_id(
                        self.dag_id, "drv", seq),
                    "parent_span_id": "",
                    "task_id": f"{self.dag_id}.exec",
                    "name": f"dag_exec:{self.dag_id}",
                    "cat": "dag_submit",
                    "dag_id": self.dag_id, "seqno": seq,
                    "start": t0, "end": t1,
                    "status": "ok", "pid": pid,
                    "worker_id": "driver", "node_id": node_id,
                }
            else:
                span = {
                    "trace_id": tracing.derived_trace_id(
                        self.dag_id, seq),
                    "span_id": tracing.derived_span_id(
                        self.dag_id, "res", sid, seq),
                    "parent_span_id": tracing.derived_span_id(
                        self.dag_id, sid, seq),
                    "task_id": f"{self.dag_id}.{sid}",
                    "name": f"dag_result:{sid}",
                    "cat": "dag_result",
                    "dag_id": self.dag_id, "sid": sid, "seqno": seq,
                    "start": t0, "end": t1, "status": "ok",
                    "pid": pid, "worker_id": "driver",
                    "node_id": node_id,
                }
            self.rt.trace_spans.append(span)

    def _teardown(self, reason: str) -> None:
        if self._torn_down:
            return
        self._torn_down = True
        self.dead = True
        try:
            self._drain_spans()
            self.rt._span_drains.remove(self._drain_spans)
        except Exception:
            pass
        if self._failure is None:
            self._failure = CompiledDagError("compiled DAG torn down",
                                             cause=reason)
        for iw in self._input_writers:
            iw.writer.close()
        for wid, p in self._participants.items():
            try:
                p["conn"].send(("dag_teardown", self.dag_id))
            except (ConnectionClosed, OSError):
                pass
        if self._host is not None:
            self._host.close()
        self.rt.compiled_dags.pop(self.dag_id, None)
        self.rt.dag_release(
            self.dag_id,
            [wid for wid, p in self._participants.items()
             if p["pinned"]],
            channels=self.stats["channels"], reason=reason)
        with self._cond:
            self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            self.dead = True
            if self._failure is None:
                self._failure = CompiledDagError(
                    "compiled DAG closed", cause="close()")
            self._cond.notify_all()
        self._teardown("close()")

    # -- execute ------------------------------------------------------------
    def execute(self, input_args: Tuple,
                input_kwargs: Dict[str, Any]) -> int:
        t_submit = time.time()
        with self._exec_lock:
            if self.dead:
                raise self._failure
            seq = self._seq + 1
            ent = {"ch": {}, "drv": {}}
            with self._cond:
                self._inflight[seq] = ent
                if len(self._inflight) > _RESULT_BUFFER_CAP:
                    self._inflight.pop(next(iter(self._inflight)))
            for idx, expr in enumerate(self._drv_exprs):
                ent["drv"][idx] = eval_input_expr(expr, input_args,
                                                  input_kwargs)
            try:
                for iw in self._input_writers:
                    vals = tuple(
                        eval_input_expr(e, input_args, input_kwargs)
                        for e in iw.exprs)
                    iw.writer.write_value(seq, vals)
            except CompiledDagError as e:
                self._fail(e)
                raise self._failure from e
            self._seq = seq
        self.stats["execs"] += 1
        try:
            _mcat().get("ray_tpu_dag_execs_total").inc(
                tags={"mode": "pipelined"})
        except Exception:
            pass
        if knobs.get_bool("RAY_TPU_FASTPATH_SPANS"):
            try:
                # driver-local root span of this execution: input-fed
                # stages derive this exact id as their parent. Only a
                # tuple append here — _drain_spans builds the dict off
                # the submit path
                ring = self._span_ring
                if len(ring) == ring.maxlen:
                    self._span_drops += 1
                ring.append(("drv", None, seq, t_submit, time.time()))
            except Exception:
                pass
        return seq

    def make_ref(self, seq: int, slot: Tuple) -> CompiledDagRef:
        """slot: ("stage", sid, idx|None) or ("drv", idx) — mapped to
        the internal (channel / driver-slot) address."""
        if slot[0] == "drv":
            return CompiledDagRef(self, seq, ("drv", slot[1]))
        return CompiledDagRef(
            self, seq, ("ch", self._term_by_sid[slot[1]], slot[2]))

    def _collect(self, ch_id: str, reader: ChannelReader) -> None:
        while True:
            try:
                seq, value = reader.read_value()
            except ChannelClosed:
                return
            now = time.time()
            with self._cond:
                ent = self._inflight.get(seq)
                if ent is not None:
                    ent["ch"][ch_id] = value
                    self._cond.notify_all()
            if knobs.get_bool("RAY_TPU_FASTPATH_SPANS"):
                try:
                    # instant span marking the result's arrival at the
                    # driver, parented to the terminal stage's derived
                    # span (ch_id: "<dag_id>.<sid>.drv"). Tuple append
                    # only — converted by _drain_spans
                    ring = self._span_ring
                    if len(ring) == ring.maxlen:
                        self._span_drops += 1
                    ring.append(("res", ch_id.split(".")[1], seq,
                                 now, now))
                except Exception:
                    pass

    def get_slot(self, seq: int, slot: Tuple,
                 timeout: Optional[float] = None) -> Any:
        deadline = None if timeout is None else time.time() + timeout
        # wtok is a one-slot holder so the wait loop can park lazily —
        # only when the slot is actually absent and we are about to
        # sleep, not on the (common) already-settled fast path.
        wtok = [0]
        try:
            return self._get_slot_locked(seq, slot, timeout, deadline,
                                         wtok)
        finally:
            waits_mod.unpark(wtok[0])

    def _get_slot_locked(self, seq, slot, timeout, deadline,
                         wtok=None):
        graced = False
        with self._cond:
            while True:
                ent = self._inflight.get(seq)
                if ent is None:
                    raise self._failure or CompiledDagError(
                        "result expired from the compiled DAG buffer",
                        cause="buffer eviction")
                if slot[0] == "drv":
                    if slot[1] in ent["drv"]:
                        value, idx = ent["drv"][slot[1]], None
                        break
                else:
                    ch_id = slot[1]
                    if ch_id in ent["ch"]:
                        value, idx = ent["ch"][ch_id], slot[2]
                        break
                if self.dead:
                    raise self._failure
                remaining = None if deadline is None \
                    else deadline - time.time()
                if remaining is not None and remaining <= 0:
                    raise GetTimeoutError(
                        f"compiled DAG result (seq {seq}) not ready "
                        f"within {timeout}s")
                if wtok is not None and not wtok[0]:
                    # First sleep slice goes un-parked (grace): the
                    # common case is a pipelined result that settles
                    # within microseconds of the fetch.
                    if not graced:
                        graced = True
                        self._cond.wait(
                            timeout=waits_mod.PARK_GRACE_S
                            if remaining is None
                            else min(waits_mod.PARK_GRACE_S,
                                     remaining))
                        continue
                    wtok[0] = waits_mod.park(
                        "dag-channel", self.dag_id, op="slot",
                        seq=seq, waiter="driver")
                self._cond.wait(timeout=remaining
                                if remaining is not None else 1.0)
        if isinstance(value, BaseException):
            raise value
        if idx is not None:
            try:
                return value[idx]
            except (TypeError, IndexError, KeyError) as e:
                raise TaskError(
                    f"terminal stage declared num_returns but returned "
                    f"a non-indexable value: {e!r}") from e
        return value
