"""Scheduling strategies for tasks/actors.

Reference parity: python/ray/util/scheduling_strategies.py —
NodeAffinitySchedulingStrategy (pin to / prefer a node) and
PlacementGroupSchedulingStrategy (schedule into a bundle), plus the
"DEFAULT" / "SPREAD" string strategies. The dispatcher honors these in
`runtime._schedule` (hard affinity fails fast when the target node is
dead; soft affinity degrades to any node; SPREAD round-robins tasks
across nodes, best-effort, instead of driver-first packing).
"""
from __future__ import annotations

import dataclasses
from typing import Any, List, Optional


@dataclasses.dataclass
class NodeAffinitySchedulingStrategy:
    node_id: str
    soft: bool = False


@dataclasses.dataclass
class PlacementGroupSchedulingStrategy:
    placement_group: Any
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


def strategy_plan(strategy, pg_allowed: List[str]):
    """Turn a scheduling_strategy into an ordered list of allowed-node
    constraints to try (each a list of node ids; [] = unconstrained) plus
    a spread flag. Returns (tries, spread). A placement-group constraint
    (pg_allowed non-empty) wins outright — mirrors the reference, where a
    bundle pin overrides other strategies."""
    if pg_allowed:
        return [pg_allowed], False
    if strategy is None or strategy == "DEFAULT":
        return [[]], False
    if strategy == "SPREAD":
        return [[]], True
    if isinstance(strategy, NodeAffinitySchedulingStrategy):
        if strategy.soft:
            return [[strategy.node_id], []], False
        return [[strategy.node_id]], False
    # Unknown strategy objects degrade to DEFAULT rather than wedging the
    # dispatcher loop.
    return [[]], False


def leaseable(spec) -> bool:
    """True when a task may ride a multi-slot worker lease (batched
    dispatch, runtime._schedule): placement must be unconstrained —
    leased slots execute wherever the lease head landed — and the task
    must be safe to re-queue without side effects on the worker pool.
    Excluded: placement groups and affinity/SPREAD strategies (their
    placement is per-task), TPU tasks (chip reservations are per-task),
    streaming generators (their item protocol is per-dispatch), and
    max_calls tasks (worker recycling counts individual dispatches)."""
    return (getattr(spec, "placement_group_id", None) is None
            and (spec.scheduling_strategy is None
                 or spec.scheduling_strategy == "DEFAULT")
            and not getattr(spec, "streaming", False)
            and getattr(spec, "max_calls", 0) == 0
            and spec.resources.get("TPU", 0) <= 0)


def node_leaseable(spec) -> bool:
    """True when a task may ride a NODE-level bulk lease (two-level
    scheduling, docs/SCHEDULING.md): everything `leaseable` requires,
    plus a deserializable payload — the driver hands the whole batch to
    the node agent sight-unseen, so a spec whose user blob failed the
    wire must stay on the per-worker path where the dispatcher's
    failure reporting sees it directly."""
    return leaseable(spec) and not getattr(spec, "wire_error", None)


def shape_key(resources) -> tuple:
    """Canonical hashable key for a resource shape — node-lease batches
    and the blocked-shape skip set group tasks by this."""
    return tuple(sorted(resources.items()))


def hard_affinity_node(strategy) -> Optional[str]:
    if (isinstance(strategy, NodeAffinitySchedulingStrategy)
            and not strategy.soft):
        return strategy.node_id
    return None


def compiled_stage_node(deps, node_of, driver_node: str) -> str:
    """Preferred node for a compiled-DAG stage (docs/DAG.md): the node
    where most of its upstream stages landed — a same-node channel is a
    shm rewrite, a cross-node one is a socket copy — falling back to
    the driver's node for roots. `node_of` maps already-placed stage
    ids to node ids; unplaced deps (shouldn't happen in topo order) are
    ignored. Ties break toward the first-listed dependency, keeping
    chains anchored where their head landed."""
    counts: dict = {}
    order: List[str] = []
    for d in deps:
        nid = node_of.get(d)
        if nid is None:
            continue
        if nid not in counts:
            order.append(nid)
        counts[nid] = counts.get(nid, 0) + 1
    if not counts:
        return driver_node
    return max(order, key=lambda n: counts[n])
