"""Autoscaler: demand-driven node/slice count policy.

Reference counterpart: python/ray/autoscaler (resource-demand scheduler
+ node launcher). In-image scope (SURVEY.md §2.1 C19): the POLICY —
bin-pack pending demands onto node types, respect min/max and
upscaling_speed, downscale idle nodes after a timeout — with no cloud
provisioner; on a TPU pod the "node type" is a slice shape (e.g. a
v5e-8 host with 8 chips).
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class NodeType:
    name: str
    resources: Dict[str, float]        # e.g. {"CPU": 8, "TPU": 8}
    min_workers: int = 0
    max_workers: int = 10


@dataclasses.dataclass
class AutoscalerConfig:
    node_types: List[NodeType]
    upscaling_speed: float = 1.0       # new nodes per existing node per round
    idle_timeout_s: float = 300.0


def _fits(avail: Dict[str, float], demand: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) >= v for k, v in demand.items())


def _subtract(avail: Dict[str, float], demand: Dict[str, float]) -> None:
    for k, v in demand.items():
        avail[k] = avail.get(k, 0.0) - v


class Autoscaler:
    """Pure policy object: feed it demands + current nodes, get a plan."""

    def __init__(self, config: AutoscalerConfig):
        self.config = config
        self._idle_since: Dict[str, float] = {}

    def bin_pack(self, demands: List[Dict[str, float]],
                 nodes_avail: List[Tuple[str, Dict[str, float]]]
                 ) -> Tuple[List[Dict[str, float]], Dict[str, int]]:
        """First-fit-decreasing pack of demands onto existing capacity,
        then onto fresh nodes. Returns (unmet_after_plan, new_nodes)."""
        avail = [dict(r) for _, r in nodes_avail]
        unmet: List[Dict[str, float]] = []
        for d in sorted(demands, key=lambda d: -sum(d.values())):
            for a in avail:
                if _fits(a, d):
                    _subtract(a, d)
                    break
            else:
                unmet.append(d)
        new_nodes: Dict[str, int] = {}
        virtual: List[Dict[str, float]] = []
        still: List[Dict[str, float]] = []
        for d in unmet:
            for a in virtual:
                if _fits(a, d):
                    _subtract(a, d)
                    break
            else:
                nt = self._best_node_type(d)
                if nt is None:
                    still.append(d)       # infeasible on any node type
                    continue
                new_nodes[nt.name] = new_nodes.get(nt.name, 0) + 1
                fresh = dict(nt.resources)
                _subtract(fresh, d)
                virtual.append(fresh)
        return still, new_nodes

    def _best_node_type(self, demand: Dict[str, float]) -> Optional[NodeType]:
        feasible = [nt for nt in self.config.node_types
                    if _fits(dict(nt.resources), demand)]
        if not feasible:
            return None
        # smallest node that fits: cheapest marginal capacity
        return min(feasible, key=lambda nt: sum(nt.resources.values()))

    def plan(self, *, demands: List[Dict[str, float]],
             nodes: List[Dict],            # {id, type, avail, used}
             now: Optional[float] = None) -> Dict:
        """One reconcile round: scale-up for unmet demand, scale-down idle.

        nodes entries: {"id": str, "type": str, "avail": {res: qty},
        "used": {res: qty}}.
        """
        now = time.time() if now is None else now
        cfg = self.config
        counts: Dict[str, int] = {}
        for n in nodes:
            counts[n["type"]] = counts.get(n["type"], 0) + 1

        infeasible, wanted = self.bin_pack(
            demands, [(n["id"], n["avail"]) for n in nodes])

        # clamp to max_workers and upscaling_speed
        launches: Dict[str, int] = {}
        for nt in cfg.node_types:
            want = wanted.get(nt.name, 0)
            have = counts.get(nt.name, 0)
            room = max(0, nt.max_workers - have)
            speed_cap = max(1, int(cfg.upscaling_speed * max(1, have)))
            launches[nt.name] = min(want, room, speed_cap)
            # honor min_workers even with zero demand
            if have + launches[nt.name] < nt.min_workers:
                launches[nt.name] = min(nt.min_workers - have, room)
        launches = {k: v for k, v in launches.items() if v > 0}

        # idle tracking + downscale candidates
        terminate: List[str] = []
        by_type = {nt.name: nt for nt in cfg.node_types}
        for n in nodes:
            busy = any(v > 0 for v in n.get("used", {}).values())
            if busy:
                self._idle_since.pop(n["id"], None)
                continue
            first_idle = self._idle_since.setdefault(n["id"], now)
            nt = by_type.get(n["type"])
            floor = nt.min_workers if nt else 0
            if (now - first_idle >= cfg.idle_timeout_s
                    and counts.get(n["type"], 0) - sum(
                        1 for t in terminate
                        if any(m["id"] == t and m["type"] == n["type"]
                               for m in nodes)) > floor):
                terminate.append(n["id"])
        return {"launch": launches, "terminate": terminate,
                "infeasible": infeasible}


def demands_from_runtime(rt) -> List[Dict[str, float]]:
    """Extract pending resource demands from a live DriverRuntime."""
    demands = []
    for spec in list(rt.pending_tasks):
        if spec.resources:
            demands.append(dict(spec.resources))
    for acspec in list(rt.pending_actors):
        if acspec.resources:
            demands.append(dict(acspec.resources))
    return demands
