"""Autoscaler: demand-driven node/slice count policy.

Reference counterpart: python/ray/autoscaler (resource-demand scheduler
+ node launcher). In-image scope (SURVEY.md §2.1 C19): the POLICY —
bin-pack pending demands onto node types, respect min/max and
upscaling_speed, downscale idle nodes after a timeout — with no cloud
provisioner; on a TPU pod the "node type" is a slice shape (e.g. a
v5e-8 host with 8 chips).
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class NodeType:
    name: str
    resources: Dict[str, float]        # e.g. {"CPU": 8, "TPU": 8}
    min_workers: int = 0
    max_workers: int = 10


@dataclasses.dataclass
class AutoscalerConfig:
    node_types: List[NodeType]
    upscaling_speed: float = 1.0       # new nodes per existing node per round
    idle_timeout_s: float = 300.0


def upscale_step(have: int, want: int, upscaling_speed: float) -> int:
    """Launches allowed this round: at most upscaling_speed * existing
    nodes (floor 1, so a cold pool can still start). Shared by the
    node-scaling plan() below and the serve replica autoscaler, which
    models replicas as nodes of a per-deployment NodeType."""
    if want <= 0:
        return 0
    return min(want, max(1, int(upscaling_speed * max(1, have))))


def _fits(avail: Dict[str, float], demand: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) >= v for k, v in demand.items())


def _subtract(avail: Dict[str, float], demand: Dict[str, float]) -> None:
    for k, v in demand.items():
        avail[k] = avail.get(k, 0.0) - v


class Autoscaler:
    """Pure policy object: feed it demands + current nodes, get a plan."""

    def __init__(self, config: AutoscalerConfig):
        self.config = config
        self._idle_since: Dict[str, float] = {}

    def bin_pack(self, demands: List[Dict[str, float]],
                 nodes_avail: List[Tuple[str, Dict[str, float]]]
                 ) -> Tuple[List[Dict[str, float]], Dict[str, int]]:
        """First-fit-decreasing pack of demands onto existing capacity,
        then onto fresh nodes. Returns (unmet_after_plan, new_nodes)."""
        avail = [dict(r) for _, r in nodes_avail]
        unmet: List[Dict[str, float]] = []
        for d in sorted(demands, key=lambda d: -sum(d.values())):
            for a in avail:
                if _fits(a, d):
                    _subtract(a, d)
                    break
            else:
                unmet.append(d)
        new_nodes: Dict[str, int] = {}
        virtual: List[Dict[str, float]] = []
        still: List[Dict[str, float]] = []
        for d in unmet:
            for a in virtual:
                if _fits(a, d):
                    _subtract(a, d)
                    break
            else:
                nt = self._best_node_type(d)
                if nt is None:
                    still.append(d)       # infeasible on any node type
                    continue
                new_nodes[nt.name] = new_nodes.get(nt.name, 0) + 1
                fresh = dict(nt.resources)
                _subtract(fresh, d)
                virtual.append(fresh)
        return still, new_nodes

    def _best_node_type(self, demand: Dict[str, float]) -> Optional[NodeType]:
        feasible = [nt for nt in self.config.node_types
                    if _fits(dict(nt.resources), demand)]
        if not feasible:
            return None
        # smallest node that fits: cheapest marginal capacity
        return min(feasible, key=lambda nt: sum(nt.resources.values()))

    def plan(self, *, demands: List[Dict[str, float]],
             nodes: List[Dict],            # {id, type, avail, used}
             now: Optional[float] = None) -> Dict:
        """One reconcile round: scale-up for unmet demand, scale-down idle.

        nodes entries: {"id": str, "type": str, "avail": {res: qty},
        "used": {res: qty}}.
        """
        now = time.time() if now is None else now
        cfg = self.config
        counts: Dict[str, int] = {}
        for n in nodes:
            counts[n["type"]] = counts.get(n["type"], 0) + 1

        infeasible, wanted = self.bin_pack(
            demands, [(n["id"], n["avail"]) for n in nodes])

        # clamp to max_workers and upscaling_speed
        launches: Dict[str, int] = {}
        for nt in cfg.node_types:
            want = wanted.get(nt.name, 0)
            have = counts.get(nt.name, 0)
            room = max(0, nt.max_workers - have)
            launches[nt.name] = min(
                upscale_step(have, want, cfg.upscaling_speed), room)
            # honor min_workers even with zero demand
            if have + launches[nt.name] < nt.min_workers:
                launches[nt.name] = min(nt.min_workers - have, room)
        launches = {k: v for k, v in launches.items() if v > 0}

        # idle tracking + downscale candidates
        terminate: List[str] = []
        by_type = {nt.name: nt for nt in cfg.node_types}
        for n in nodes:
            busy = any(v > 0 for v in n.get("used", {}).values())
            if busy:
                self._idle_since.pop(n["id"], None)
                continue
            first_idle = self._idle_since.setdefault(n["id"], now)
            nt = by_type.get(n["type"])
            floor = nt.min_workers if nt else 0
            if (now - first_idle >= cfg.idle_timeout_s
                    and counts.get(n["type"], 0) - sum(
                        1 for t in terminate
                        if any(m["id"] == t and m["type"] == n["type"]
                               for m in nodes)) > floor):
                terminate.append(n["id"])
        return {"launch": launches, "terminate": terminate,
                "infeasible": infeasible}


def demands_from_runtime(rt) -> List[Dict[str, float]]:
    """Extract pending resource demands from a live DriverRuntime."""
    demands = []
    for spec in list(rt.pending_tasks):
        if spec.resources:
            demands.append(dict(spec.resources))
    for acspec in list(rt.pending_actors):
        if acspec.resources:
            demands.append(dict(acspec.resources))
    return demands


# ---------------------------------------------------------------------------
# Live autoscaling: a provider that actually launches/terminates node
# agents, and a reconcile loop driving the policy against a DriverRuntime.
# Reference counterpart: python/ray/autoscaler/_private/autoscaler.py
# (StandardAutoscaler) + node_launcher.py; cloud provisioners are out of
# scope — LocalNodeProvider stands in by spawning agent subprocesses,
# which is also exactly how a TPU-pod deployment adds a host.
# ---------------------------------------------------------------------------

class NodeProvider:
    """Launch/terminate nodes of a NodeType. Implementations map a
    provider-side handle to the runtime node id (they pre-choose it)."""

    def launch(self, node_type: NodeType) -> str:
        raise NotImplementedError

    def terminate(self, node_id: str) -> None:
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


class LocalNodeProvider(NodeProvider):
    """Spawns `python -m ray_tpu.core.node` subprocesses against the
    driver's TCP address, pre-assigning each node id so the autoscaler
    can track its launches through the GCS node table."""

    def __init__(self, driver_address: str):
        import subprocess  # noqa: PLC0415
        self._subprocess = subprocess
        self.driver_address = driver_address
        self.procs: Dict[str, "object"] = {}

    def launch(self, node_type: NodeType) -> str:
        import json as _json  # noqa: PLC0415
        import os  # noqa: PLC0415
        import sys  # noqa: PLC0415
        from .ids import new_node_id  # noqa: PLC0415
        node_id = new_node_id()
        res = dict(node_type.resources)
        cpus = int(res.pop("CPU", 1))
        tpus = int(res.pop("TPU", 0))
        env = dict(os.environ)
        env["RAY_TPU_NODE_TYPE"] = node_type.name
        if tpus:
            env["RAY_TPU_CHIPS"] = str(tpus)
        else:
            # CPU-only node types stay off the TPU plugin; TPU node
            # types keep the real backend (their tpu_capable workers
            # must see the chips).
            from ..util.jaxenv import subprocess_env_cpu  # noqa: PLC0415
            subprocess_env_cpu(env)
        cmd = [sys.executable, "-m", "ray_tpu.core.node",
               self.driver_address, "--num-cpus", str(cpus),
               "--node-id", node_id]
        if tpus:
            cmd += ["--num-tpus", str(tpus)]
        if res:
            cmd += ["--resources", _json.dumps(res)]
        self.procs[node_id] = self._subprocess.Popen(cmd, env=env)
        return node_id

    def terminate(self, node_id: str) -> None:
        proc = self.procs.pop(node_id, None)
        if proc is not None:
            try:
                proc.terminate()
            except Exception:
                pass
            import threading  # noqa: PLC0415

            def reap(proc=proc):
                try:
                    proc.wait(timeout=5)
                except Exception:
                    try:
                        proc.kill()
                        proc.wait(timeout=5)
                    except Exception:
                        pass
            threading.Thread(target=reap, daemon=True).start()

    def alive(self, node_id: str) -> bool:
        """True while the launched agent process is running (poll() also
        reaps exited children so they never zombie)."""
        proc = self.procs.get(node_id)
        if proc is None:
            return False
        if proc.poll() is not None:
            self.procs.pop(node_id, None)
            return False
        return True

    def shutdown(self) -> None:
        for nid in list(self.procs):
            self.terminate(nid)


class StandardAutoscaler:
    """Reconcile loop: pending demand -> policy plan -> provider actions.

    Scales the cluster while the runtime schedules onto whatever nodes
    exist; the driver node itself is never terminated."""

    def __init__(self, rt, config: AutoscalerConfig,
                 provider: NodeProvider, *, interval_s: float = 2.0):
        import threading  # noqa: PLC0415
        self.rt = rt
        self.policy = Autoscaler(config)
        self.provider = provider
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._launched: Dict[str, str] = {}   # node_id -> type name
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="rtpu-autoscaler")
        self._thread.start()

    def _node_views(self) -> List[Dict]:
        views = []
        for ns in list(self.rt.cluster_nodes.values()):
            if not ns.alive or ns.node_id == self.rt.node_id:
                continue  # the driver host is not scalable inventory
            ntype = (self._launched.get(ns.node_id)
                     or ns.labels.get("node-type", "unknown"))
            used = {k: ns.total.get(k, 0.0) - ns.avail.get(k, 0.0)
                    for k in ns.total}
            views.append({"id": ns.node_id, "type": ntype,
                          "avail": dict(ns.avail),
                          "used": {k: v for k, v in used.items()
                                   if v > 1e-9}})
        return views

    def reconcile_once(self) -> Dict:
        demands = demands_from_runtime(self.rt)
        # A launch whose process died before registering is evicted so
        # the next tick relaunches for its demand (otherwise it would be
        # phantom in-flight capacity forever).
        alive = getattr(self.provider, "alive", None)
        if alive is not None:
            for nid in list(self._launched):
                if nid not in self.rt.cluster_nodes and not alive(nid):
                    self._launched.pop(nid, None)
        # launches still registering count as capacity-in-flight: without
        # this, every tick would relaunch for the same unmet demand.
        pending_types = [t for nid, t in self._launched.items()
                         if nid not in self.rt.cluster_nodes]
        by_name = {nt.name: nt for nt in self.policy.config.node_types}
        views = self._node_views()
        for i, tname in enumerate(pending_types):
            nt = by_name.get(tname)
            if nt is not None:
                views.append({"id": f"__pending_{i}", "type": tname,
                              "avail": dict(nt.resources),
                              "used": {"CPU": 1e-6}})  # never idle-reaped
        plan = self.policy.plan(demands=demands, nodes=views)
        for tname, count in plan["launch"].items():
            nt = by_name[tname]
            for _ in range(count):
                nid = self.provider.launch(nt)
                self._launched[nid] = tname
        for nid in plan["terminate"]:
            if nid.startswith("__pending_"):
                continue
            self.provider.terminate(nid)
            self._launched.pop(nid, None)
        return plan

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.reconcile_once()
            except Exception:
                import traceback  # noqa: PLC0415
                traceback.print_exc()
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
