"""Worker-local device-resident object store (compiled-DAG channels).

Reference parity: python/ray/experimental/channel/shared_memory_channel.py
+ torch_tensor_nccl_channel.py — the reference's accelerated DAG moves
tensors actor->actor through typed channels without host round-trips.
TPU-first re-design (VERDICT r4 missing #2): a task/actor return whose
value contains live `jax.Array`s stays DEVICE-RESIDENT in the producing
worker process; the ObjectRef's location is a lightweight device handle
(kind="device", name=<worker_id>). Consumers on the SAME worker (actor
method chains, locality-scheduled DAG stages) read the live value out of
this table — no device->host copy, no serialization, no shm traffic.
Only when a consumer elsewhere (another worker, or the driver) actually
gets the object does the holder materialize it to the shm store, via the
normal serialization path.

Single-controller nuance: on this image the TPU tunnel admits ONE
process, so cross-process device handoff is impossible by construction —
same-process reuse IS the whole win, and it is exactly what compiled
DAGs with actor reuse produce.

The table is process-local; COUNTERS make transfer behavior testable
(tests assert device_hits == n_intermediate_edges, materialized == n_
final_reads).

Contract: a same-worker consumer receives the LIVE object, not a copy —
the same read-only discipline as the shm path's zero-copy numpy views.
jax.Arrays are functionally immutable so the sharp edge is only mutable
containers around them (don't mutate a value you returned from a task)
and explicit buffer donation/deletion of an array something else may
still reference. Once an object materializes (a consumer elsewhere read
it), the device entry is dropped — the host copy becomes the single
source of truth and HBM is reclaimed.
"""
from __future__ import annotations

import sys
import threading
from typing import Any, Dict, Optional

from ..util import knobs

# kept-resident returns / local-table dep reads / D2H serializations
COUNTERS = {"kept_device": 0, "device_hits": 0, "materialized": 0}

_TABLE: Dict[str, Any] = {}
_LOCK = threading.Lock()

# Bound the number of live device values a worker pins (each holds HBM
# until consumed/freed/materialized). A full table does NOT evict —
# new values simply refuse residency and serialize through the normal
# shm path until frees/materializations make room.
MAX_ENTRIES = knobs.get_int("RAY_TPU_DEVICE_OBJECTS_MAX")


def enabled() -> bool:
    return knobs.get_bool("RAY_TPU_DEVICE_OBJECTS")


def should_keep(value: Any) -> bool:
    """Keep device-resident iff jax is already loaded in this process
    and the value contains at least one jax.Array leaf. Never imports
    jax into a worker that wasn't using it."""
    if not enabled():
        return False
    jax = sys.modules.get("jax")
    if jax is None:
        return False
    with _LOCK:
        if len(_TABLE) >= MAX_ENTRIES:
            return False
    try:
        return any(isinstance(leaf, jax.Array)
                   for leaf in jax.tree_util.tree_leaves(value))
    except Exception:  # exotic non-pytree values: serialize normally
        return False


def put(oid: str, value: Any) -> None:
    with _LOCK:
        _TABLE[oid] = value
    COUNTERS["kept_device"] += 1


def try_keep(store, worker_id: str, oid: str, value: Any):
    """The ONE seal-or-keep decision shared by task returns and
    worker-side api.put: keep device-resident when policy allows,
    else serialize into the shm store. Returns the ObjectLocation."""
    from .object_store import ObjectLocation, current_node_id  # noqa: PLC0415
    from .spilling import put_value_or_spill  # noqa: PLC0415
    if should_keep(value):
        put(oid, value)
        return ObjectLocation(kind="device", size=0, name=worker_id,
                              node_id=current_node_id())
    return put_value_or_spill(store, oid, value)


def get(oid: str) -> Any:
    """Raises KeyError when not resident here."""
    with _LOCK:
        value = _TABLE[oid]
    COUNTERS["device_hits"] += 1
    return value


def contains(oid: str) -> bool:
    with _LOCK:
        return oid in _TABLE


def peek(oid: str) -> Optional[Any]:
    """No-counter read for the materialization path."""
    with _LOCK:
        return _TABLE.get(oid)


def drop(oid: str) -> None:
    with _LOCK:
        _TABLE.pop(oid, None)


def clear() -> None:
    with _LOCK:
        _TABLE.clear()
    COUNTERS.update({"kept_device": 0, "device_hits": 0,
                     "materialized": 0})
