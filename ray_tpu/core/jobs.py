"""Job submission: run driver scripts under the cluster's supervision.

Reference counterpart: python/ray/job_submission (JobSubmissionClient:
submit_job/stop_job/get_job_status/get_job_logs/tail_job_logs) and
dashboard job manager. Local scope (SURVEY.md §2.8 O9): the entrypoint
runs as a subprocess with captured logs; runtime_env env_vars/working_dir
apply to it.
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import tempfile
import time
import uuid
from typing import Any, Dict, Iterator, List, Optional


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"


class _Job:
    def __init__(self, submission_id: str, entrypoint: str,
                 proc: subprocess.Popen, log_path: str, metadata):
        self.submission_id = submission_id
        self.entrypoint = entrypoint
        self.proc = proc
        self.log_path = log_path
        self.metadata = metadata or {}
        self.start_time = time.time()
        self.end_time: Optional[float] = None
        self.stopped = False

    def status(self) -> str:
        rc = self.proc.poll()
        if rc is None:
            return JobStatus.RUNNING
        if self.end_time is None:
            self.end_time = time.time()
        if self.stopped:
            return JobStatus.STOPPED
        return JobStatus.SUCCEEDED if rc == 0 else JobStatus.FAILED


class JobSubmissionClient:
    """Reference-parity client (python/ray/job_submission). Two modes:

    * local (address=None): jobs run as subprocesses of THIS process.
    * HTTP (address="http://host:port"): every call proxies to a
      dashboard's /api/jobs endpoints (observability/dashboard.py), the
      way the reference client talks to the dashboard job head — submit
      from any process, logs stream back over chunked HTTP.
    """

    def __init__(self, address: Optional[str] = None,
                 log_dir: Optional[str] = None):
        self._address = (address.rstrip("/")
                         if address and address.startswith("http")
                         else None)
        self._jobs: Dict[str, _Job] = {}
        self._log_dir = log_dir or tempfile.mkdtemp(prefix="ray_tpu_jobs_")

    # ---- HTTP proxy plumbing ----
    def _http(self, route: str, payload=None, timeout: float = 30.0):
        import json as json_mod
        import urllib.request
        req = urllib.request.Request(
            self._address + route,
            data=(json_mod.dumps(payload).encode()
                  if payload is not None else None),
            headers={"Content-Type": "application/json"},
            method="POST" if payload is not None else "GET")
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                out = json_mod.loads(r.read())
        except urllib.error.HTTPError as e:
            try:
                msg = json_mod.loads(e.read()).get("error", str(e))
            except Exception:  # noqa: BLE001
                msg = str(e)
            raise ValueError(msg) from None
        if isinstance(out, dict) and "error" in out:
            raise ValueError(out["error"])
        return out

    def submit_job(self, *, entrypoint: str,
                   runtime_env: Optional[Dict[str, Any]] = None,
                   submission_id: Optional[str] = None,
                   metadata: Optional[Dict[str, str]] = None) -> str:
        if self._address:
            return self._http("/api/jobs", {
                "entrypoint": entrypoint, "runtime_env": runtime_env,
                "submission_id": submission_id,
                "metadata": metadata})["submission_id"]
        from . import runtime_env as renv_mod
        renv = renv_mod.validate(runtime_env)
        sid = submission_id or f"raytpu-job-{uuid.uuid4().hex[:10]}"
        if sid in self._jobs:
            raise ValueError(f"submission_id {sid!r} already used")
        env = dict(os.environ)
        env.update(renv.get("env_vars", {}))
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = os.pathsep.join(
            [repo_root, *(renv.get("py_modules") or []),
             *[p for p in env.get("PYTHONPATH", "").split(os.pathsep)
               if p]])
        cwd = renv.get("working_dir") or os.getcwd()
        log_path = os.path.join(self._log_dir, f"{sid}.log")
        logf = open(log_path, "wb")
        proc = subprocess.Popen(
            entrypoint, shell=True, cwd=cwd, env=env,
            stdout=logf, stderr=subprocess.STDOUT,
            start_new_session=True)   # own pgid: stop_job kills the tree
        logf.close()
        self._jobs[sid] = _Job(sid, entrypoint, proc, log_path, metadata)
        return sid

    def _job(self, sid: str) -> _Job:
        if sid not in self._jobs:
            raise ValueError(f"unknown job {sid!r}")
        return self._jobs[sid]

    def get_job_status(self, submission_id: str) -> str:
        if self._address:
            return self.get_job_info(submission_id)["status"]
        return self._job(submission_id).status()

    def get_job_info(self, submission_id: str) -> Dict[str, Any]:
        if self._address:
            return self._http(f"/api/jobs/{submission_id}")
        j = self._job(submission_id)
        return {"submission_id": j.submission_id, "status": j.status(),
                "entrypoint": j.entrypoint, "metadata": j.metadata,
                "start_time": j.start_time, "end_time": j.end_time}

    def list_jobs(self) -> List[Dict[str, Any]]:
        if self._address:
            return self._http("/api/jobs")
        return [self.get_job_info(sid) for sid in self._jobs]

    def get_job_logs(self, submission_id: str) -> str:
        if self._address:
            return self._http(f"/api/jobs/{submission_id}/logs")["logs"]
        j = self._job(submission_id)
        try:
            with open(j.log_path, "rb") as f:
                return f.read().decode(errors="replace")
        except OSError:
            return ""

    def tail_job_logs(self, submission_id: str,
                      poll_interval_s: float = 0.1) -> Iterator[str]:
        if self._address:
            yield from self._tail_http(submission_id)
            return
        j = self._job(submission_id)
        pos = 0
        while True:
            with open(j.log_path, "rb") as f:
                f.seek(pos)
                chunk = f.read()
                pos = f.tell()
            if chunk:
                yield chunk.decode(errors="replace")
            elif j.status() != JobStatus.RUNNING:
                return
            else:
                time.sleep(poll_interval_s)

    def _tail_http(self, submission_id: str) -> Iterator[str]:
        """Stream the dashboard's chunked follow endpoint until EOF."""
        import codecs
        import urllib.request
        url = (f"{self._address}/api/jobs/{submission_id}/logs"
               f"?follow=1")
        # incremental decoder: a multi-byte UTF-8 char split across
        # read1 chunks must not turn into replacement garbage
        dec = codecs.getincrementaldecoder("utf-8")(errors="replace")
        try:
            with urllib.request.urlopen(url, timeout=None) as r:
                while True:
                    piece = r.read1(65536)
                    if not piece:
                        tail = dec.decode(b"", final=True)
                        if tail:
                            yield tail
                        return
                    text = dec.decode(piece)
                    if text:
                        yield text
        except urllib.error.HTTPError as e:
            raise ValueError(f"tail failed: {e}") from None

    def stop_job(self, submission_id: str) -> bool:
        if self._address:
            return self._http(f"/api/jobs/{submission_id}/stop",
                              {})["stopped"]
        j = self._job(submission_id)
        if j.proc.poll() is not None:
            return False
        j.stopped = True
        try:
            os.killpg(j.proc.pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            j.proc.terminate()
        try:
            j.proc.wait(timeout=3.0)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(j.proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                j.proc.kill()
        return True

    def wait_until_finished(self, submission_id: str,
                            timeout: float = 300.0) -> str:
        deadline = time.time() + timeout
        while time.time() < deadline:
            st = self.get_job_status(submission_id)
            if st not in (JobStatus.PENDING, JobStatus.RUNNING):
                return st
            time.sleep(0.05)
        raise TimeoutError(f"job {submission_id} still running "
                           f"after {timeout}s")
