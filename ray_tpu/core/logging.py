"""Worker log capture + driver streaming.

Reference counterpart: python/ray/_private/ray_logging — per-worker log
files under the session dir, with `log_to_driver=True` tailing them into
the driver's stdout prefixed `(worker_id pid)` the way `(raylet)` /
`(pid=...)` prefixes work in the reference.

Capture is fd-level (dup2), so C/C++ native prints (XLA, the shm arena)
land in the file too, not just Python's sys.stdout.
"""
from __future__ import annotations

import os
import sys
import threading
import time
from typing import Dict, Optional


def redirect_process_output(log_path: str) -> None:
    """In the worker: point fd 1/2 at log_path (line-buffered)."""
    os.makedirs(os.path.dirname(log_path), exist_ok=True)
    fd = os.open(log_path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    sys.stdout.flush()
    sys.stderr.flush()
    os.dup2(fd, 1)
    os.dup2(fd, 2)
    os.close(fd)
    # rebind the Python-level streams to the new fds, line-buffered
    sys.stdout = os.fdopen(1, "w", buffering=1, closefd=False)
    sys.stderr = os.fdopen(2, "w", buffering=1, closefd=False)


class LogStreamer:
    """In the driver: tail every worker log file, prefix, and echo."""

    def __init__(self, log_dir: str, *, out=None, poll_interval_s: float = 0.2):
        self.log_dir = log_dir
        self.out = out or sys.stdout
        self.poll_interval_s = poll_interval_s
        self._pos: Dict[str, int] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="rtpu-log-stream")
        self._thread.start()

    def _emit(self, fname: str, chunk: str) -> None:
        label = fname.rsplit(".", 1)[0]          # worker-w0001
        for line in chunk.splitlines():
            if line.strip():
                self.out.write(f"({label}) {line}\n")
        try:
            self.out.flush()
        except Exception:
            pass

    def _scan_once(self, final: bool = False) -> None:
        try:
            names = sorted(os.listdir(self.log_dir))
        except OSError:
            return
        for fname in names:
            if not fname.endswith(".log"):
                continue
            path = os.path.join(self.log_dir, fname)
            pos = self._pos.get(fname, 0)
            try:
                with open(path, "rb") as f:
                    f.seek(pos)
                    raw = f.read()
            except OSError:
                continue
            if not raw:
                continue
            # consume only whole lines so a poll landing mid-write never
            # splits a line (or a multi-byte char); the final drain takes
            # whatever remains.
            cut = len(raw) if final else raw.rfind(b"\n") + 1
            if cut <= 0:
                continue
            self._pos[fname] = pos + cut
            self._emit(fname, raw[:cut].decode("utf-8", errors="replace"))

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            self._scan_once()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)   # no concurrent scans
        self._scan_once(final=True)      # drain, including partial lines
