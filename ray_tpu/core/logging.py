"""Worker log capture + driver streaming + per-task attribution.

Reference counterpart: python/ray/_private/ray_logging — per-worker log
files under the session dir, with `log_to_driver=True` tailing them into
the driver's stdout prefixed `(worker_id pid)` the way `(raylet)` /
`(pid=...)` prefixes work in the reference.

Capture is fd-level (dup2), so C/C++ native prints (XLA, the shm arena)
land in the file too, not just Python's sys.stdout.

Per-task attribution (failure forensics): the worker writes a marker
line straight to fd 1 whenever the currently-executing task changes, so
every captured line between two markers belongs to that task — native
prints included, since everything shares the one appended fd. The
driver side strips markers from the echoed stream (tagging the prefix
instead) and `task_log_tail()` reassembles one task's lines for
post-mortem bundles. With actor max_concurrency > 1 several tasks share
the process; attribution is then last-marker-wins (best effort, same as
the reference's out-of-band prints).
"""
from __future__ import annotations

import os
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..util import knobs

# Marker line format: TASK_MARKER<task_id or "-">TASK_MARKER_END + "\n".
# Chosen to never collide with ordinary output and to survive
# line-splitting readers (always written as one whole line).
TASK_MARKER = "::ray_tpu::task::"
TASK_MARKER_END = "::"

_redirected = False
_marker_lock = threading.Lock()


def redirect_process_output(log_path: str) -> None:
    """In the worker: point fd 1/2 at log_path (line-buffered)."""
    global _redirected
    os.makedirs(os.path.dirname(log_path), exist_ok=True)
    fd = os.open(log_path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    sys.stdout.flush()
    sys.stderr.flush()
    os.dup2(fd, 1)
    os.dup2(fd, 2)
    os.close(fd)
    # rebind the Python-level streams to the new fds, line-buffered
    sys.stdout = os.fdopen(1, "w", buffering=1, closefd=False)
    sys.stderr = os.fdopen(2, "w", buffering=1, closefd=False)
    _redirected = True


def mark_current_task(task_id: Optional[str]) -> None:
    """Stamp the log with the task now executing (None = idle). A raw
    os.write to fd 1 keeps ordering with both Python prints (flushed
    first) and native writes, which share the O_APPEND fd. No-op when
    output was never redirected (interactive worker: no file to tag)."""
    try:
        # the sampling profiler shares the task markers: tell it which
        # task now owns this thread BEFORE the redirect check, so
        # attribution works even in interactive (unredirected) workers
        from ..observability import sampling_profiler  # noqa: PLC0415
        sampling_profiler.mark_thread(task_id)
    except Exception:
        pass
    if not _redirected:
        return
    try:
        with _marker_lock:
            sys.stdout.flush()
            sys.stderr.flush()
            os.write(1, (f"{TASK_MARKER}{task_id or '-'}"
                         f"{TASK_MARKER_END}\n").encode())
    except Exception:
        pass  # attribution must never fail user work


def parse_marker(line: str) -> Optional[Optional[str]]:
    """task_id if `line` is a marker ("-" -> None idle marker);
    a non-marker line returns the sentinel string "__not_marker__"."""
    s = line.strip()
    if s.startswith(TASK_MARKER) and s.endswith(TASK_MARKER_END):
        tid = s[len(TASK_MARKER):-len(TASK_MARKER_END)]
        return None if tid == "-" else tid
    return "__not_marker__"


def attribute_lines(text: str, current: Optional[str] = None
                    ) -> Tuple[List[Tuple[Optional[str], str]],
                               Optional[str]]:
    """Split captured text into (task_id, line) pairs, threading the
    marker state; returns (pairs, final_current) so a tailing caller
    can carry attribution across chunks."""
    pairs: List[Tuple[Optional[str], str]] = []
    for line in text.splitlines():
        mk = parse_marker(line)
        if mk != "__not_marker__":
            current = mk
            continue
        pairs.append((current, line))
    return pairs, current


# Only the newest max_lines survive a tail query, so reading a whole
# multi-GB worker log to answer one would be pure waste — read at most
# this many trailing bytes per file. A task whose attribution marker
# fell before the window loses its oldest lines (best effort, same as
# any tail).
TAIL_READ_BYTES = knobs.get_int("RAY_TPU_LOG_TAIL_BYTES")


def read_log_tail(path: str,
                  max_bytes: int = 0) -> str:
    """The trailing `max_bytes` (default TAIL_READ_BYTES) of a log
    file, starting at a whole line."""
    max_bytes = max_bytes or TAIL_READ_BYTES
    with open(path, "rb") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        f.seek(max(0, size - max_bytes))
        raw = f.read()
    if size > max_bytes:
        # drop the (possibly split) first line of the window
        cut = raw.find(b"\n") + 1
        raw = raw[cut:]
    return raw.decode("utf-8", errors="replace")


def task_log_tail(log_dir: str, task_id: str,
                  max_lines: int = 200) -> List[Dict[str, str]]:
    """The tail of every captured line attributed to `task_id` across
    this node's worker log files (newest last), for post-mortem
    bundles: [{"worker": "worker-w0001", "line": ...}, ...]."""
    out: List[Dict[str, str]] = []
    try:
        names = sorted(os.listdir(log_dir))
    except OSError:
        return out
    for fname in names:
        if not fname.endswith(".log"):
            continue
        try:
            text = read_log_tail(os.path.join(log_dir, fname))
        except OSError:
            continue
        for tid, line in attribute_lines(text)[0]:
            if tid == task_id and line.strip():
                out.append({"worker": fname.rsplit(".", 1)[0],
                            "line": line})
    return out[-max_lines:]


class LogStreamer:
    """In the driver: tail every worker log file, prefix, and echo.
    Marker lines are consumed (not echoed); while a task is attributed
    to a file, its lines stream prefixed `(worker-wNNNN task=<id>)`."""

    def __init__(self, log_dir: str, *, out=None, poll_interval_s: float = 0.2):
        self.log_dir = log_dir
        self.out = out or sys.stdout
        self.poll_interval_s = poll_interval_s
        self._pos: Dict[str, int] = {}
        self._task: Dict[str, Optional[str]] = {}   # fname -> current task
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="rtpu-log-stream")
        self._thread.start()

    def _emit(self, fname: str, chunk: str) -> None:
        label = fname.rsplit(".", 1)[0]          # worker-w0001
        pairs, self._task[fname] = attribute_lines(
            chunk, self._task.get(fname))
        for tid, line in pairs:
            if not line.strip():
                continue
            if tid:
                self.out.write(f"({label} task={tid}) {line}\n")
            else:
                self.out.write(f"({label}) {line}\n")
        try:
            self.out.flush()
        except Exception:
            pass

    def _scan_once(self, final: bool = False) -> None:
        try:
            names = sorted(os.listdir(self.log_dir))
        except OSError:
            return
        for fname in names:
            if not fname.endswith(".log"):
                continue
            path = os.path.join(self.log_dir, fname)
            pos = self._pos.get(fname, 0)
            try:
                with open(path, "rb") as f:
                    f.seek(pos)
                    raw = f.read()
            except OSError:
                continue
            if not raw:
                continue
            # consume only whole lines so a poll landing mid-write never
            # splits a line (or a multi-byte char); the final drain takes
            # whatever remains.
            cut = len(raw) if final else raw.rfind(b"\n") + 1
            if cut <= 0:
                continue
            self._pos[fname] = pos + cut
            self._emit(fname, raw[:cut].decode("utf-8", errors="replace"))

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            self._scan_once()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)   # no concurrent scans
        self._scan_once(final=True)      # drain, including partial lines
