"""Resource model with TPU as a first-class accelerator.

Reference parity: python/ray/_private/resource_spec.py and
python/ray/_private/accelerators/tpu.py (TPU pod/slice detection at
:198, pod-type resources at :276-319). TPU chips are native schedulable
resources ("TPU"); a node belonging to a pod slice additionally carries
topology labels (pod type, slice name, worker index, chips per host) and
— on the slice's worker 0 — the "TPU-<pod_type>-head" gang resource, so
a whole slice can be claimed by scheduling one head task/actor and
fanning out over the slice's nodes (the reference's multi-host gang
idiom).
"""
from __future__ import annotations

import os
from typing import Dict, Optional

from ..util import knobs

TPU_HEAD_FMT = "TPU-{pod_type}-head"


def detect_node_resources(num_cpus: Optional[int] = None,
                          num_tpus: Optional[int] = None) -> Dict[str, float]:
    if num_cpus is None:
        num_cpus = os.cpu_count() or 1
        # The runtime itself needs headroom; still expose at least 4 virtual
        # CPU slots so task-parallel libraries (data/tune) can overlap work —
        # CPUs in ray (and here) are scheduling tokens, not pinned cores.
        num_cpus = max(num_cpus, 4)
    res: Dict[str, float] = {"CPU": float(num_cpus)}
    if num_tpus is None:
        num_tpus = _detect_tpu_chips()
    if num_tpus:
        res["TPU"] = float(num_tpus)
    res["memory"] = float(_detect_memory_bytes())
    topo = detect_tpu_topology(num_tpus)
    if topo.get("tpu-pod-type"):
        # One gang resource per slice, held by the slice's first worker:
        # scheduling {TPU-<pod>-head: 1} lands exactly one controller task
        # on each slice (ref accelerators/tpu.py:276-319).
        if int(topo.get("tpu-worker-id", "0") or 0) == 0:
            res[TPU_HEAD_FMT.format(pod_type=topo["tpu-pod-type"])] = 1.0
    return res


def detect_tpu_topology(num_chips: Optional[int] = None) -> Dict[str, str]:
    """Slice/pod topology labels from the environment.

    Mirrors the reference's TPU pod detection from TPU-VM metadata/env
    (accelerators/tpu.py:198): on a real TPU VM, the runtime publishes
    accelerator type (e.g. "v5e-8"), the slice/pod name, and this host's
    worker index within the slice. Here they come from env so a pod can
    also be modeled in tests.
    """
    labels: Dict[str, str] = {}
    pod_type = (knobs.get_raw("RAY_TPU_POD_TYPE")
                or os.environ.get("TPU_ACCELERATOR_TYPE", ""))
    if pod_type:
        labels["tpu-pod-type"] = pod_type
    slice_name = (knobs.get_raw("RAY_TPU_SLICE")
                  or os.environ.get("TPU_NAME", ""))
    if slice_name:
        labels["tpu-slice"] = slice_name
    worker_id = (knobs.get_raw("RAY_TPU_WORKER_ID")
                 or os.environ.get("TPU_WORKER_ID", ""))
    if worker_id:
        labels["tpu-worker-id"] = worker_id
    if num_chips is None:
        num_chips = _detect_tpu_chips()
    if num_chips and labels:
        labels["tpu-chips-per-host"] = str(num_chips)
    return labels


def _detect_tpu_chips() -> int:
    # Avoid importing jax here (heavy, and workers may be CPU-only); trust
    # the environment first, mirroring reference TPU detection via env/
    # metadata (python/ray/_private/accelerators/tpu.py).
    env = knobs.get_int("RAY_TPU_CHIPS")
    if env is not None:   # 0 is a real override: force chipless
        return env
    try:
        import jax  # noqa: PLC0415
        return sum(1 for d in jax.devices() if d.platform == "tpu")
    except Exception:
        return 0


def _detect_memory_bytes() -> int:
    try:
        import psutil  # noqa: PLC0415
        return int(psutil.virtual_memory().total * 0.7)
    except Exception:
        return 8 << 30


def fits(avail: Dict[str, float], req: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in req.items() if v > 0)


def acquire(avail: Dict[str, float], req: Dict[str, float]) -> None:
    for k, v in req.items():
        if v > 0:
            avail[k] = avail.get(k, 0.0) - v


def release(avail: Dict[str, float], req: Dict[str, float]) -> None:
    for k, v in req.items():
        if v > 0:
            avail[k] = avail.get(k, 0.0) + v


def normalize_task_resources(num_cpus=None, num_tpus=None, resources=None,
                             memory=None, default_cpus: float = 1.0) -> Dict[str, float]:
    req: Dict[str, float] = dict(resources or {})
    req["CPU"] = float(default_cpus if num_cpus is None else num_cpus)
    if num_tpus:
        req["TPU"] = float(num_tpus)
    if memory:
        req["memory"] = float(memory)
    return {k: v for k, v in req.items() if v > 0}
