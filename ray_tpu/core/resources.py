"""Resource model with TPU as a first-class accelerator.

Reference parity: python/ray/_private/resource_spec.py and
python/ray/_private/accelerators/tpu.py (TPU pod/slice detection, the
"TPU-<version>-head" resource). Here TPU chips are native schedulable
resources ("TPU") plus topology labels, so placement can be ICI-aware.
"""
from __future__ import annotations

import os
from typing import Dict, Optional


def detect_node_resources(num_cpus: Optional[int] = None,
                          num_tpus: Optional[int] = None) -> Dict[str, float]:
    if num_cpus is None:
        num_cpus = os.cpu_count() or 1
        # The runtime itself needs headroom; still expose at least 4 virtual
        # CPU slots so task-parallel libraries (data/tune) can overlap work —
        # CPUs in ray (and here) are scheduling tokens, not pinned cores.
        num_cpus = max(num_cpus, 4)
    res: Dict[str, float] = {"CPU": float(num_cpus)}
    if num_tpus is None:
        num_tpus = _detect_tpu_chips()
    if num_tpus:
        res["TPU"] = float(num_tpus)
    res["memory"] = float(_detect_memory_bytes())
    return res


def _detect_tpu_chips() -> int:
    # Avoid importing jax here (heavy, and workers may be CPU-only); trust
    # the environment first, mirroring reference TPU detection via env/
    # metadata (python/ray/_private/accelerators/tpu.py).
    env = os.environ.get("RAY_TPU_CHIPS")
    if env:
        return int(env)
    try:
        import jax  # noqa: PLC0415
        return sum(1 for d in jax.devices() if d.platform == "tpu")
    except Exception:
        return 0


def _detect_memory_bytes() -> int:
    try:
        import psutil  # noqa: PLC0415
        return int(psutil.virtual_memory().total * 0.7)
    except Exception:
        return 8 << 30


def fits(avail: Dict[str, float], req: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in req.items() if v > 0)


def acquire(avail: Dict[str, float], req: Dict[str, float]) -> None:
    for k, v in req.items():
        if v > 0:
            avail[k] = avail.get(k, 0.0) - v


def release(avail: Dict[str, float], req: Dict[str, float]) -> None:
    for k, v in req.items():
        if v > 0:
            avail[k] = avail.get(k, 0.0) + v


def normalize_task_resources(num_cpus=None, num_tpus=None, resources=None,
                             memory=None, default_cpus: float = 1.0) -> Dict[str, float]:
    req: Dict[str, float] = dict(resources or {})
    req["CPU"] = float(default_cpus if num_cpus is None else num_cpus)
    if num_tpus:
        req["TPU"] = float(num_tpus)
    if memory:
        req["memory"] = float(memory)
    return {k: v for k, v in req.items() if v > 0}
