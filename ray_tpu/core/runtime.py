"""Driver runtime: single-controller scheduler + object directory.

Reference parity (collapsed into one process, by design):
  * raylet local scheduler  — src/ray/raylet/local_task_manager.cc
  * GCS server              — src/ray/gcs/gcs_server/
  * ownership/object dir    — src/ray/core_worker/reference_count.cc,
                              src/ray/object_manager/ownership_based_object_directory.cc
  * worker pool             — src/ray/raylet/worker_pool.cc

Concurrency model: every state mutation flows through one dispatcher thread
consuming an inbox queue (worker messages, API calls, timers). API threads
block on events; worker connections get one reader thread each. This is the
TPU-friendly single-controller analogue of the reference's distributed
raylet protocol — on a TPU pod, one driver per slice controls all hosts, and
the data plane (XLA collectives over ICI) never touches this control plane.
"""
from __future__ import annotations

import collections
import os
import queue
import subprocess
import sys
import tempfile
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import resources as res_mod
from . import scheduling as sched_mod
from . import serialization
from .gcs import GCS, ActorEntry, TaskEntry, NodeEntry
from .ids import new_node_id, new_object_id
from .object_ref import ObjectRef
from .object_store import make_store
from .protocol import (Connection, ConnectionClosed, tcp_listener,
                       unix_listener)
from .task import TaskSpec, ActorCreationSpec
from ..util import knobs
from ..exceptions import (ActorDiedError, CompiledDagError, GetTimeoutError,
                          ObjectLostError, PlacementGroupError,
                          RuntimeNotInitializedError, TaskCancelledError,
                          TaskError, WorkerCrashedError)


_mcat_mod = None
_ev_mod = None


def _mcat():
    # lazy: ray_tpu.util's __init__ imports modules that import THIS
    # module, so a top-level util import would be circular during
    # package init; cached after the first call (hot paths call this
    # several times per task — the importlib machinery is measurable)
    global _mcat_mod
    if _mcat_mod is None:
        from ..util import metrics_catalog  # noqa: PLC0415
        _mcat_mod = metrics_catalog
    return _mcat_mod


def _ev():
    # same lazy-import-then-cache rationale as _mcat
    global _ev_mod
    if _ev_mod is None:
        from ..util import events  # noqa: PLC0415
        _ev_mod = events
    return _ev_mod


_waits_mod = None


def _waits():
    # same lazy-import-then-cache rationale as _mcat
    global _waits_mod
    if _waits_mod is None:
        from ..util import waits  # noqa: PLC0415
        _waits_mod = waits
    return _waits_mod

_runtime: Optional[Any] = None
_runtime_lock = threading.Lock()


def get_runtime():
    if _runtime is None:
        raise RuntimeNotInitializedError(
            "ray_tpu.init() must be called first")
    return _runtime


def set_runtime(rt) -> None:
    global _runtime
    _runtime = rt


def runtime_initialized() -> bool:
    return _runtime is not None


def _cpu_only(held: Dict[str, float]) -> Dict[str, float]:
    return {k: v for k, v in held.items() if k == "CPU"}


def _non_cpu(held: Dict[str, float]) -> Dict[str, float]:
    return {k: v for k, v in held.items() if k != "CPU"}


class WorkerState:
    __slots__ = ("worker_id", "conn", "proc", "pid", "state", "current_task",
                 "actor_id", "held_resources", "held_tpu_ids", "blocked",
                 "started_at", "purpose", "tpu_capable", "node_id",
                 "func_calls", "lease", "direct_addr", "last_progress",
                 "node_lease")

    def __init__(self, worker_id: str, proc: Optional[subprocess.Popen],
                 purpose=None, tpu_capable: bool = False,
                 node_id: Optional[str] = None):
        self.worker_id = worker_id
        self.proc = proc               # None for workers on remote nodes
        self.conn: Optional[Connection] = None
        self.pid: Optional[int] = None
        self.state = "starting"        # starting|idle|busy|actor|dead
        self.current_task: Optional[str] = None
        # task ids dispatched under this worker's current lease, in
        # execution order (head = the task actually running; the worker
        # executes its queue strictly FIFO). One-slot leases are the
        # legacy single-dispatch case.
        self.lease: collections.deque = collections.deque()
        # listener address for direct worker->worker actor calls
        # (registered at worker startup; None when the worker predates
        # the direct-call plane or failed to bind)
        self.direct_addr: Optional[str] = None
        # last lease grant or completion: the lease progress watchdog
        # reclaims unstarted slots when the head stalls without parking
        # in a driver-visible verb (gang tasks spinning in a user-space
        # rendezvous loop must not pin their peers behind them)
        self.last_progress = 0.0
        # id of the NODE-level bulk lease holding this worker (two-level
        # scheduling): the node agent, not the driver, fans tasks to it
        # while set; resources release at lease close, not per task
        self.node_lease: Optional[str] = None
        self.actor_id: Optional[str] = None
        self.held_resources: Dict[str, float] = {}
        self.held_tpu_ids: List[int] = []
        self.func_calls: Dict[str, int] = {}   # func_id -> executions
        self.blocked = False
        self.started_at = time.time()
        self.purpose = purpose         # None (general) | actor_id
        self.tpu_capable = tpu_capable
        self.node_id = node_id


class NodeState:
    """Per-node scheduling view: capacity, availability, topology labels,
    and (for remote nodes) the node-agent connection used to spawn
    workers and fetch objects. The driver's own host is node 0 with
    conn=None (reference parity: per-node resource views in
    gcs_node_manager.cc / node_manager.cc)."""
    __slots__ = ("node_id", "hostname", "total", "avail", "labels", "conn",
                 "alive", "free_tpu_ids", "last_heartbeat",
                 "heartbeat_missed", "incarnation", "restored",
                 "lease_capable")

    def __init__(self, node_id: str, hostname: str,
                 resources: Dict[str, float],
                 labels: Optional[Dict[str, str]] = None,
                 conn: Optional[Connection] = None):
        self.node_id = node_id
        self.hostname = hostname
        self.total = dict(resources)
        self.avail = dict(resources)
        self.labels = dict(labels or {})
        self.conn = conn
        self.alive = True
        # liveness plumbing (event plane): agents ping periodically;
        # the reaper tick flags staleness as a node.heartbeat_miss
        # event before the socket-level death determination lands
        self.last_heartbeat = time.time()
        self.heartbeat_missed = False
        # bumped on rejoin; messages from older incarnations are fenced
        self.incarnation = 0
        # rebuilt from persisted state by a resumed driver and not yet
        # re-registered: the agent's reattach flips this back off
        self.restored = False
        # the agent advertised its local dispatch plane at registration
        # (two-level scheduling): only then may the driver grant this
        # node bulk leases
        self.lease_capable = False
        # Specific chip indices handed to tasks/actors (get_tpu_ids):
        # concurrent TPU workloads on one host must see disjoint chips.
        self.free_tpu_ids = list(range(int(resources.get("TPU", 0))))


class NodeLease:
    """Driver-side ledger of one NODE-level bulk lease (two-level
    scheduling, docs/SCHEDULING.md): a resource shape, the workers
    claimed for it (each holding one `need` worth of the node's
    resources until the lease closes), and the granted tasks still
    outstanding. Standing leases carry no driver tasks — they park
    capacity for a node's agent-local nested submissions and are
    released by the agent when idle (or reclaimed by the tick when
    driver work starves)."""

    __slots__ = ("lease_id", "node_id", "need", "need_key", "wids",
                 "tasks", "standing", "created_at", "last_activity")

    def __init__(self, lease_id: str, node_id: str,
                 need: Dict[str, float], wids: List[str],
                 standing: bool = False):
        self.lease_id = lease_id
        self.node_id = node_id
        self.need = dict(need)
        self.need_key = sched_mod.shape_key(need)
        self.wids = list(wids)
        self.tasks: Dict[str, TaskSpec] = {}   # outstanding ledger
        self.standing = standing
        self.created_at = time.time()
        # stamped at grant/extend/completion/spill: the tick watchdog
        # force-revokes a lease whose agent stops making progress
        self.last_activity = self.created_at


class GenStream:
    """Driver-side state of one streaming-generator task
    (num_returns="streaming"): item refs arrive as the remote generator
    yields; consumers pop them in order via gen_next (reference parity:
    ObjectRefGenerator / streaming generator tasks, _raylet.pyx)."""
    __slots__ = ("task_id", "items", "done", "error", "waiters",
                 "terminal_sent", "retained")

    def __init__(self, task_id: str):
        self.task_id = task_id
        self.items: collections.deque = collections.deque()   # sealed oids
        self.done = False
        self.error: Optional[BaseException] = None
        # each waiter: (cb, abandoned_flag_list); cb((kind, payload))
        self.waiters: collections.deque = collections.deque()
        # already enqueued on the retention-eviction deque
        self.retained = False
        # the done/error reply reached a consumer (GC precondition: the
        # real error object must be delivered before the stream drops to
        # the generic task-table fallback)
        self.terminal_sent = False


class Waiter:
    """A pending get/wait. Satisfied (and its callback fired) exactly once,
    from the dispatcher thread."""
    _ids = iter(range(1, 1 << 62))

    def __init__(self, oids: List[str], num_returns: Optional[int],
                 callback: Callable[[Dict[str, Tuple[str, Any]], List[str]], None],
                 needs_bytes: bool = True):
        self.waiter_id = next(Waiter._ids)
        self.oids = oids
        # settled ids accumulate here so each seal costs one membership
        # update, not a rescan of every oid (a 1000-ref get used to pay
        # O(N^2) _object_settled calls across its seals)
        self.settled: set = set()
        uniq = len(set(oids))
        self.num_returns = uniq if num_returns is None \
            else min(num_returns, uniq)
        self.callback = callback
        self.done = False
        # get-style waiters need the PAYLOAD (a device-resident object
        # must materialize first); wait-style waiters only need
        # readiness — a device loc counts as ready and must NOT trigger
        # a D2H materialization (that would also destroy the device-
        # locality scheduling the object exists for)
        self.needs_bytes = needs_bytes


class PlacementGroupState:
    def __init__(self, pg_id: str, bundles: List[Dict[str, float]],
                 strategy: str, name: str = ""):
        self.pg_id = pg_id
        self.bundles = bundles
        self.strategy = strategy
        self.name = name
        self.state = "PENDING"         # PENDING|CREATED|INFEASIBLE|REMOVED
        self.ready_ref: Optional[str] = None
        # node_id per bundle, filled at admission by the strategy solver
        self.bundle_nodes: List[str] = []
        # chip indices reserved per bundle at admission (tasks scheduled
        # into a bundle report these from get_tpu_ids)
        self.bundle_tpu_ids: List[List[int]] = []
        self.created_at = time.time()


class DriverRuntime:
    is_driver = True
    # count backstop for the lineage table (the primary bound is
    # accumulated bytes, RAY_TPU_LINEAGE_BYTES — see _retain_lineage)
    _LINEAGE_RETAIN = 4096

    def __init__(self, *, num_cpus=None, num_tpus=None, resources=None,
                 object_store_memory=None, max_workers=None, namespace="default",
                 job_id=None, log_to_driver=True, listen=None,
                 state_dir=None, resume=False):
        self.namespace = namespace
        self.job_id = job_id or f"job-{os.getpid()}"
        self.gcs = GCS()
        self.node_id = new_node_id()
        # ---- control-plane persistence (core/persistence.py): with a
        # state dir, every GCS mutation WALs and resume=True rebuilds
        # the tables after a driver crash under a bumped incarnation
        from . import persistence as persist_mod  # noqa: PLC0415
        state_dir = state_dir or persist_mod.default_state_dir()
        self.state_dir = state_dir
        self.incarnation = 0
        self.resumed = False
        self._resume_rec = None
        self._persist = None
        if resume is True and not state_dir:
            # silently starting fresh here would re-run every
            # side-effecting task of a job that believes it resumed
            raise RuntimeError(
                "init(resume=True) requires a state dir: pass "
                "state_dir=... or set RAY_TPU_STATE_DIR "
                "(resume=\"auto\" starts fresh when there is none)")
        if state_dir and resume:
            rec = persist_mod.load(state_dir)
            if rec is None:
                if resume != "auto":
                    raise RuntimeError(
                        f"init(resume=True): no persisted driver state "
                        f"under {state_dir!r} (missing MANIFEST.json)")
            else:
                self._resume_rec = rec
                self.incarnation = rec.incarnation + 1
                self.resumed = True
                if rec.node_id:
                    # the driver node KEEPS its id across restarts
                    # (mirroring node agents, which keep theirs across
                    # rejoins and bump an incarnation): restored
                    # lineage specs' NodeAffinity pins, persisted
                    # ObjectLocations, and forensics all keep naming a
                    # node that still exists
                    self.node_id = rec.node_id
                if listen is None \
                        and not knobs.get_raw("RAY_TPU_LISTEN"):
                    # re-bind the crashed driver's control address so
                    # waiting node agents reattach to it
                    listen = rec.listen
        elif state_dir and persist_mod.wipe(state_dir):
            sys.stderr.write(
                f"[ray_tpu] fresh init(): cleared stale driver state "
                f"from {state_dir}\n")
        # Stamp this process's node id so ObjectLocations created by the
        # driver (and env-inheriting local workers) carry it.
        os.environ["RAY_TPU_NODE_ID"] = self.node_id
        node_res = res_mod.detect_node_resources(num_cpus, num_tpus)
        if resources:
            node_res.update(resources)
        labels = res_mod.detect_tpu_topology(int(node_res.get("TPU", 0)))
        self.cluster_nodes: Dict[str, NodeState] = {
            self.node_id: NodeState(self.node_id, os.uname().nodename,
                                    node_res, labels=labels, conn=None)}
        self.gcs.nodes[self.node_id] = NodeEntry(
            node_id=self.node_id, hostname=os.uname().nodename,
            resources=dict(node_res), labels=labels)

        cap = object_store_memory \
            or knobs.get_int("RAY_TPU_STORE_BYTES")
        self.store = make_store(capacity_bytes=cap, is_owner=True)
        self.max_workers = max_workers \
            or knobs.get_int("RAY_TPU_MAX_WORKERS")

        self._tmpdir = tempfile.mkdtemp(prefix="ray_tpu_")
        from .spilling import SpillManager  # noqa: PLC0415
        self._spill_env_owned = "RAY_TPU_SPILL_DIR" not in os.environ
        spill_dir = knobs.get_raw("RAY_TPU_SPILL_DIR") or os.path.join(
            self._tmpdir, "spill")
        os.environ["RAY_TPU_SPILL_DIR"] = spill_dir  # workers inherit
        self._spill = SpillManager(self.store, spill_dir, self.node_id)
        self.socket_path = os.path.join(self._tmpdir, "driver.sock")
        self._listener = unix_listener(self.socket_path)
        # Multi-host: optional TCP listener for remote node agents and the
        # workers they spawn ("host:port", port 0 = ephemeral).
        listen = listen or knobs.get_raw("RAY_TPU_LISTEN")
        self._tcp_listener = None
        self.tcp_address: Optional[str] = None
        if listen:
            host, _, port = str(listen).rpartition(":")
            host = host or "127.0.0.1"
            self._tcp_listener = tcp_listener(host, int(port or 0))
            lh, lp = self._tcp_listener.getsockname()[:2]
            if lh in ("0.0.0.0", "::"):
                # Wildcard binds accept on every interface but the
                # advertised address must be routable from other hosts.
                from ..util.netutil import routable_ip  # noqa: PLC0415
                lh = routable_ip()
            self.tcp_address = f"tcp://{lh}:{lp}"
        self.log_dir = os.path.join(self._tmpdir, "logs")
        os.makedirs(self.log_dir, exist_ok=True)
        self._log_streamer = None
        if log_to_driver:
            from .logging import LogStreamer  # noqa: PLC0415
            self._log_streamer = LogStreamer(self.log_dir)

        self.inbox: "queue.Queue" = queue.Queue()
        self.workers: Dict[str, WorkerState] = {}
        self.pending_tasks: collections.deque = collections.deque()
        self._spread_rr = 0   # rotating node index for SPREAD scheduling
        self._gen_streams: Dict[str, GenStream] = {}
        # rid -> (abandoned_flag, worker, blocked_here) for parked
        # worker-side generator waiters
        self._gen_worker_waiters: Dict[str, tuple] = {}
        # settled-but-unconsumed streams, oldest first (bounded retention)
        self._gen_settled: collections.deque = collections.deque()
        # settled streams still holding undrained items (larger bound)
        self._gen_undrained: collections.deque = collections.deque()
        # task_ids whose undrained items were evicted: late consumers
        # get an explicit ObjectLostError, not a silent "done".
        # deque bounds the memory; the set makes _gen_lookup's
        # membership check O(1) on the dispatcher thread.
        self._gen_evicted: collections.deque = collections.deque()
        self._gen_evicted_set: set = set()
        # batched-submission round-trips (compiled DAG test hook)
        self.submit_many_calls = 0
        # ---- decentralized batched dispatch (docs/SCHEDULING.md) ----
        # .remote() submits coalesce into api_submit_many frames under a
        # size + time flush window; dispatches grant multi-slot worker
        # leases; actor dispatch pipelines past max_concurrency (the
        # worker enforces the real execution bound). RAY_TPU_BATCH=0 is
        # the kill switch back to the legacy per-message paths.
        self._batch_enabled = knobs.get_bool("RAY_TPU_BATCH")
        self._flush_n = knobs.get_int("RAY_TPU_BATCH_FLUSH_N")
        self._flush_window = knobs.get_float("RAY_TPU_BATCH_FLUSH_S")
        self._lease_cap = knobs.get_int("RAY_TPU_LEASE_SLOTS")
        self._actor_pipeline = knobs.get_int("RAY_TPU_ACTOR_PIPELINE")
        if not self._batch_enabled:
            self._lease_cap = 1
            self._actor_pipeline = 0
        self._submit_buf: List[TaskSpec] = []
        self._submit_buf_lock = threading.Lock()
        self._submit_buf_event = threading.Event()
        # dispatch-plane telemetry (state API dispatch_summary / bench
        # messages-per-task): flushed submit batches, lease lifecycle,
        # frames and logical messages in each direction
        self.submit_batches = 0
        self.batched_submits = 0
        self.lease_grants = 0
        self.lease_revokes = 0
        self.dispatch_frames = 0
        self.dispatched_tasks = 0
        self.ctrl_frames = 0
        self.ctrl_msgs: collections.Counter = collections.Counter()
        # ---- two-level scheduling (docs/SCHEDULING.md) ----
        # NODE-level bulk leases: the driver hands a batch of compatible
        # queued tasks plus a set of the node's workers to its agent in
        # one frame; the agent fans them out locally and streams batched
        # completions back. RAY_TPU_NODE_LEASES=0 kills the path.
        self._node_leases_enabled = knobs.get_bool("RAY_TPU_NODE_LEASES")
        self._node_lease_slots = max(
            1, knobs.get_int("RAY_TPU_NODE_LEASE_SLOTS"))
        if not self._batch_enabled:
            self._node_leases_enabled = False
        self.node_leases: Dict[str, NodeLease] = {}
        self._nlease_counter = 0
        # node_id -> deadline (time.time); a node that just spilled
        # tasks back is skipped by the grant pass until this passes
        self._nlease_backoff: Dict[str, float] = {}
        self.node_lease_grants = 0
        self.node_lease_extends = 0
        self.node_lease_tasks = 0
        self.spillbacks = 0
        # compiled-DAG controllers by dag_id (docs/DAG.md); acquires
        # queue here until the dispatcher can pin every stage's worker
        self.compiled_dags: Dict[str, Any] = {}
        self._dag_acquires: List[dict] = []
        # (worker_id, task_id) pairs reclaimed from a blocked worker's
        # lease: a result that slips in anyway (revoke raced a user
        # thread) must be dropped, not double-sealed over the re-run
        self._revoked_set: set = set()
        self._revoked_q: collections.deque = collections.deque()
        self._kv_lock = threading.Lock()
        self.pending_actors: collections.deque = collections.deque()
        self.pending_restarts: collections.deque = collections.deque()
        self.actor_queues: Dict[str, collections.deque] = {}
        self.actor_max_conc: Dict[str, int] = {}
        # concurrency groups: per-actor {group: limit} and per
        # (actor_id, group|None) in-flight counts (None = the default
        # max_concurrency lane; this map is THE in-flight gate)
        self.actor_group_conc: Dict[str, Dict[str, int]] = {}
        self.actor_group_inflight: Dict[tuple, int] = {}
        self.waiters: Dict[int, Waiter] = {}
        self.object_waiters: Dict[str, List[int]] = {}
        self.report_handlers: Dict[str, Callable] = {}
        self.placement_groups: Dict[str, PlacementGroupState] = {}
        self._task_events: Dict[str, List[Tuple[float, str]]] = {}
        self._actor_create_specs: Dict[str, ActorCreationSpec] = {}
        self._respawnable_specs: Dict[str, TaskSpec] = {}
        # finished non-actor task specs for lineage reconstruction
        # (insertion-ordered; bounded by accumulated bytes AND count —
        # evicting a producer pins its surviving outputs as
        # non-reconstructable via ObjectEntry.lineage_evicted)
        self._lineage_specs: Dict[str, TaskSpec] = {}
        self._lineage_sizes: Dict[str, int] = {}
        self._lineage_bytes = 0
        self._lineage_cap = knobs.get_int("RAY_TPU_LINEAGE_BYTES")
        self._lineage_enabled = knobs.get_bool("RAY_TPU_LINEAGE")
        # how long a reader blocks for a reconstruction it triggered
        # before giving up on the object
        self._reconstruct_wait = knobs.get_float(
            "RAY_TPU_RECONSTRUCTION_WAIT_S")
        # latest __ray_save__ checkpoint per actor, handed back to the
        # replacement worker for __ray_restore__ around a restart
        self._actor_checkpoints: Dict[str, bytes] = {}
        # (node_id, conn id) pairs already reported as fenced, so a
        # chatty stale incarnation logs one node.fence, not thousands
        self._fenced_seen: set = set()
        # device-resident objects with an in-flight materialize request
        # (core/device_store.py); cleared when the holder's re-seal lands
        self._materializing: set = set()
        # pending-placement diagnostics: first-seen ts per task/actor id
        # and a warned set, so a workload stuck behind exhausted
        # resources surfaces a one-time stderr warning instead of
        # hanging silently (reference: raylet's pending-task warnings)
        self._pending_since: Dict[str, float] = {}
        self._pending_warned: set = set()
        self._wid_counter = 0
        self._shutdown = threading.Event()
        self._conn_by_wid: Dict[str, Connection] = {}
        # cross-node fetch plumbing: rid -> (Event, box)
        self._fetch_counter = 0
        self._fetch_lock = threading.Lock()
        self._fetch_events: Dict[int, Tuple[threading.Event, dict]] = {}

        # cluster metrics plane: remote processes ship delta snapshots
        # of their registries here (util/metrics.py); trace spans from
        # worker executions land in trace_spans for the timeline export
        from ..util.metrics import ClusterMetricsStore  # noqa: PLC0415
        self.cluster_metrics = ClusterMetricsStore()
        self.trace_spans: collections.deque = collections.deque(
            maxlen=8192)
        # deferred driver-side span producers (compiled-DAG controllers
        # buffer submit/result markers in bounded rings; see
        # drain_fastpath_spans)
        self._span_drains: List[Any] = []

        # cluster event plane (util/events.py): lifecycle events from
        # this process and every worker/node-agent merge here, indexed
        # by task/actor/object/node id for the state API, /api/events,
        # and post-mortem bundles
        from ..util.events import ClusterEventStore  # noqa: PLC0415
        self.cluster_events = ClusterEventStore()

        # cluster profile plane (observability/sampling_profiler.py):
        # workers ship folded-stack deltas over sys.profile on the same
        # telemetry heartbeat as metrics/spans; profile_ctl round-trips
        # (start/stop/snapshot) resolve through rid-keyed futures like
        # cross-node fetches
        from ..observability.sampling_profiler import \
            ClusterProfileStore  # noqa: PLC0415
        self.profile_store = ClusterProfileStore()
        self._profile_counter = 0
        self._profile_lock = threading.Lock()
        self._profile_replies: Dict[int, Tuple[threading.Event, dict]] = {}

        # cluster wait-state plane (util/waits.py): aged WaitRecord
        # snapshots from every worker/agent fold here; the hang
        # watchdog (observability/waitgraph.py) walks them together
        # with the driver's own wait table and GCS tables at
        # RAY_TPU_HANG_PROBE_S cadence
        from ..util.waits import ClusterWaitStore  # noqa: PLC0415
        self.cluster_waits = ClusterWaitStore()
        self._hang_monitor = None   # built lazily by _start_hang_watchdog
        self._node_hb_timeout = knobs.get_float(
            "RAY_TPU_NODE_HEARTBEAT_TIMEOUT_S")
        # heartbeat-DECLARED death: a node silent past this long is
        # declared dead without waiting for its socket to close (a
        # SIGSTOPped/preempted host can hold a socket open for minutes);
        # its object copies are pruned and reconstruction starts
        # immediately. The fenced agent rejoins under a new incarnation.
        self._node_death_timeout = knobs.get_float(
            "RAY_TPU_NODE_DEATH_TIMEOUT_S",
            default=2.0 * self._node_hb_timeout)

        # peer-to-peer object transfer plane (core/object_transfer.py):
        # the GCS object table is the location directory; this maps each
        # node to its data-plane listener so requesters pull object
        # bytes straight from the holder. The driver's own server covers
        # driver-node objects; relay over the control connections stays
        # only as an instrumented fallback (relay_bytes counter).
        self.transfer_addrs: Dict[str, str] = {}
        self._transfer_server = None
        self.relay_bytes = 0
        self._relay_lock = threading.Lock()
        if self._tcp_listener is not None:
            from .object_transfer import TransferServer  # noqa: PLC0415
            try:
                host = self.tcp_address[len("tcp://"):].rpartition(":")[0]
                # bind the SAME interface as the control plane: a
                # loopback-only driver must not expose a wider data plane
                self._transfer_server = TransferServer(
                    self.store, host=host or "0.0.0.0",
                    advertise_host=host or None,
                    spill_dirs=[spill_dir])
                self.transfer_addrs[self.node_id] = \
                    self._transfer_server.address
            except Exception:
                self._transfer_server = None

        self.report_handlers["sys.lookup_actor"] = self._sys_lookup_actor
        self.report_handlers["sys.kv"] = \
            lambda _wid, payload: self._kv_op(*payload)
        self.report_handlers["sys.metrics"] = self._on_worker_metrics
        self.report_handlers["sys.spans"] = self._on_worker_spans
        self.report_handlers["sys.events"] = self._on_worker_events
        self.report_handlers["sys.profile"] = self._on_worker_profile
        self.report_handlers["sys.waits"] = self._on_worker_waits
        # control-plane actors (the serve controller's autoscaler) need
        # the node table and placement-group ops; both live only in the
        # driver, so workers reach them over report_sync channels
        self.report_handlers["sys.cluster_view"] = self._sys_cluster_view
        self.report_handlers["sys.pg"] = self._sys_pg
        # GCS actor directory for driver-bypass actor calls: a caller
        # resolves the callee's direct-call address ONCE, then rides a
        # worker->worker connection (docs/SCHEDULING.md)
        self.report_handlers["sys.actor_addr"] = self._sys_actor_addr

        # restored remote-held objects parked until their node
        # reattaches: nid -> [(oid, loc), ...]; past the grace deadline
        # they go through lineage reconstruction instead
        self._reattach_pending: Dict[str, List[tuple]] = {}
        self._reattach_deadline = 0.0
        if state_dir:
            bound = None
            if self.tcp_address:
                bound = self.tcp_address[len("tcp://"):]
            self._persist = persist_mod.GCSPersistence(
                state_dir, incarnation=self.incarnation,
                job_id=self.job_id, node_id=self.node_id, listen=bound,
                resuming=self._resume_rec is not None)
        if self._resume_rec is not None:
            # single-threaded here (dispatcher not started yet): safe to
            # mutate every table directly
            self._restore_from(self._resume_rec)
            self._resume_rec = None
            # snapshot the RESTORED tables before anything else runs:
            # until this lands, the crashed life's manifest stays
            # authoritative (GCSPersistence deferred its swap), so a
            # second crash at ANY point resumes from intact state
            if self._persist is not None and \
                    not self._persist.snapshot(self._snapshot_tables):
                sys.stderr.write(
                    "[ray_tpu] WARNING: post-resume snapshot failed; "
                    "persistence is running degraded (the previous "
                    "life's state dir generation remains "
                    "authoritative)\n")

        # Backstop for drivers that exit without calling shutdown() (e.g.
        # a pytest process): workers self-exit on socket close, but the shm
        # arena needs an explicit owner-side unlink or it outlives us in
        # /dev/shm.
        import atexit
        atexit.register(self.shutdown)

        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True, name="rtpu-dispatch")
        self._dispatcher.start()
        self._acceptor = threading.Thread(
            target=self._accept_loop, args=(self._listener,),
            daemon=True, name="rtpu-accept")
        self._acceptor.start()
        if self._tcp_listener is not None:
            threading.Thread(target=self._accept_loop,
                             args=(self._tcp_listener,), daemon=True,
                             name="rtpu-accept-tcp").start()
        self._reaper = threading.Thread(
            target=self._reap_loop, daemon=True, name="rtpu-reaper")
        self._reaper.start()
        if self._batch_enabled:
            threading.Thread(target=self._submit_flush_loop, daemon=True,
                             name="rtpu-submit-flush").start()
        self._start_hang_watchdog()

    def _start_hang_watchdog(self) -> None:
        """The wait-graph watchdog: probe the cluster's wait records
        for deadlocks, stale waits, and stragglers every
        RAY_TPU_HANG_PROBE_S. Off when the wait plane is killed
        (RAY_TPU_WAITS=0) or the cadence is <= 0; the records
        themselves still flow for ad-hoc `ray_tpu stuck` queries."""
        from ..util import waits as waits_mod
        probe_s = knobs.get_float("RAY_TPU_HANG_PROBE_S")
        if not waits_mod.enabled() or probe_s <= 0:
            return
        from ..observability.waitgraph import HangMonitor
        self._hang_monitor = HangMonitor(self)

        def loop() -> None:
            while not self._shutdown.wait(probe_s):
                try:
                    self._hang_monitor.probe()
                except Exception:
                    pass    # a bad probe skips one tick, never kills
                    # the watchdog

        threading.Thread(target=loop, daemon=True,
                         name="rtpu-hang-watchdog").start()

    def hang_monitor(self):
        """The live HangMonitor (building it on demand so state-API
        callers can probe even when the watchdog thread is off)."""
        if self._hang_monitor is None:
            from ..observability.waitgraph import HangMonitor
            self._hang_monitor = HangMonitor(self)
        return self._hang_monitor

    # ================= driver restart / resume =================
    def _restore_from(self, rec) -> None:
        """Rebuild the control plane from a crashed driver's persisted
        state (core/persistence.py) and queue reconciliation:

        * remote nodes become reattach candidates (their agents rejoin
          through the incarnation fencing machinery; until then their
          objects park in _reattach_pending),
        * objects whose only payloads died with the old driver go
          through PR-4 lineage reconstruction,
        * actors restart from their persisted __ray_save__ checkpoints
          (named / checkpointed / max_restarts>0 actors only — the
          serve controller rides this and re-deploys its targets),
        * everything else (in-flight tasks, streams, placement groups)
          is the resuming job's to resubmit.

        Runs in __init__ before any thread starts."""
        self._emit("driver.restart",
                   f"driver resumed as incarnation {self.incarnation} "
                   f"from {self.state_dir} "
                   f"({rec.replayed_records} WAL records replayed"
                   f"{', torn tail truncated' if rec.torn_tail else ''}"
                   f"{', clean shutdown' if rec.clean else ''})",
                   node_id=self.node_id,
                   incarnation=self.incarnation,
                   replayed_records=rec.replayed_records,
                   torn_tail=rec.torn_tail, clean=rec.clean)
        if self._persist is not None:
            self._persist.replayed_records = rec.replayed_records
            self._persist.torn_tail_recovered = rec.torn_tail
        old_driver_nid = rec.node_id
        if old_driver_nid and old_driver_nid != self.node_id:
            # only for state dirs written before node-id adoption: the
            # dead driver's id survives as a tombstone for forensics
            self.gcs.nodes.setdefault(old_driver_nid, NodeEntry(
                node_id=old_driver_nid, hostname="(dead driver)",
                resources={}, alive=False))

        # ---- nodes: alive-at-crash remote nodes await reattach
        for nid, info in rec.nodes.items():
            if nid == old_driver_nid:
                continue
            self.gcs.nodes[nid] = NodeEntry(
                node_id=nid, hostname=info.get("hostname", "?"),
                resources=dict(info.get("resources") or {}),
                labels=dict(info.get("labels") or {}),
                alive=False,
                incarnation=int(info.get("incarnation", 0)))
            if not info.get("alive", False):
                continue    # declared dead pre-crash: nothing to wait on
            ns = NodeState(nid, info.get("hostname", "?"),
                           dict(info.get("resources") or {}),
                           labels=info.get("labels"), conn=None)
            ns.alive = False
            ns.restored = True
            ns.incarnation = int(info.get("incarnation", 0))
            self.cluster_nodes[nid] = ns
        grace = knobs.get_float(
            "RAY_TPU_RESUME_REATTACH_GRACE_S",
            default=knobs.get_float("RAY_TPU_NODE_REJOIN_S"))
        self._reattach_deadline = time.time() + grace

        # ---- lineage + task table (reconstruction needs both)
        for task_id, spec in rec.lineage.items():
            self._lineage_specs[task_id] = spec
            cost = self._lineage_cost(spec)
            self._lineage_sizes[task_id] = cost
            self._lineage_bytes += cost
            self.gcs.tasks[task_id] = TaskEntry(
                task_id=task_id, name=spec.name, state="FINISHED",
                actor_id=spec.actor_id)

        # ---- objects: classify every persisted payload location
        lost: List[str] = []
        for oid, e in rec.objects.items():
            if e.state != "ready":
                continue
            servable, awaiting = [], []
            for loc in [e.loc, *e.copies]:
                if loc is None:
                    continue
                kind = getattr(loc, "kind", None)
                if kind == "inline":
                    servable.append(loc)
                    continue
                if kind == "device":
                    continue            # holder died with the driver
                nid = getattr(loc, "node_id", None) or old_driver_nid
                ns = self.cluster_nodes.get(nid)
                if ns is not None and getattr(ns, "restored", False):
                    awaiting.append(loc)
                    continue
                # driver-local (or dead-node) payload: the store died
                # with its process, but a spill copy on disk survives a
                # SIGKILL — re-home it onto the new driver node
                spath = getattr(loc, "spill_path", None) or (
                    loc.name if kind == "spill" else None)
                if spath and os.path.exists(spath):
                    loc.node_id = self.node_id
                    servable.append(loc)
            self.gcs.objects[oid] = e
            if servable:
                e.loc, e.copies = servable[0], servable[1:] + awaiting
            elif awaiting:
                # park until the holder reattaches; the reattach path
                # re-seals (fresh seal_seq), the grace expiry
                # reconstructs instead
                e.state, e.loc, e.copies = "pending", None, []
                nid = awaiting[0].node_id
                self._reattach_pending.setdefault(nid, []).append(
                    (oid, awaiting[0]))
            else:
                e.state, e.loc, e.copies = "pending", None, []
                lost.append(oid)

        # ---- actors: resume-eligible ones restart from checkpoints
        self.gcs.named_actors.update(rec.named_actors)
        self._actor_checkpoints.update(rec.checkpoints)
        for aid, ae in rec.actors.items():
            self.gcs.actors[aid] = ae
            if ae.state == "DEAD":
                continue    # a dead actor's name is not resurrected
            acspec = ae.create_spec
            pg_id = getattr(acspec, "placement_group_id", None) \
                if acspec is not None else None
            resumable = acspec is not None and pg_id is None and (
                bool(ae.name) or aid in rec.checkpoints
                or ae.max_restarts > 0)
            if not resumable:
                ae.state = "DEAD"
                ae.worker_id = None
                ae.death_cause = (
                    "placement groups are not persisted across a "
                    "driver restart" if pg_id is not None else
                    "driver restarted; actor is not resumable (no "
                    "name, no __ray_save__ checkpoint, max_restarts=0)")
                self._emit("actor.death", ae.death_cause, actor_id=aid,
                           class_name=ae.class_name)
                self._persist_actor_state(ae)
                continue
            ae.state = "RESTARTING"
            ae.worker_id = None
            self.actor_max_conc[aid] = acspec.max_concurrency
            self.actor_group_conc[aid] = dict(
                getattr(acspec, "concurrency_groups", None) or {})
            self.pending_restarts.append(aid)
            self._emit("actor.restart",
                       f"driver restart (incarnation "
                       f"{self.incarnation}); restarting"
                       + (" from persisted checkpoint"
                          if aid in rec.checkpoints else ""),
                       actor_id=aid, class_name=ae.class_name)
            self._persist_actor_state(ae)

        # ---- internal KV (job-level resume handles live here)
        self.gcs.kv.update(rec.kv)

        # lost objects reconstruct once the dispatcher starts (their
        # producer chains re-queue through _handle_lost_object)
        if lost:
            self.inbox.put(("resume_reconcile", lost))
        sys.stderr.write(
            f"[ray_tpu] driver resumed as incarnation "
            f"{self.incarnation}: {len(rec.objects)} objects "
            f"({len(lost)} lost with the old driver, "
            f"{sum(len(v) for v in self._reattach_pending.values())} "
            f"awaiting node reattach), {len(rec.actors)} actors "
            f"({len(self.pending_restarts)} restarting), "
            f"{len(rec.lineage)} lineage specs, "
            f"{rec.replayed_records} WAL records replayed\n")

    def _resume_reconcile(self, lost: List[str]) -> None:
        """Dispatcher-side half of resume: push every payload that died
        with the old driver through the PR-4 loss machinery — lineage
        re-execution when the producer's spec survived, a clean
        ObjectLostError otherwise."""
        for oid in lost:
            e = self.gcs.objects.get(oid)
            if e is None or e.state != "pending":
                continue
            self._handle_lost_object(
                oid, e,
                cause="payload lived in the crashed driver's store")

    def _check_reattach_grace(self) -> None:
        """Give up on restored nodes that never reattached: their parked
        objects go through lineage reconstruction instead."""
        if not self._reattach_pending \
                or time.time() < self._reattach_deadline:
            return
        pend, self._reattach_pending = self._reattach_pending, {}
        for nid, items in pend.items():
            for oid, loc in items:
                e = self.gcs.objects.get(oid)
                if e is None or e.state != "pending":
                    continue
                self._handle_lost_object(
                    oid, e,
                    cause=f"holder node {nid} did not reattach within "
                          f"the resume grace window", node_id=nid)

    def _snapshot_tables(self) -> dict:
        """Build the snapshot payload (dispatcher thread: tables are
        consistent without locks; only kv is shared with API threads)."""
        nodes = {}
        for nid, ns in self.cluster_nodes.items():
            if nid == self.node_id:
                continue
            nodes[nid] = {"node_id": nid, "hostname": ns.hostname,
                          "resources": dict(ns.total),
                          "labels": dict(ns.labels),
                          "incarnation": ns.incarnation,
                          "alive": ns.alive}
        with self._kv_lock:
            kv = dict(self.gcs.kv)
        return {
            "objects": {oid: e for oid, e in self.gcs.objects.items()
                        if e.state == "ready"},
            "actors": dict(self.gcs.actors),
            "checkpoints": dict(self._actor_checkpoints),
            "named_actors": dict(self.gcs.named_actors),
            "nodes": nodes,
            "lineage": dict(self._lineage_specs),
            "kv": kv,
        }

    def _persist_actor_state(self, ae) -> None:
        if self._persist is not None:
            self._persist.actor_state(ae)

    def persistence_stats(self) -> Optional[dict]:
        """Persistence-health snapshot for the state API / CLI; None
        when no state dir is configured."""
        if self._persist is None:
            return None
        stats = self._persist.stats()
        stats["resumed"] = self.resumed
        stats["reattach_awaiting_objects"] = sum(
            len(v) for v in list(self._reattach_pending.values()))
        return stats

    # ================= threads =================
    def _accept_loop(self, listener):
        while not self._shutdown.is_set():
            try:
                sock, _ = listener.accept()
            except OSError:
                return
            conn = Connection(sock)
            threading.Thread(target=self._reader, args=(conn,),
                             daemon=True).start()

    def _reader(self, conn: Connection):
        """One thread per inbound connection; the first message decides
        whether the peer is a worker ("register") or a remote node agent
        ("register_node")."""
        wid = None
        nid = None
        try:
            msg = conn.recv()
            if msg[0] == "register":
                wid = msg[1]
                self.inbox.put(("register", wid, conn, msg[2],
                                msg[3] if len(msg) > 3 else None))
                while True:
                    # raylint: disable=RT003 driver-side reader: worker
                    # process death closes the socket (EOF); host-level
                    # silence is the heartbeat monitor's job, which
                    # closes this conn on the node's death
                    # determination, unblocking the read
                    m = conn.recv()
                    self.inbox.put(("worker_msg", wid, m))
            elif msg[0] == "register_node":
                nid = msg[1]["node_id"]
                self.inbox.put(("register_node", msg[1], conn))
                while True:
                    # raylint: disable=RT003 heartbeat-declared node
                    # death closes this conn, so a silent peer unblocks
                    # the read within RAY_TPU_NODE_DEATH_TIMEOUT_S
                    m = conn.recv()
                    # the conn travels with the message so the dispatcher
                    # can fence traffic from a superseded incarnation
                    self.inbox.put(("node_msg", nid, m, conn))
            else:
                conn.close()
        except ConnectionClosed:
            if wid is not None:
                self.inbox.put(("worker_dead", wid))
            if nid is not None:
                self.inbox.put(("node_dead", nid, conn))

    def _reap_loop(self):
        while not self._shutdown.is_set():
            time.sleep(0.5)
            # Periodic tick: re-runs _schedule even with no worker events,
            # so time-based decisions (pg infeasibility grace) fire.
            self.inbox.put(("tick",))
            for w in list(self.workers.values()):
                if w.state != "starting":
                    continue
                if w.proc is not None and w.proc.poll() is not None:
                    self.inbox.put(("worker_dead", w.worker_id))
                elif w.proc is None and time.time() - w.started_at > 120:
                    # remote worker that never registered (agent-side
                    # spawn failure with no proc handle to poll)
                    self.inbox.put(("worker_dead", w.worker_id))

    def _dispatch_loop(self):
        while True:
            # raylint: disable=RT003 every control frame lands in this
            # inbox and the reap loop posts a tick each interval: the
            # blocking get is the dispatcher's idle state, never a park
            item = self.inbox.get()
            if item is None:
                return
            try:
                self._handle(item)
                self._schedule()
            except Exception:
                sys.stderr.write("ray_tpu dispatcher error:\n"
                                 + traceback.format_exc())

    # ================= event handling =================
    def _handle(self, item):
        kind = item[0]
        if kind == "tick":
            self._update_builtin_gauges()
            self._check_node_heartbeats()
            self._check_lease_watchdog()
            self._check_node_lease_watchdog()
            self._check_reattach_grace()
            if self._persist is not None and \
                    self._persist.maybe_snapshot(self._snapshot_tables):
                self._emit("gcs.snapshot",
                           node_id=self.node_id,
                           incarnation=self.incarnation,
                           **{k: v for k, v in
                              self._persist.stats().items()
                              if k in ("snapshots_taken",
                                       "wal_records")})
                try:
                    _mcat().get("ray_tpu_gcs_snapshots_total").inc()
                except Exception:
                    pass
            self.drain_local_events()
            return
        if kind == "resume_reconcile":
            self._resume_reconcile(item[1])
            return
        if kind == "wal":
            # API-thread mutations (internal KV) persist through here so
            # appends serialize with snapshot rotation
            if self._persist is not None:
                self._persist.append(item[1])
            return
        if kind == "final_snapshot":
            # shutdown(): the LAST snapshot must run on this thread —
            # the tables are only consistent here
            if self._persist is not None:
                self._persist.snapshot(self._snapshot_tables)
            item[1].set()
            return
        if kind == "register":
            _, wid, conn, pid = item[:4]
            w = self.workers.get(wid)
            if w is None:
                conn.close()
                return
            w.conn, w.pid = conn, pid
            if len(item) > 4:
                w.direct_addr = item[4]
            self._conn_by_wid[wid] = conn
            if w.purpose is not None:
                w.state = "actor"
                acspec = self._actor_create_specs.get(w.purpose)
                if acspec is not None:
                    w.actor_id = acspec.actor_id
                    # a restart hands back the latest __ray_save__
                    # checkpoint so the actor resumes instead of resetting
                    conn.send(("create_actor", acspec,
                               self._actor_checkpoints.get(
                                   acspec.actor_id)))
            else:
                w.state = "idle"
        elif kind == "worker_msg":
            _, wid, m = item
            self.ctrl_frames += 1
            self._handle_worker_msg(wid, m)
        elif kind == "worker_dead":
            self._on_worker_dead(item[1])
        elif kind == "register_node":
            self._on_register_node(item[1], item[2])
        elif kind == "node_msg":
            self.ctrl_frames += 1
            self._handle_node_msg(item[1], item[2],
                                  item[3] if len(item) > 3 else None)
        elif kind == "node_dead":
            self._on_node_dead(item[1],
                               conn=item[2] if len(item) > 2 else None)
        elif kind == "object_unreachable":
            self._on_object_unreachable(
                item[1], item[2], item[3] if len(item) > 3 else None)
        elif kind == "object_copied":
            e = self.gcs.objects.get(item[1])
            if e is not None and e.state == "ready":
                newloc = item[2]
                if newloc not in [e.loc, *e.copies]:
                    # copies belong to the CURRENT seal generation
                    try:
                        newloc.seal_seq = e.seal_seq
                    except Exception:
                        pass
                    self._emit("object.transfer", object_id=item[1],
                               node_id=newloc.node_id or self.node_id,
                               size=getattr(newloc, "size", None))
                    if (newloc.node_id or self.node_id) == self.node_id:
                        # driver-local re-host: promote it so driver-side
                        # readers hit local shm; the original stays a
                        # directory candidate and is freed alongside it
                        e.copies.append(e.loc)
                        e.loc = newloc
                    else:
                        # a peer pull landed a copy on another node:
                        # directory entry only (the primary stays put)
                        e.copies.append(newloc)
        elif kind == "api_submit":
            self._register_task(item[1])
        elif kind == "api_submit_many":
            # one inbox round-trip for a whole compiled-DAG level
            for spec in item[1]:
                self._register_task(spec)
        elif kind == "api_submit_actor":
            self._register_actor_creation(item[1])
        elif kind == "api_seal":
            _, oid, loc = item
            self._seal(oid, loc)
        elif kind == "api_waiter":
            self._add_waiter(item[1])
        elif kind == "api_gen_next":
            self._gen_request(item[1], item[2], item[3])
        elif kind == "waiter_timeout":
            self._fire_waiter(item[1], timed_out=True)
        elif kind == "api_cancel":
            self._cancel(item[1], item[2])
        elif kind == "api_cancel_obj":
            # Resolve object -> producing task here in the dispatcher, after
            # any preceding submit in the FIFO inbox has been processed.
            e = self.gcs.objects.get(item[1])
            if e is not None and e.owner_task:
                self._cancel(e.owner_task, item[2])
        elif kind == "api_kill_actor":
            self._kill_actor(item[1], item[2])
        elif kind == "api_free":
            self._free(item[1])
        elif kind == "api_create_pg":
            self._create_pg(item[1])
        elif kind == "api_remove_pg":
            self._remove_pg(item[1])
        elif kind == "api_dag_acquire":
            self._dag_acquires.append(item[1])
            self._process_dag_acquires()
        elif kind == "api_dag_release":
            self._dag_release(item[1], item[2], item[3])

    def _handle_worker_msg(self, wid: str, m):
        from .protocol import RECV_ERROR  # noqa: PLC0415
        w = self.workers.get(wid)
        mtype = m[0]
        if mtype == RECV_ERROR:
            sys.stderr.write(
                f"[ray_tpu driver] dropped undeserializable message from "
                f"{wid}:\n{m[1]}")
            return
        if mtype == "batch":
            # coalesced worker->driver frame: the inner messages are
            # ordinary control messages in their original send order
            for sub in m[1]:
                self._handle_worker_msg(wid, sub)
            return
        self.ctrl_msgs[mtype] += 1
        if w is not None and w.state == "dead" and mtype in (
                "task_done", "gen_item", "actor_created", "actor_exit",
                "put", "put_error", "materialized", "actor_ckpt",
                "object_unreachable"):
            # incarnation fence: a worker already declared dead (its node
            # was heartbeat-declared dead, or it was terminated) may still
            # be alive and sending — results from the fenced life must not
            # race the retried/reconstructed one
            return
        if mtype == "task_done":
            self._on_task_done(wid, m[1], m[2], m[3])
        elif mtype == "gen_item":
            self._on_gen_item(m[1], m[2], m[3])
        elif mtype == "gen_next_request":
            _, rid, task_id = m
            self._gen_next_for_worker(w, rid, task_id)
        elif mtype == "gen_abandon":
            self._gen_abandon_worker(m[1])
        elif mtype == "actor_created":
            self._on_actor_created(wid, m[1], m[2], m[3])
        elif mtype == "actor_exit":
            self._on_actor_exit(m[1])
        elif mtype == "put":
            self._seal(m[1], m[2])
        elif mtype == "materialized":
            oid, loc = m[1], m[2]
            self._materializing.discard(oid)
            if oid in self.gcs.objects:
                self._seal(oid, loc)
            else:
                # freed while the holder was serializing: reclaim the
                # fresh shm copy instead of resurrecting a ghost entry
                if loc.kind in ("shm", "native") and \
                        (loc.node_id or self.node_id) == self.node_id:
                    self.store.delete_segment(loc.name, loc.size)
        elif mtype == "materialize_failed":
            # The holder is ALIVE but the value won't serialize (e.g. an
            # unpicklable leaf next to the jax arrays). Reconstruction
            # would re-produce the same unserializable value forever —
            # surface the error to the waiters instead.
            e = self.gcs.objects.get(m[1])
            self._materializing.discard(m[1])
            if e is not None and e.state == "ready" \
                    and getattr(e.loc, "kind", None) == "device":
                self._fail_object(m[1], ObjectLostError(
                    f"device-resident object {m[1]} failed to "
                    f"materialize: {m[2]}"))
        elif mtype == "submit":
            self._register_task(m[1])
        elif mtype == "submit_many":
            # a worker-side fan-out coalesced into one frame
            for spec in m[1]:
                self._register_task(spec)
        elif mtype == "put_error":
            # a direct-call result escaped this cluster's caller (its
            # ref was serialized) but the call errored: fail the object
            # so driver-side readers see the error, not a hang
            self._fail_object(m[1], m[2])
        elif mtype == "submit_actor":
            self._register_actor_creation(m[1])
        elif mtype == "get_request":
            _, rid, oids, timeout = m
            self._worker_get(w, rid, oids, timeout)
        elif mtype == "wait_request":
            _, rid, oids, num_returns, timeout = m
            self._worker_wait(w, rid, oids, num_returns, timeout)
        elif mtype == "kill_actor":
            self._kill_actor(m[1], m[2])
        elif mtype == "actor_ckpt":
            self._on_actor_ckpt(wid, m[1], m[2])
        elif mtype == "dwait":
            # worker parked on a direct-call future past the grace
            # window: lend its CPU and reclaim leased slots, exactly
            # like a driver-path get_request would (symmetric unblock
            # on dwait False; actor workers never lend, as before)
            if w is not None and w.state == "busy":
                if m[1] and not w.blocked:
                    w.blocked = True
                    res_mod.release(self._wnode_avail(w),
                                    _cpu_only(w.held_resources))
                    if len(w.lease) > 1:
                        self._reclaim_lease(w)
                elif not m[1] and w.blocked:
                    w.blocked = False
                    res_mod.acquire(self._wnode_avail(w),
                                    _cpu_only(w.held_resources))
        elif mtype == "object_unreachable":
            self._on_object_unreachable(m[1], m[2],
                                        m[3] if len(m) > 3 else None)
        elif mtype == "cancel":
            # Workers cancel by OBJECT id (mirroring ray.cancel(ref));
            # resolve to the producing task like the driver's
            # api_cancel_obj path. A task id (generator cancel) is also
            # accepted directly.
            e = self.gcs.objects.get(m[1])
            if e is not None and e.owner_task:
                self._cancel(e.owner_task, m[2])
            else:
                self._cancel(m[1], m[2])
        elif mtype == "dag_ready":
            ctl = self.compiled_dags.get(m[1])
            if ctl is not None:
                ctl.on_ready(m[2], m[3])
        elif mtype == "dag_error":
            ctl = self.compiled_dags.get(m[1])
            if ctl is not None:
                ctl.on_install_error(m[2], m[3])
        elif mtype == "dag_down":
            ctl = self.compiled_dags.get(m[1])
            if ctl is not None:
                ctl.on_down(m[2], m[3])
        elif mtype == "profile_reply":
            _, rid, payload = m
            with self._profile_lock:
                pair = self._profile_replies.get(rid)
            if pair is not None:
                pair[1]["payload"] = payload
                pair[0].set()
        elif mtype == "report":
            h = self.report_handlers.get(m[1])
            if h:
                try:
                    h(wid, m[2])
                except Exception:
                    traceback.print_exc()
        elif mtype == "report_sync":
            _, rid, channel, payload = m
            h = self.report_handlers.get(channel)
            result = None
            if h:
                try:
                    result = h(wid, payload)
                except Exception:
                    traceback.print_exc()
            if w and w.conn:
                w.conn.send(("get_reply", rid, result))

    # ---------------- nodes ----------------
    def _on_register_node(self, info: dict, conn: Connection) -> None:
        nid = info["node_id"]
        inc = int(info.get("incarnation", 0))
        prev = self.cluster_nodes.get(nid)
        if prev is not None and prev.alive and prev.conn is not None:
            if inc <= prev.incarnation:
                # duplicate/stale registration for a live node
                try:
                    conn.close()
                except Exception:
                    pass
                return
            # a NEWER incarnation arrived before the old socket's death
            # was determined: declare the old one dead first so its
            # workers, objects, and bundles fail over exactly once
            self._on_node_dead(nid)
        # the fence-report dedup is per (nid, conn) pair: reset on each
        # (re)registration so the set stays bounded and an id()-reused
        # future connection can still report once
        self._fenced_seen = {k for k in self._fenced_seen
                             if k[0] != nid}
        was_restored = prev is not None and getattr(prev, "restored",
                                                    False)
        ns = NodeState(nid, info.get("hostname", "?"), info["resources"],
                       labels=info.get("labels"), conn=conn)
        ns.incarnation = inc
        ns.lease_capable = bool(info.get("node_leases"))
        self.cluster_nodes[nid] = ns
        self.gcs.nodes[nid] = NodeEntry(
            node_id=nid, hostname=ns.hostname, resources=dict(ns.total),
            labels=dict(ns.labels), incarnation=inc)
        if info.get("transfer_address"):
            self.transfer_addrs[nid] = info["transfer_address"]
        if self._persist is not None:
            self._persist.node_register(
                {"node_id": nid, "hostname": ns.hostname,
                 "resources": dict(ns.total),
                 "labels": dict(ns.labels), "incarnation": inc})
        if was_restored:
            # reattach after a driver restart: the agent (and its store)
            # never died — every parked object it holds becomes ready
            # again under a fresh seal generation
            parked = self._reattach_pending.pop(nid, [])
            resealed = 0
            for oid, loc in parked:
                e = self.gcs.objects.get(oid)
                if e is not None and e.state == "pending":
                    self._seal(oid, loc)
                    resealed += 1
            self._emit("node.reattach",
                       f"node {nid} ({ns.hostname}) reattached to the "
                       f"restarted driver (incarnation {inc}); "
                       f"{resealed} restored objects ready again",
                       node_id=nid, objects_resealed=resealed,
                       driver_incarnation=self.incarnation)
        elif prev is not None:
            # elastic rejoin (preempted/stalled host back): queued work
            # may flow to it again; everything it held was failed over
            # at death determination and is NOT resurrected
            self._emit("node.rejoin",
                       f"node {nid} ({ns.hostname}) re-registered as "
                       f"incarnation {inc}; stale messages from the old "
                       "incarnation are fenced",
                       node_id=nid)
        else:
            self._emit("node.register", node_id=nid,
                       hostname=ns.hostname, resources=dict(ns.total))
        # the driver's own transfer address travels per-candidate in
        # pull_object/locations payloads, so the ack stays minimal
        conn.send(("node_registered", self.node_id, self.job_id,
                   self.incarnation))

    def _handle_node_msg(self, nid: str, m, conn=None) -> None:
        from .protocol import RECV_ERROR  # noqa: PLC0415
        ns = self.cluster_nodes.get(nid)
        if ns is not None and (not ns.alive or (
                conn is not None and ns.conn is not None
                and ns.conn is not conn)):
            # incarnation fence: traffic from a heartbeat-declared-dead
            # node, or over a connection a rejoin superseded, must not
            # heal liveness or mutate state. Closing the stale socket
            # prompts that agent to re-register under a new incarnation.
            key = (nid, id(conn))
            if key not in self._fenced_seen:
                self._fenced_seen.add(key)
                self._emit("node.fence",
                           f"dropping {m[0]!r} (and any further traffic) "
                           f"from a superseded incarnation of node {nid}",
                           node_id=nid)
            if conn is not None:
                try:
                    conn.close()
                except Exception:
                    pass
            return
        if ns is not None:
            # any traffic proves liveness; a flagged miss heals
            ns.last_heartbeat = time.time()
            ns.heartbeat_missed = False
        mtype = m[0]
        if mtype != "batch":
            # logical node-plane message accounting ("batch" recurses
            # into its parts): the two-level scheduling tests assert
            # driver-frame invariants over these deltas
            self.ctrl_msgs[mtype] += 1
        if mtype == "heartbeat":
            # ack so the AGENT can tell a silent-dead driver host from
            # an idle one (node.py's RAY_TPU_DRIVER_SILENCE_S watchdog;
            # a half-open TCP peer never errors a blocking recv) —
            # this is the agent-side mirror of heartbeat-declared death
            if conn is not None:
                try:
                    conn.send(("heartbeat_ack", m[1]))
                except Exception:
                    pass  # reader will determine the death
            return
        if mtype == "batch":
            # agent-side telemetry kinds coalesced into one frame
            for sub in m[1]:
                self._handle_node_msg(nid, sub, conn)
            return
        if mtype == RECV_ERROR:
            sys.stderr.write(f"[ray_tpu driver] dropped undeserializable "
                             f"message from node {nid}:\n{m[1]}")
        elif mtype == "fetched":
            _, rid, data, err = m
            with self._fetch_lock:
                pair = self._fetch_events.pop(rid, None)
            if pair is not None:
                ev, box = pair
                box["data"], box["err"] = data, err
                ev.set()
        elif mtype == "fetched_chunk":
            # large payloads stream in frames under the protocol cap
            _, rid, off, total, chunk = m
            with self._fetch_lock:
                pair = self._fetch_events.get(rid)
            if pair is None:
                return
            ev, box = pair
            buf = box.get("buf")
            if buf is None:
                buf = box["buf"] = bytearray(total)
                box["got"] = 0
            buf[off:off + len(chunk)] = chunk
            box["got"] += len(chunk)
            if box["got"] >= total:
                with self._fetch_lock:
                    self._fetch_events.pop(rid, None)
                box["data"], box["err"] = bytes(buf), None
                ev.set()
        elif mtype == "pulled":
            # a node agent finished (or failed) a peer pull we asked for
            _, rid, oid, newloc, err = m
            with self._fetch_lock:
                pair = self._fetch_events.pop(rid, None)
            if pair is not None:
                ev, box = pair
                box["loc"], box["err"] = newloc, err
                ev.set()
            elif newloc is not None:
                # the requester gave up waiting (timeout -> relay) but
                # the pull completed: register the copy anyway so the
                # directory serves it and the free path reclaims it
                self.inbox.put(("object_copied", oid, newloc))
        elif mtype == "locate":
            # agent-side PullManager re-resolving a stale directory
            # entry between retry rounds
            _, rid, oid = m
            ns = self.cluster_nodes.get(nid)
            if ns is not None and ns.conn is not None:
                try:
                    ns.conn.send(("locations", rid,
                                  self._object_candidates(oid)))
                except ConnectionClosed:
                    pass
        elif mtype == "metrics":
            # the node agent's own registry (store stats etc.) ships on
            # the node connection; workers ship on their own conns
            self.cluster_metrics.ingest(
                {"node_id": nid, "worker_id": "node-agent"}, m[1])
        elif mtype == "spans":
            # agent-side trace spans (per-pull transfer spans)
            for sp in m[1] or ():
                sp = dict(sp)
                sp.setdefault("worker_id", "node-agent")
                if not sp.get("node_id"):
                    sp["node_id"] = nid
                self.trace_spans.append(sp)
        elif mtype == "events":
            # agent-side lifecycle events (event plane delta batch)
            self.cluster_events.ingest(
                {"node_id": nid, "worker_id": "node-agent"}, m[1])
        elif mtype == "waits":
            # agent-side wait records (synthesized lease-queue heads)
            self.cluster_waits.ingest(
                f"agent:{nid}",
                {"node_id": nid, "worker_id": "node-agent"}, m[1])
        elif mtype == "worker_spawn_failed":
            sys.stderr.write(f"[ray_tpu driver] node {nid} failed to spawn "
                             f"worker {m[1]}: {m[2]}\n")
            self.inbox.put(("worker_dead", m[1]))
        elif mtype == "nlease_done":
            # batched completions off a node-level bulk lease
            for tid, wid, sealed, err in m[2]:
                self._on_nlease_done(m[1], tid, wid, sealed, err)
        elif mtype == "nlease_spill":
            self._on_nlease_spill(nid, m[1], m[2], m[3])
        elif mtype == "nlease_want":
            self._on_nlease_want(nid, m[1], m[2])
        elif mtype == "nlease_release":
            # the agent drained a standing lease and went idle: its
            # workers return to the pool
            self._close_node_lease(m[1], notify=False)
        elif mtype == "submit":
            # agent-forwarded nested spillover (deps not node-local or
            # no capacity arrived): enters the normal task queue
            self._register_task(m[1])

    def _on_node_dead(self, nid: str, conn=None) -> None:
        ns = self.cluster_nodes.get(nid)
        if ns is None or not ns.alive:
            return
        if conn is not None and ns.conn is not None and ns.conn is not conn:
            # socket-close of a SUPERSEDED incarnation: the rejoined
            # node stays alive
            return
        # determinism for forensics: the causal chain always reads
        # heartbeat-miss -> death, even when the socket close beat the
        # staleness check to the determination
        if not ns.heartbeat_missed:
            ns.heartbeat_missed = True
            self._emit("node.heartbeat_miss",
                       f"connection to node {nid} lost", node_id=nid)
        ns.alive = False
        entry = self.gcs.nodes.get(nid)
        if entry is not None:
            entry.alive = False
        if self._persist is not None:
            self._persist.node_death(nid)
        self._emit("node.death",
                   f"node {nid} ({ns.hostname}) declared dead; failing "
                   "over its workers, objects, and placement bundles",
                   node_id=nid)
        self.cluster_metrics.drop_source({"node_id": nid})
        # drop the agent's wait snapshot too — a dead agent's lease
        # queues are gone, and ghost waits would poison the waitgraph
        self.cluster_waits.drop_source(f"agent:{nid}")
        # location directory upkeep: the dead node serves no more pulls
        self.transfer_addrs.pop(nid, None)
        # Bulk node leases die with their agent. Unstarted slots
        # re-pend WITHOUT burning a retry, but up to one task per
        # leased worker may have been EXECUTING when the node died —
        # those (the oldest outstanding entries, by grant order)
        # follow normal worker-death retry accounting so a started
        # task can't silently re-run past its retry budget. (Conn is
        # gone, so no result can race this; a rejoining agent is a
        # fresh incarnation that dropped its lease state.) Close
        # zeroes held_resources BEFORE the worker-death loop so the
        # per-worker release below can't double-release.
        for lid, lease in list(self.node_leases.items()):
            if lease.node_id == nid:
                self._revoke_node_lease(
                    lid, reason="node_death",
                    charge=min(len(lease.wids), len(lease.tasks)))
                self._close_node_lease(lid, notify=False)
        # In-flight fetches against this node resolve via their timeout.
        for w in list(self.workers.values()):
            if w.node_id == nid and w.state != "dead":
                self._on_worker_dead(w.worker_id)
        # CREATED placement groups with a bundle on the dead node go back
        # to PENDING (the reference's RESCHEDULING): surviving-node
        # reservations are released and phase 0 re-solves against the
        # remaining topology. ready_ref stays sealed — holders simply see
        # their pg-bound work queue until capacity reappears (or the
        # infeasibility grace declares it impossible).
        for pg in self.placement_groups.values():
            if pg.state == "CREATED" and nid in pg.bundle_nodes:
                for i, (b, bn) in enumerate(zip(pg.bundles,
                                                pg.bundle_nodes)):
                    node = self.cluster_nodes.get(bn)
                    if bn != nid and node is not None and node.alive:
                        res_mod.release(node.avail, b)
                        ids = (pg.bundle_tpu_ids[i]
                               if i < len(pg.bundle_tpu_ids) else [])
                        if ids:
                            node.free_tpu_ids = sorted(
                                set(node.free_tpu_ids) | set(ids))
                pg.bundle_nodes = []
                pg.state = "PENDING"
                pg.created_at = time.time()
        self._reconstruct_lost_objects(nid)

    def _reconstruct_lost_objects(self, nid: str) -> None:
        """Lineage reconstruction (reference:
        core_worker/reference_count.cc + task resubmission): when a node
        dies — socket-close OR heartbeat-declared — every ready object
        whose payload lived there either fails over to a surviving copy,
        is re-created by re-running its producing task (kept in the
        bounded lineage log), or fails. Runs in the dispatcher BEFORE
        readers chase the stale location."""
        def alive(node_id) -> bool:
            n = self.cluster_nodes.get(node_id)
            return n is not None and n.alive

        for oid, e in list(self.gcs.objects.items()):
            if e.state != "ready":
                continue
            if getattr(e.loc, "kind", None) == "inline":
                continue  # payload rides in the location itself
            # location directory upkeep: copies on the dead node must
            # not be handed to pullers as candidates
            e.copies = [c for c in e.copies
                        if getattr(c, "node_id", None) != nid]
            loc_node = getattr(e.loc, "node_id", None)
            if loc_node != nid:
                continue
            survivors = [c for c in e.copies
                         if getattr(c, "node_id", None) is None
                         or alive(c.node_id)]
            if survivors:
                e.loc = survivors[0]
                e.copies = [c for c in survivors if c is not e.loc]
                continue
            self._handle_lost_object(
                oid, e, cause=f"only copy lived on dead node {nid}",
                node_id=nid)

    # ---------------- lineage / reconstruction ----------------
    @staticmethod
    def _max_reconstruction_depth() -> int:
        return knobs.get_int("RAY_TPU_MAX_RECONSTRUCTION_DEPTH")

    @staticmethod
    def _max_reconstructions() -> int:
        """Per-task cap on REPEAT re-executions (distinct from the
        recursion depth cap): a flapping node must not re-run the same
        producer forever while a reader blocks."""
        return knobs.get_int("RAY_TPU_MAX_RECONSTRUCTIONS")

    def _lineage_cost(self, spec) -> int:
        """Rough retained footprint of one lineage entry: func_bytes
        usually dominates; by-VALUE args are estimated by walking a few
        container levels (getsizeof alone counts a list's pointer
        array, not the gigabytes of ndarrays inside it). Args passed by
        ObjectRef cost nothing — the ref IS the lineage edge."""
        def est(a, depth=0):
            if isinstance(a, ObjectRef):
                return 64
            nb = getattr(a, "nbytes", None)
            if isinstance(nb, int):
                return nb
            if isinstance(a, (bytes, bytearray, memoryview, str)):
                return len(a)
            if depth < 3 and isinstance(a, (list, tuple, set)):
                return 64 + sum(est(x, depth + 1) for x in a)
            if depth < 3 and isinstance(a, dict):
                return 64 + sum(est(k, depth + 1) + est(v, depth + 1)
                                for k, v in a.items())
            try:
                return sys.getsizeof(a)
            except Exception:
                return 64
        n = len(spec.func_bytes or b"") + 256
        for a in list(spec.args) + list(spec.kwargs.values()):
            n += est(a)
        return n

    def _retain_lineage(self, task_id: str, spec) -> None:
        """Keep a finished task's spec so its outputs can name their
        recipe. Bounded by accumulated bytes (RAY_TPU_LINEAGE_BYTES) and
        entry count; evicting a producer pins its surviving outputs as
        non-reconstructable (the newest entry is always kept, even when
        alone over the cap)."""
        if not self._lineage_enabled:
            return
        cost = self._lineage_cost(spec)
        # move-to-end on re-retain (a reconstructed producer finishing
        # again): eviction pops oldest-INSERTED, and a hot re-executed
        # spec must not sit at the head of the line
        self._lineage_specs.pop(task_id, None)
        self._lineage_specs[task_id] = spec
        self._lineage_bytes += cost - self._lineage_sizes.get(task_id, 0)
        self._lineage_sizes[task_id] = cost
        if self._persist is not None:
            self._persist.lineage_retain(task_id, spec)
        # the spec is (back) in the table: un-pin outputs a concurrent
        # eviction may have flagged while this re-run was in flight
        for oid in spec.return_ids:
            e = self.gcs.objects.get(oid)
            if e is not None:
                e.lineage_evicted = False
        while len(self._lineage_specs) > 1 and (
                self._lineage_bytes > self._lineage_cap
                or len(self._lineage_specs) > self._LINEAGE_RETAIN):
            old_id = next(iter(self._lineage_specs))
            old = self._lineage_specs.pop(old_id)
            self._lineage_bytes -= self._lineage_sizes.pop(old_id, 0)
            if self._persist is not None:
                self._persist.lineage_evict(old_id)
            for ooid in old.return_ids:
                oe = self.gcs.objects.get(ooid)
                if oe is not None:
                    oe.lineage_evicted = True

    def _object_live(self, e) -> bool:
        """At least one recorded payload location is still servable
        (inline / alive node / alive holding worker)."""
        if e.state != "ready":
            return False
        for loc in [e.loc, *e.copies]:
            if loc is None:
                continue
            kind = getattr(loc, "kind", None)
            if kind == "inline":
                return True
            if kind == "device":
                w = self.workers.get(loc.name)
                if w is not None and w.state != "dead" \
                        and w.conn is not None:
                    return True
                continue
            nid = getattr(loc, "node_id", None) or self.node_id
            n = self.cluster_nodes.get(nid)
            if n is not None and n.alive:
                return True
        return False

    def _lost_object_error(self, oid: str, e, detail: str):
        """The user-facing error for a lost, non-reconstructable object.
        An object produced by a dead actor's task reports the ACTOR's
        death (with its death_cause), not a bare ObjectLostError — the
        two used to race on worker-death ordering."""
        te = self.gcs.tasks.get(e.owner_task) if e.owner_task else None
        aid = te.actor_id if te is not None else None
        if aid:
            ae = self.gcs.actors.get(aid)
            if ae is not None and ae.state in ("DEAD", "RESTARTING"):
                cause = ae.death_cause or "worker died"
                return ActorDiedError(
                    f"object {oid} was produced by actor {aid} "
                    f"({ae.class_name}), which died: {cause} [{detail}]")
        return ObjectLostError(f"object {oid} {detail}")

    def _handle_lost_object(self, oid: str, e, *, cause: str,
                            node_id=None) -> bool:
        """An object's last payload copy is gone: re-execute its
        producer from the lineage table when possible, else fail it.
        Returns True when a reconstruction is in flight."""
        why = self._reconstruct_object(oid, cause=cause, node_id=node_id)
        if why is None:
            return True
        detail = f"{cause}; {why}"
        self._emit("object.lost", detail, object_id=oid,
                   task_id=e.owner_task or None, node_id=node_id)
        self._fail_object(oid, self._lost_object_error(oid, e, detail))
        return False

    def _reconstruct_object(self, oid: str, *, depth: int = 0,
                            cause: str = "", node_id=None,
                            _seen=None) -> Optional[str]:
        """Queue a lineage re-execution of `oid`'s producing task — and,
        recursively, of any lost arguments up to
        RAY_TPU_MAX_RECONSTRUCTION_DEPTH. Returns None when a re-run is
        (now or already) in flight; otherwise a human-readable reason
        why the object cannot be reconstructed. Dispatcher-thread only;
        concurrent triggers dedupe on the entry/task state."""
        e = self.gcs.objects.get(oid)
        if e is None:
            return "object entry was freed"
        task_id = e.owner_task
        te = self.gcs.tasks.get(task_id) if task_id else None
        if e.state == "pending" and te is not None \
                and te.state in ("PENDING", "SCHEDULED", "RUNNING"):
            return None  # a concurrent reconstruction is already running
        if not self._lineage_enabled:
            return "lineage recording is disabled (RAY_TPU_LINEAGE=0)"
        if not task_id:
            return ("has no producing task (ray_tpu.put / driver-created "
                    "objects are not reconstructable)")
        if getattr(e, "lineage_evicted", False):
            return ("its producing task's spec was evicted from the "
                    "lineage table (RAY_TPU_LINEAGE_BYTES cap)")
        spec = self._lineage_specs.get(task_id) \
            or self._respawnable_specs.get(task_id)
        if spec is None:
            return "its producing task's spec is not in the lineage table"
        if spec.actor_id is not None:
            return ("its producer was an actor method and is not "
                    "re-executable")
        if getattr(spec, "streaming", False):
            return ("its producer was a streaming generator (consumed "
                    "items cannot replay)")
        if getattr(spec, "reconstructions", 0) \
                >= self._max_reconstructions():
            return (f"its producer already re-executed "
                    f"{spec.reconstructions} times "
                    f"(RAY_TPU_MAX_RECONSTRUCTIONS cap)")
        _seen = _seen if _seen is not None else set()
        if task_id in _seen:
            return None  # this producer is already part of the chain
        _seen.add(task_id)
        maxd = self._max_reconstruction_depth()
        # lost ARGUMENTS first: every dep must be present or recoverable,
        # or the re-run would either hang pending or fail on an errored
        # dep — the recursion is what re-executes a whole producer chain
        for d in spec.dep_object_ids:
            de = self.gcs.objects.get(d)
            if de is None:
                return (f"argument {d} of {spec.name} was freed; cannot "
                        "re-execute")
            lost_dep = de.state == "error" and isinstance(
                de.error, ObjectLostError)
            if de.state == "error" and not lost_dep:
                return (f"argument {d} of {spec.name} failed to "
                        f"produce: {de.error!r}")
            if de.state == "pending" or (de.state == "ready"
                                         and self._object_live(de)):
                continue
            if depth + 1 > maxd:
                return (f"argument {d} of {spec.name} is lost and "
                        f"re-creating it would exceed "
                        f"RAY_TPU_MAX_RECONSTRUCTION_DEPTH={maxd}")
            why = self._reconstruct_object(
                d, depth=depth + 1,
                cause=f"lost argument of {spec.name}",
                node_id=node_id, _seen=_seen)
            if why is not None:
                return (f"argument {d} of {spec.name} is lost and not "
                        f"reconstructable: {why}")
        resubmit = te is None or te.state not in ("PENDING", "SCHEDULED",
                                                  "RUNNING")
        self._emit("object.lost",
                   f"{cause or 'payload lost'}; reconstructing via "
                   "recorded lineage",
                   severity="warning", object_id=oid, task_id=task_id,
                   node_id=node_id)
        # Reset ONLY this lost object — sibling returns with live
        # payloads keep serving reads; the re-run's seal refreshes them.
        e.state, e.loc, e.error, e.copies = "pending", None, None, []
        self._emit("object.reconstruct",
                   f"re-executing producer {spec.name} "
                   f"({'resubmitted' if resubmit else 'already queued'}"
                   f", depth {depth})",
                   object_id=oid, task_id=task_id, node_id=node_id,
                   name=spec.name, depth=depth)
        try:
            _mcat().get("ray_tpu_object_reconstructions_total").inc()
        except Exception:
            pass
        if resubmit:
            spec.reconstructions = getattr(spec, "reconstructions", 0) + 1
            if te is not None:
                te.state = "PENDING"
                te.finished_at = None
            self._respawnable_specs[task_id] = spec
            self.pending_tasks.append(spec)
            self._emit("task.retry",
                       f"lineage reconstruction of {oid}: "
                       f"{cause or 'payload lost'}",
                       task_id=task_id, object_id=oid, node_id=node_id,
                       name=spec.name)
            sys.stderr.write(
                f"[ray_tpu] reconstructing {spec.name} ({task_id}) for "
                f"lost object {oid}: {cause or 'payload lost'}\n")
        return None

    def _on_object_unreachable(self, oid: str, nid=None,
                               seq=None) -> None:
        """A reader exhausted the pull/relay paths against `oid`'s
        recorded locations (PullManager failover exhaustion, fetch
        timeout, holder gone): prune the copies it failed against and
        reconstruct unless a live candidate remains. Dispatcher only."""
        e = self.gcs.objects.get(oid)
        if e is None or e.state != "ready":
            return  # already reconstructing / freed / failed
        if seq is not None and seq != e.seal_seq:
            # the reader failed against an OLDER seal generation and a
            # reseal has landed since (e.g. a reconstruction that
            # finished while this report was in flight, possibly back
            # on the same rejoined node): don't prune the fresh copy —
            # the reader's retry will pick it up
            return
        if nid is not None:
            keep = [c for c in [e.loc, *e.copies]
                    if c is not None
                    and (getattr(c, "node_id", None)
                         or self.node_id) != nid]
        else:
            keep = [c for c in [e.loc, *e.copies] if c is not None]
        if keep:
            e.loc, e.copies = keep[0], keep[1:]
            if self._object_live(e):
                return  # a failover candidate remains; readers retry
        self._handle_lost_object(
            oid, e,
            cause="every recorded copy is unreachable"
                  + (f" (holder node {nid} did not serve the read)"
                     if nid else ""),
            node_id=nid)

    def _await_object(self, oid: str,
                      timeout: Optional[float] = 60.0):
        """Block until `oid` settles again; returns the waiter-style
        ("loc"|"error", payload) pair, or ("timeout", None). Helper/API
        threads only (never the dispatcher) — the shared wait behind
        _reload_one and the reconstruction retries in _worker_get."""
        ev = threading.Event()
        box: Dict[str, Any] = {}

        def cb(results, ready):
            box.update(results)
            ev.set()

        waiter = Waiter([oid], None, cb)
        self.inbox.put(("api_waiter", waiter))
        if not ev.wait(timeout):
            waiter.done = True
            return ("timeout", None)
        return box.get(oid, ("error", ObjectLostError(f"{oid} missing")))

    def _object_candidates(self, oid: str) -> List[Tuple[Any, Optional[str]]]:
        """Location-directory entries for one object: every live
        (location, holder transfer address) pair, primary first. Device
        locations are excluded — they materialize through the holder
        worker before any transfer. Dispatcher-thread only."""
        e = self.gcs.objects.get(oid)
        if e is None or e.state != "ready":
            return []
        out: List[Tuple[Any, Optional[str]]] = []
        for loc in [e.loc, *e.copies]:
            if loc is None or getattr(loc, "kind", None) == "device":
                continue
            nid = loc.node_id or self.node_id
            node = self.cluster_nodes.get(nid)
            if node is None or not node.alive:
                continue
            out.append((loc, self.transfer_addrs.get(nid)))
        return out

    def _count_relay(self, n: int) -> None:
        with self._relay_lock:   # helper threads relay concurrently
            self.relay_bytes += n
        try:
            _mcat().get("ray_tpu_transfer_relay_bytes_total").inc(n)
        except Exception:
            pass

    def _request_node_pull(self, requester_nid: str, oid: str,
                           candidates, timeout: float = 60.0):
        """Ask `requester_nid`'s agent to pull `oid` from a holder over
        the transfer plane; returns the fresh local ObjectLocation or
        None (caller falls back to the relay). Helper threads only."""
        ns = self.cluster_nodes.get(requester_nid)
        if ns is None or not ns.alive or ns.conn is None:
            return None
        if not any(addr for _loc, addr in candidates):
            return None  # no holder has a data-plane listener
        with self._fetch_lock:
            self._fetch_counter += 1
            rid = self._fetch_counter
            ev: threading.Event = threading.Event()
            box: dict = {}
            self._fetch_events[rid] = (ev, box)
        try:
            ns.conn.send(("pull_object", rid, oid, candidates))
        except ConnectionClosed:
            with self._fetch_lock:
                self._fetch_events.pop(rid, None)
            return None
        if not ev.wait(timeout=timeout):
            with self._fetch_lock:
                self._fetch_events.pop(rid, None)
            return None
        if box.get("err") is not None:
            return None
        return box.get("loc")

    def fetch_bytes(self, loc, oid: Optional[str] = None
                    ) -> "bytes | bytearray":
        """Pull a remote object's packed payload to this process. Peer
        path first: a direct TCP pull from the holder node's transfer
        server (driver sockets untouched); the control-connection relay
        through the holder's agent remains as the instrumented fallback.
        Called from API/helper threads (never the dispatcher — it blocks)."""
        addr = self.transfer_addrs.get(loc.node_id or "")
        if addr is not None:
            from . import object_transfer  # noqa: PLC0415
            t0 = time.time()
            try:
                data = object_transfer.pull_bytes(addr, oid or loc.name
                                                  or "?", loc)
            except Exception:  # fall back to relay (never swallow
                pass           # KeyboardInterrupt/SystemExit)
            else:
                try:
                    _mcat().get(
                        "ray_tpu_transfer_bytes_pulled_total").inc(
                        len(data))
                    _mcat().get("ray_tpu_transfer_pulls_total").inc(
                        tags={"result": "ok"})
                    _mcat().get(
                        "ray_tpu_transfer_pull_latency_s").observe(
                        time.time() - t0)
                except Exception:
                    pass
                return data
        ns = self.cluster_nodes.get(loc.node_id or "")
        if ns is None or not ns.alive or ns.conn is None:
            raise ObjectLostError(
                f"object payload lives on node {loc.node_id}, which is "
                "gone")
        with self._fetch_lock:
            self._fetch_counter += 1
            rid = self._fetch_counter
            ev: threading.Event = threading.Event()
            box: dict = {}
            self._fetch_events[rid] = (ev, box)
        try:
            ns.conn.send(("fetch_object", rid, loc))
        except ConnectionClosed:
            with self._fetch_lock:
                self._fetch_events.pop(rid, None)
            raise ObjectLostError(
                f"node {loc.node_id} connection lost during fetch") from None
        # Poll-wait so a holder death mid-fetch surfaces within ~a
        # second (the first send to a freshly-killed peer often lands in
        # the TCP buffer, so waiting the full budget would serialize a
        # dead node's timeout into every reader).
        deadline = time.time() + 60.0
        while not ev.wait(timeout=1.0):
            if not ns.alive:
                with self._fetch_lock:
                    self._fetch_events.pop(rid, None)
                raise ObjectLostError(
                    f"node {loc.node_id} died during fetch of "
                    f"{loc.name}")
            if time.time() > deadline:
                with self._fetch_lock:
                    self._fetch_events.pop(rid, None)
                raise ObjectLostError(
                    f"fetch of {loc.name} from node {loc.node_id} "
                    f"timed out")
        if box.get("err") is not None:
            err = box["err"]
            raise err if isinstance(err, BaseException) else \
                ObjectLostError(str(err))
        # these bytes crossed the driver's control connection: the peer
        # path was unavailable (no transfer server, or the pull failed)
        self._count_relay(len(box["data"]))
        return box["data"]

    def _load_location(self, loc) -> Any:
        """Materialize a value wherever its payload lives."""
        if loc.kind == "inline" or loc.node_id in (None, self.node_id):
            return self.store.get_value(loc)
        return serialization.unpack(self.fetch_bytes(loc))

    # ---------------- objects ----------------
    def _seal(self, oid: str, loc) -> None:
        e = self.gcs.seal_object(oid, loc)
        self._materializing.discard(oid)
        if self._persist is not None:
            self._persist.object_seal(e)
        self._emit("object.seal", object_id=oid, task_id=e.owner_task,
                   node_id=getattr(loc, "node_id", None) or self.node_id,
                   kind=getattr(loc, "kind", None),
                   size=getattr(loc, "size", None))
        self._spill.on_seal(oid, e.loc)
        self._notify_object(oid)

    # ---------------- streaming generators ----------------
    def _on_gen_item(self, task_id: str, oid: str, loc) -> None:
        self._seal(oid, loc)
        s = self._gen_streams.get(task_id)
        if s is None:
            return
        s.items.append(oid)
        self._gen_fire(s)

    # Fully-drained settled streams a consumer never took the terminal
    # reply for are kept for this many entries, then evicted
    # oldest-first (their item refs stay valid in the store;
    # _gen_lookup answers done/error from the task table). Settled
    # streams still HOLDING undrained items get a separate, larger
    # bound (_GEN_UNDRAINED_RETAIN): evicting one loses item refs, so
    # it happens only under sustained fire-and-forget abuse and
    # surfaces as an explicit ObjectLostError, never a silent "done".
    # Together they bound driver memory for fire-and-forget workloads.
    _GEN_SETTLED_RETAIN = 1024
    _GEN_UNDRAINED_RETAIN = 4096

    def _gen_settle(self, task_id: str, error=None) -> None:
        s = self._gen_streams.get(task_id)
        if s is None:
            return
        if error is None:
            s.done = True
        else:
            s.error = error
        self._gen_fire(s)
        if task_id not in self._gen_streams:     # drained+GC'd already
            return
        if s.items:
            self._gen_undrained.append(task_id)
            while len(self._gen_undrained) > self._GEN_UNDRAINED_RETAIN:
                old_id = self._gen_undrained.popleft()
                old = self._gen_streams.get(old_id)
                if old is None or not old.items:
                    continue  # drained in the meantime: retained deque
                              # (or the task table) already covers it
                self._gen_streams.pop(old_id, None)
                self._gen_evicted.append(old_id)
                self._gen_evicted_set.add(old_id)
                while len(self._gen_evicted) > self._GEN_UNDRAINED_RETAIN:
                    self._gen_evicted_set.discard(
                        self._gen_evicted.popleft())
        else:
            self._gen_retain(s)

    def _gen_retain(self, s: GenStream) -> None:
        """Enqueue a settled stream for retention-eviction — but ONLY
        once it holds no unconsumed item refs: evicting a stream with
        pending items would make _gen_lookup answer the task-table
        "done" fallback and silently lose them. Streams still holding
        items are re-enqueued by _gen_gc when their last item drains."""
        if s.items or s.retained:
            return
        s.retained = True
        self._gen_settled.append(s.task_id)
        while len(self._gen_settled) > self._GEN_SETTLED_RETAIN:
            old = self._gen_settled.popleft()
            self._gen_streams.pop(old, None)

    def _gen_reply(self, s: GenStream):
        """(kind, payload) if the stream can answer now, else None."""
        if s.items:
            return ("item", s.items.popleft())
        if s.error is not None:
            s.terminal_sent = True
            return ("error", s.error)
        if s.done:
            s.terminal_sent = True
            return ("done", None)
        return None

    def _gen_fire(self, s: GenStream) -> None:
        while s.waiters:
            head_cb, abandoned = s.waiters[0]
            if abandoned[0]:
                s.waiters.popleft()
                continue
            r = self._gen_reply(s)
            if r is None:
                break
            s.waiters.popleft()
            try:
                head_cb(r)
            except Exception:
                traceback.print_exc()
        self._gen_gc(s)

    def _gen_lookup(self, task_id: str):
        """(stream, None) for a live stream, else (None, terminal_reply).
        Finished streams are GC'd from _gen_streams; the task table keeps
        answering late/repeat consumers."""
        s = self._gen_streams.get(task_id)
        if s is not None:
            return s, None
        if task_id in self._gen_evicted_set:
            return None, ("error", ObjectLostError(
                f"streaming generator {task_id}: undrained item refs "
                f"were evicted (stream settled and was never consumed "
                f"past the retention bound)"))
        te = self.gcs.tasks.get(task_id)
        if te is None:
            return None, ("error", ValueError(
                f"no streaming generator for task {task_id}"))
        if te.state == "FINISHED":
            return None, ("done", None)
        if te.state == "CANCELLED":
            return None, ("error",
                          TaskCancelledError(f"task {task_id} cancelled"))
        return None, ("error", TaskError(
            f"streaming task {task_id} failed", "", te.name))

    def _gen_gc(self, s: GenStream) -> None:
        """Drop fully-drained settled streams (long-lived drivers submit
        unbounded numbers of generator tasks; _gen_lookup keeps answering
        from the task table afterwards)."""
        if s.terminal_sent and not s.items and not s.waiters:
            self._gen_streams.pop(s.task_id, None)
        elif (s.done or s.error is not None) and not s.items:
            # settled stream just fully drained its items (consumer has
            # not taken the terminal reply yet): now safe to bound
            self._gen_retain(s)

    def _gen_request(self, task_id: str, cb, abandoned) -> None:
        """Answer immediately if possible, else park the waiter."""
        s, terminal = self._gen_lookup(task_id)
        if s is None:
            cb(terminal)
            return
        r = self._gen_reply(s)
        if r is not None:
            cb(r)
            self._gen_gc(s)
            return
        s.waiters.append((cb, abandoned))

    def _gen_next_for_worker(self, w, rid: str, task_id: str) -> None:
        def send(result, w=w, rid=rid):
            if w is not None and w.conn is not None:
                try:
                    w.conn.send(("get_reply", rid, result))
                except ConnectionClosed:
                    pass

        s, terminal = self._gen_lookup(task_id)
        if s is None:
            send(terminal)
            return
        r = self._gen_reply(s)
        if r is not None:
            send(r)
            self._gen_gc(s)
            return
        # Must park: same blocked-worker protocol as _worker_get — while
        # a worker waits on the stream it lends its CPU back, else a
        # consumer task on a 1-CPU node deadlocks the generator feeding
        # it.
        blocked_here = (w is not None and w.state == "busy"
                        and not w.blocked)
        if blocked_here:
            w.blocked = True
            res_mod.release(self._wnode_avail(w),
                            _cpu_only(w.held_resources))
            self._reclaim_lease(w)

        def cb(result, w=w, rid=rid, blocked_here=blocked_here):
            self._gen_worker_waiters.pop(rid, None)
            if blocked_here and w is not None and w.blocked:
                w.blocked = False
                res_mod.acquire(self._wnode_avail(w),
                                _cpu_only(w.held_resources))
            send(result)

        abandoned = [False]
        self._gen_worker_waiters[rid] = (abandoned, w, blocked_here)
        s.waiters.append((cb, abandoned))

    def _gen_abandon_worker(self, rid: str) -> None:
        """A worker's gen_next timed out: mark its parked waiter so a
        later item is not popped into a reply nobody is waiting for, and
        restore the CPU the waiter had lent back. (If the reply already
        fired, the item was delivered to the timed-out rid and is lost —
        gen_next timeouts are inherently racy.)"""
        entry = self._gen_worker_waiters.pop(rid, None)
        if entry is None:
            return
        flag, w, blocked_here = entry
        flag[0] = True
        if blocked_here and w is not None and w.blocked \
                and w.state != "dead":
            w.blocked = False
            res_mod.acquire(self._wnode_avail(w),
                            _cpu_only(w.held_resources))

    def _fail_object(self, oid: str, err) -> None:
        self.gcs.fail_object(oid, err)
        self._notify_object(oid)

    def _notify_object(self, oid: str) -> None:
        for waiter_id in self.object_waiters.pop(oid, []):
            w = self.waiters.get(waiter_id)
            if w is None or w.done:
                continue
            if self._object_settled(oid, w.needs_bytes):
                w.settled.add(oid)
                if len(w.settled) >= w.num_returns:
                    self._fire_waiter(waiter_id, timed_out=False)
                    continue
            else:
                # still unsettled for this waiter — e.g. the seal
                # carried a DEVICE location and the bytes only land
                # with the holder's materialize re-seal: stay
                # subscribed or that re-seal would notify nobody
                self.object_waiters.setdefault(oid, []).append(
                    waiter_id)

    def _object_settled(self, oid: str, needs_bytes: bool = True) -> bool:
        e = self.gcs.objects.get(oid)
        if e is None:
            return False
        if (needs_bytes and e.state == "ready"
                and getattr(e.loc, "kind", None) == "device"):
            # the waiter needs BYTES but the value lives device-resident
            # in its producing worker (core/device_store.py): ask the
            # holder to materialize; the re-seal settles the waiter.
            # (Same-worker consumers never reach here — they hit the
            # worker-local table before sending a get_request.)
            self._request_materialize(oid, e)
            return False
        return e.state in ("ready", "error")

    def _request_materialize(self, oid: str, e) -> None:
        if oid in self._materializing:
            return
        w = self.workers.get(e.loc.name)
        if w is None or w.state == "dead" or w.conn is None:
            self._device_object_lost(oid, e)
            return
        self._materializing.add(oid)
        try:
            w.conn.send(("materialize", oid))
        except ConnectionClosed:
            # the holder is plainly dead even if its socket-close event
            # hasn't landed yet: run the FULL death handling (actor
            # death first, then device-object loss) so a dead actor's
            # objects fail with ActorDiedError, not ObjectLostError
            self._materializing.discard(oid)
            self._on_worker_dead(w.worker_id)

    def _device_object_lost(self, oid: str, e) -> None:
        """A device-resident object's holder is gone (or refused):
        re-run the producing task from the lineage log, or fail the
        object — the single-object analog of _reconstruct_lost_objects."""
        self._materializing.discard(oid)
        self._handle_lost_object(
            oid, e, cause="device-resident holder worker died")

    def _add_waiter(self, w: Waiter, timeout: Optional[float] = None):
        self.waiters[w.waiter_id] = w
        for oid in w.oids:
            if oid not in self.gcs.objects:
                self.gcs.add_pending_object(oid)
            if self._object_settled(oid, w.needs_bytes):
                w.settled.add(oid)
            else:
                self.object_waiters.setdefault(oid, []).append(w.waiter_id)
        if len(w.settled) >= w.num_returns:
            self._fire_waiter(w.waiter_id, timed_out=False)
        if not w.done and timeout is not None:
            t = threading.Timer(
                timeout, lambda: self.inbox.put(("waiter_timeout", w.waiter_id)))
            t.daemon = True
            t.start()

    def _fire_waiter(self, waiter_id: int, timed_out: bool):
        w = self.waiters.pop(waiter_id, None)
        if w is None or w.done:
            return
        w.done = True
        results: Dict[str, Tuple[str, Any]] = {}
        ready: List[str] = []
        for oid in w.oids:
            e = self.gcs.objects.get(oid)
            if e is None or e.state == "pending":
                continue
            if (w.needs_bytes and e.state == "ready"
                    and getattr(e.loc, "kind", None) == "device"):
                continue  # bytes not host-side yet (timed-out fire)
            ready.append(oid)
            if e.state == "ready":
                results[oid] = ("loc", e.loc)
            else:
                results[oid] = ("error", e.error)
        try:
            w.callback(results, ready)
        except Exception:
            traceback.print_exc()

    # ---------------- tasks ----------------
    def _register_task(self, spec: TaskSpec):
        te = TaskEntry(task_id=spec.task_id, name=spec.name,
                       actor_id=spec.actor_id, submitted_at=time.time(),
                       retries_left=spec.max_retries,
                       trace_id=getattr(spec, "trace_id", ""),
                       span_id=getattr(spec, "span_id", ""),
                       parent_span_id=getattr(spec, "parent_span_id", ""))
        self.gcs.tasks[spec.task_id] = te
        _mcat().get("ray_tpu_tasks_submitted_total").inc(tags={
            "kind": "actor_task" if spec.actor_id else "task"})
        self._emit("task.submit", task_id=spec.task_id,
                   actor_id=spec.actor_id, name=spec.name)
        for oid in spec.return_ids:
            self.gcs.add_pending_object(oid, owner_task=spec.task_id)
        if getattr(spec, "streaming", False):
            self._gen_streams[spec.task_id] = GenStream(spec.task_id)
        if spec.actor_id is not None:
            aentry = self.gcs.actors.get(spec.actor_id)
            if aentry is None or aentry.state == "DEAD":
                err = ActorDiedError(
                    f"actor {spec.actor_id} is dead"
                    + (f": {aentry.death_cause}" if aentry else ""))
                te.state = "FAILED"
                for oid in spec.return_ids:
                    self._fail_object(oid, err)
                self._gen_settle(spec.task_id, err)
                return
            self.actor_queues.setdefault(spec.actor_id,
                                         collections.deque()).append(spec)
        else:
            self.pending_tasks.append(spec)

    def _register_actor_creation(self, acspec: ActorCreationSpec):
        ae = ActorEntry(actor_id=acspec.actor_id, name=acspec.name,
                        namespace=acspec.namespace,
                        class_name=acspec.class_name,
                        resources=dict(acspec.resources),
                        max_restarts=acspec.max_restarts,
                        create_spec=acspec)
        self.gcs.actors[acspec.actor_id] = ae
        self._emit("actor.create", actor_id=acspec.actor_id,
                   class_name=acspec.class_name, name=acspec.name)
        if acspec.name:
            ok = self.gcs.register_named_actor(
                acspec.namespace, acspec.name, acspec.actor_id)
            if not ok:
                ae.state = "DEAD"
                ae.death_cause = f"name {acspec.name!r} already taken"
                self._emit("actor.death", ae.death_cause,
                           actor_id=acspec.actor_id,
                           class_name=acspec.class_name)
                if self._persist is not None:
                    self._persist.actor_create(ae)
                return
        if self._persist is not None:
            self._persist.actor_create(ae)
        self.actor_max_conc[acspec.actor_id] = acspec.max_concurrency
        self.actor_group_conc[acspec.actor_id] = dict(
            getattr(acspec, "concurrency_groups", None) or {})
        self.pending_actors.append(acspec)

    # ---------------- scheduling ----------------
    _PENDING_WARN_S = 10.0

    def _warn_if_stuck(self, key: str, what: str,
                       need: Dict[str, float]) -> None:
        """One-time stderr warning when a task/actor has been pending
        past _PENDING_WARN_S with nowhere to place it — exhausted CPU
        slots hang silently otherwise (a Gateway+controller+replica app
        on init(num_cpus=2) waits forever with zero feedback)."""
        now = time.time()
        first = self._pending_since.setdefault(key, now)
        if key in self._pending_warned \
                or now - first < self._PENDING_WARN_S:
            return
        self._pending_warned.add(key)
        self._emit("scheduler.backpressure",
                   f"{what} pending {now - first:.0f}s: requires "
                   f"{need or '{}'} with no feasible placement",
                   task_id=key if key.startswith("tsk-") else None,
                   actor_id=key if key.startswith("act-") else None)
        cap = {}
        avail = {}
        for ns in self.cluster_nodes.values():
            if not ns.alive:
                continue
            for r, v in ns.total.items():
                cap[r] = cap.get(r, 0) + v
            for r, v in ns.avail.items():
                avail[r] = avail.get(r, 0) + v
        sys.stderr.write(
            f"[ray_tpu] WARNING: {what} has been pending for "
            f"{now - first:.0f}s: requires {need or '{}'}, cluster "
            f"capacity {cap}, currently free {avail}. If demand exceeds "
            f"capacity it will wait forever — raise init(num_cpus=...) "
            f"or free resources.\n")

    def _deps_ready(self, dep_ids: List[str]) -> Optional[bool]:
        """True = all ready; False = still pending; None = a dep errored."""
        ok = True
        for oid in dep_ids:
            e = self.gcs.objects.get(oid)
            if e is None or e.state == "pending":
                ok = False
            elif e.state == "error":
                return None
        return ok

    def _alive_nodes(self) -> List[NodeState]:
        """Driver node first (locality), then remote nodes by id."""
        out = []
        drv = self.cluster_nodes.get(self.node_id)
        if drv is not None and drv.alive:
            out.append(drv)
        out.extend(sorted(
            (n for n in self.cluster_nodes.values()
             if n.alive and n.node_id != self.node_id),
            key=lambda n: n.node_id))
        return out

    def _solve_pg(self, pg: PlacementGroupState) -> Optional[List[str]]:
        """Assign each bundle a node per the strategy, against current
        availability. Returns node ids per bundle, None if not (yet)
        possible. Raises PlacementGroupError for STRICT_SPREAD that can
        never fit the alive topology (ref: gcs_placement_group_scheduler.cc
        strategy handling)."""
        nodes = self._alive_nodes()
        if not nodes:
            return None

        def fits_all_on(node: NodeState, bundles) -> bool:
            total: Dict[str, float] = {}
            for b in bundles:
                for k, v in b.items():
                    total[k] = total.get(k, 0.0) + v
            return res_mod.fits(node.avail, total)

        if pg.strategy in ("STRICT_PACK", "PACK"):
            for n in nodes:
                if fits_all_on(n, pg.bundles):
                    return [n.node_id] * len(pg.bundles)
            if pg.strategy == "STRICT_PACK":
                return None
            # PACK (non-strict): greedy first-fit across nodes
            scratch = {n.node_id: dict(n.avail) for n in nodes}
            assignment = []
            for b in pg.bundles:
                for n in nodes:
                    if res_mod.fits(scratch[n.node_id], b):
                        res_mod.acquire(scratch[n.node_id], b)
                        assignment.append(n.node_id)
                        break
                else:
                    return None
            return assignment
        if pg.strategy == "STRICT_SPREAD":
            if len(pg.bundles) > len(nodes):
                raise PlacementGroupError(
                    f"STRICT_SPREAD needs {len(pg.bundles)} distinct "
                    f"nodes; only {len(nodes)} alive")
            # greedy distinct-node matching (bundles are usually uniform)
            used: set = set()
            assignment = []
            for b in pg.bundles:
                for n in nodes:
                    if n.node_id not in used and res_mod.fits(n.avail, b):
                        used.add(n.node_id)
                        assignment.append(n.node_id)
                        break
                else:
                    return None
            return assignment
        # SPREAD (best-effort round-robin, reusing nodes when needed)
        scratch = {n.node_id: dict(n.avail) for n in nodes}
        assignment = []
        start = 0
        for b in pg.bundles:
            placed = False
            for j in range(len(nodes)):
                n = nodes[(start + j) % len(nodes)]
                if res_mod.fits(scratch[n.node_id], b):
                    res_mod.acquire(scratch[n.node_id], b)
                    assignment.append(n.node_id)
                    start = (start + j + 1) % len(nodes)
                    placed = True
                    break
            if not placed:
                return None
        return assignment

    def _pg_allowed_nodes(self, pg_id: Optional[str],
                          bundle_index: int) -> Optional[List[str]]:
        """Node ids a pg-bound task/actor may run on; None = pg not ready
        (requeue); empty list = unconstrained."""
        if pg_id is None:
            return []
        pg = self.placement_groups.get(pg_id)
        if pg is None or pg.state != "CREATED":
            return None
        if 0 <= bundle_index < len(pg.bundle_nodes):
            return [pg.bundle_nodes[bundle_index]]
        return list(dict.fromkeys(pg.bundle_nodes))

    def _schedule(self):
        # 0. pending placement groups admit as resources free up
        for pg in list(self.placement_groups.values()):
            if pg.state == "PENDING":
                try:
                    assignment = self._solve_pg(pg)
                except PlacementGroupError as e:
                    # Topology-infeasible *right now* — but nodes may
                    # still be joining (a STRICT_SPREAD created before
                    # remote agents register must not fail instantly).
                    # Only declare infeasibility after a grace window.
                    grace = knobs.get_float(
                        "RAY_TPU_PG_INFEASIBLE_GRACE_S")
                    if time.time() - pg.created_at < grace:
                        continue
                    pg.state = "INFEASIBLE"
                    self._fail_object(pg.ready_ref, e)
                    continue
                if assignment is None:
                    continue
                pg.bundle_tpu_ids = []
                for b, nid in zip(pg.bundles, assignment):
                    node = self.cluster_nodes[nid]
                    res_mod.acquire(node.avail, b)
                    k = int(b.get("TPU", 0))
                    pg.bundle_tpu_ids.append(node.free_tpu_ids[:k])
                    del node.free_tpu_ids[:k]
                pg.bundle_nodes = assignment
                pg.state = "CREATED"
                self._seal(pg.ready_ref,
                           self.store.put_value(pg.ready_ref, True))

        # 0.5 compiled-DAG placements waiting on worker spawns
        if self._dag_acquires:
            self._process_dag_acquires()

        # 1. actor creations (dedicated worker each)
        still = collections.deque()
        while self.pending_actors:
            acspec = self.pending_actors.popleft()
            dr = self._deps_ready(acspec.dep_object_ids)
            if dr is None:
                ae = self.gcs.actors[acspec.actor_id]
                ae.state, ae.death_cause = "DEAD", "constructor arg errored"
                self._persist_actor_state(ae)
                continue
            if dr is False:
                still.append(acspec)
                continue
            allowed = self._pg_allowed_nodes(
                getattr(acspec, "placement_group_id", None),
                getattr(acspec, "bundle_index", -1))
            if allowed is None:
                still.append(acspec)
                continue
            need = {} if getattr(acspec, "placement_group_id", None) \
                else acspec.resources
            strat = getattr(acspec, "scheduling_strategy", None)
            hard = sched_mod.hard_affinity_node(strat)
            if hard is not None and not allowed:
                hn = self.cluster_nodes.get(hard)
                if hn is None or not hn.alive:
                    ae = self.gcs.actors[acspec.actor_id]
                    ae.state = "DEAD"
                    ae.death_cause = (f"NodeAffinity target node {hard!r} "
                                      "is dead or unknown")
                    self._persist_actor_state(ae)
                    continue
            tries, spread = sched_mod.strategy_plan(strat, allowed)
            node = None
            for att in tries:
                node = self._pick_node(need, att, spread=spread)
                if node is not None:
                    break
            if node is None:
                self._warn_if_stuck(
                    acspec.actor_id,
                    f"actor {acspec.class_name} ({acspec.actor_id})",
                    need)
                still.append(acspec)
                continue
            self._pending_since.pop(acspec.actor_id, None)
            res_mod.acquire(node.avail, need)
            self._actor_create_specs[acspec.actor_id] = acspec
            wid = self._spawn_worker(purpose=acspec.actor_id,
                                     node_id=node.node_id)
            w = self.workers[wid]
            w.held_resources = dict(need)
            if getattr(acspec, "placement_group_id", None) is not None:
                acspec.tpu_ids = self._pg_tpu_ids(
                    acspec.placement_group_id, acspec.bundle_index,
                    node.node_id)
            else:
                acspec.tpu_ids = self._take_tpu_ids(node, need, w)
            w.actor_id = acspec.actor_id
        self.pending_actors = still

        # 1.5 actor restarts: same fit/pg rules as creation, but without
        # re-checking constructor deps (they were consumed at creation)
        still = collections.deque()
        while self.pending_restarts:
            aid = self.pending_restarts.popleft()
            ae = self.gcs.actors.get(aid)
            if ae is None or ae.state != "RESTARTING":
                continue
            acspec: ActorCreationSpec = ae.create_spec
            allowed = self._pg_allowed_nodes(
                getattr(acspec, "placement_group_id", None),
                getattr(acspec, "bundle_index", -1))
            if allowed is None:
                still.append(aid)
                continue
            need = {} if getattr(acspec, "placement_group_id", None) \
                else acspec.resources
            strat = getattr(acspec, "scheduling_strategy", None)
            hard = sched_mod.hard_affinity_node(strat)
            if hard is not None and not allowed:
                hn = self.cluster_nodes.get(hard)
                if hn is None or not hn.alive:
                    ae.state = "DEAD"
                    ae.death_cause = (f"NodeAffinity target node {hard!r} "
                                      "died; cannot restart pinned actor")
                    self._persist_actor_state(ae)
                    # queued method calls fail via the DEAD branch of the
                    # actor-task scheduling section below
                    continue
            tries, spread = sched_mod.strategy_plan(strat, allowed)
            node = None
            for att in tries:
                node = self._pick_node(need, att, spread=spread)
                if node is not None:
                    break
            if node is None:
                still.append(aid)
                continue
            res_mod.acquire(node.avail, need)
            self._actor_create_specs[aid] = acspec
            new_wid = self._spawn_worker(purpose=aid, node_id=node.node_id)
            nw = self.workers[new_wid]
            nw.held_resources = dict(need)
            if getattr(acspec, "placement_group_id", None) is not None:
                acspec.tpu_ids = self._pg_tpu_ids(
                    acspec.placement_group_id, acspec.bundle_index,
                    node.node_id)
            else:
                acspec.tpu_ids = self._take_tpu_ids(node, need, nw)
            nw.actor_id = aid
        self.pending_restarts = still

        # 2. normal tasks
        # 2.0 two-level scheduling: the head run of same-shape
        # leaseable tasks goes to node agents in bulk; leftovers fall
        # through to per-worker placement below
        if self._node_leases_enabled:
            self._grant_node_leases()
        still = collections.deque()
        # CPU tasks may fall back onto idle TPU workers only when no TPU
        # task is waiting — otherwise a CPU backlog ahead of a TPU task
        # would repeatedly steal the one worker that can run it.
        tpu_demand = any(s.resources.get("TPU", 0) > 0
                         for s in self.pending_tasks)
        # Placement for an unconstrained task depends only on its
        # resource shape, so once one shape fails to place in this pass
        # every identical task behind it would fail the same way — skip
        # them (a 1k-task fan-out used to pay ~130 full placement
        # evaluations PER TASK across the passes of its drain).
        blocked_shapes: set = set()
        while self.pending_tasks:
            spec = self.pending_tasks.popleft()
            te = self.gcs.tasks[spec.task_id]
            if te.state == "CANCELLED":
                continue
            shape = None
            if spec.placement_group_id is None and (
                    spec.scheduling_strategy is None
                    or spec.scheduling_strategy == "DEFAULT"):
                shape = tuple(sorted(spec.resources.items()))
                if shape in blocked_shapes:
                    still.append(spec)
                    continue
            dr = self._deps_ready(spec.dep_object_ids)
            if dr is None:
                te.state = "FAILED"
                self._respawnable_specs.pop(spec.task_id, None)
                err = TaskError("upstream dependency failed", "", spec.name)
                for oid in spec.return_ids:
                    self._fail_object(oid, err)
                self._gen_settle(spec.task_id, err)
                continue
            if dr is False:
                still.append(spec)
                continue
            allowed = self._pg_allowed_nodes(spec.placement_group_id,
                                             spec.bundle_index)
            if allowed is None:
                still.append(spec)
                continue
            need = spec.resources if spec.placement_group_id is None else {}
            task_needs_tpu = spec.resources.get("TPU", 0) > 0
            hard = sched_mod.hard_affinity_node(spec.scheduling_strategy)
            if hard is not None and not allowed:
                hn = self.cluster_nodes.get(hard)
                if hn is None or not hn.alive:
                    te.state = "FAILED"
                    self._respawnable_specs.pop(spec.task_id, None)
                    err = TaskError(
                        f"NodeAffinity target node {hard!r} is dead or "
                        "unknown", "", spec.name)
                    for oid in spec.return_ids:
                        self._fail_object(oid, err)
                    continue
            tries, spread = sched_mod.strategy_plan(
                spec.scheduling_strategy, allowed)
            w = None
            if (not spread and hard is None
                    and spec.placement_group_id is None):
                # device-object locality: a task consuming a device-
                # resident dep runs on its holding worker when that
                # worker is free — the dep is then served from the
                # in-process table with zero D2H/serialization
                w = self._device_locality_worker(
                    spec, need, task_needs_tpu, allowed,
                    allow_tpu_fallback=not tpu_demand)
                if w is None:
                    # store-object locality (transfer-plane hint): prefer
                    # an idle worker on the node already holding the
                    # task's dep payloads — the arg fetch then becomes a
                    # local shm read instead of a peer pull. Soft: falls
                    # through to normal placement when no such worker is
                    # free (reference: locality-aware lease targeting).
                    for lnid in self._dep_locality_nodes(spec):
                        if allowed and lnid not in allowed:
                            continue
                        w = self._find_idle_worker(
                            needs_tpu=task_needs_tpu,
                            allow_tpu_fallback=not tpu_demand,
                            allowed_nodes=[lnid], need=need)
                        if w is not None:
                            break
            if w is None and spread:
                # SPREAD is node-first round-robin: assign the task a
                # target node once (sticky across scheduling passes —
                # re-rolling every pass would collapse onto whichever
                # node has warm workers) and insist on a worker THERE,
                # spawning one if allowed.
                target = getattr(spec, "_spread_target", None)
                tn = self.cluster_nodes.get(target) if target else None
                if tn is None or not tn.alive:
                    tn = self._pick_node(need, [], spread=True)
                    if tn is not None:
                        spec._spread_target = tn.node_id
                if tn is not None:
                    w = self._find_idle_worker(
                        needs_tpu=task_needs_tpu,
                        allow_tpu_fallback=not tpu_demand,
                        allowed_nodes=[tn.node_id], need=need)
                    if w is None:
                        if self._can_spawn(tn, needs_tpu=task_needs_tpu):
                            self._spawn_worker(purpose=None,
                                               tpu_capable=task_needs_tpu,
                                               node_id=tn.node_id)
                            still.append(spec)
                            continue
                        # target saturated and can't grow: best-effort
                        # spread — fall through and run anywhere rather
                        # than starve behind the pinned node
            if w is None:
                for att in tries:
                    w = self._find_idle_worker(
                        needs_tpu=task_needs_tpu,
                        allow_tpu_fallback=not tpu_demand,
                        allowed_nodes=att, need=need)
                    if w is not None:
                        break
            if w is None:
                for att in tries:
                    node = self._pick_node(need, att, spread=spread)
                    if node is not None and self._can_spawn(
                            node, needs_tpu=task_needs_tpu):
                        self._spawn_worker(purpose=None,
                                           tpu_capable=task_needs_tpu,
                                           node_id=node.node_id)
                        break
                else:
                    self._warn_if_stuck(spec.task_id,
                                        f"task {spec.name}", need)
                if shape is not None:
                    blocked_shapes.add(shape)
                still.append(spec)
                continue
            self._pending_since.pop(spec.task_id, None)
            node = self.cluster_nodes[w.node_id]
            if spec.placement_group_id is not None:
                spec.tpu_ids = self._pg_tpu_ids(
                    spec.placement_group_id, spec.bundle_index, w.node_id)
            else:
                spec.tpu_ids = self._take_tpu_ids(node, need, w)
            # Lease fill (raylet-style, collapsed to the worker level):
            # grant this worker a bounded batch of compatible queued
            # tasks in ONE frame. The worker executes them strictly
            # FIFO against the single resource slot the lease holds;
            # results return in batched frames. Fill is capped so other
            # idle capacity still gets its share (a 2-CPU host splits a
            # fan-out across both workers, never serializes it onto
            # one), and reclaimed if the running head blocks in get().
            lease = [spec]
            if self._lease_cap > 1 and sched_mod.leaseable(spec):
                fill = self._lease_fill_count(need)
                while len(lease) < fill and self.pending_tasks:
                    cand = self.pending_tasks[0]
                    cte = self.gcs.tasks.get(cand.task_id)
                    if cte is not None and cte.state == "CANCELLED":
                        self.pending_tasks.popleft()
                        continue
                    if (not sched_mod.leaseable(cand)
                            or cand.resources != spec.resources
                            or self._deps_ready(cand.dep_object_ids)
                            is not True):
                        break   # contiguous prefix only: FIFO preserved
                    self.pending_tasks.popleft()
                    self._pending_since.pop(cand.task_id, None)
                    lease.append(cand)
            if len(lease) > 1:
                # Stamp the lease id onto every spec BEFORE the wire
                # send: the worker's exec spans carry it as a span
                # attribute, so the timeline can join a multi-task
                # grant back to the lease_grant span without any extra
                # frames (flight recorder, docs/OBSERVABILITY.md).
                lid = f"lease-{w.worker_id}-{self.lease_grants + 1}"
                for s in lease:
                    s.lease_id = lid
            try:
                if len(lease) == 1:
                    w.conn.send(("exec_task", spec))
                else:
                    w.conn.send(("exec_task_many", lease))
            except ConnectionClosed:
                # Worker socket just broke: its death event will arrive via
                # the reader thread; requeue the specs and keep scheduling.
                self._return_tpu_ids(w)
                w.state = "dying"
                still.extend(lease)
                continue
            self.dispatch_frames += 1
            self.dispatched_tasks += len(lease)
            if self._revoked_set:
                # a task reclaimed from this worker earlier may be
                # re-dispatched right back to it — its NEW result must
                # not be dropped by the stale-result guard
                for s in lease:
                    self._revoked_set.discard((w.worker_id, s.task_id))
            res_mod.acquire(node.avail, need)
            w.state = "busy"
            w.lease = collections.deque(s.task_id for s in lease)
            w.current_task = spec.task_id
            w.held_resources = dict(need)
            now = time.time()
            w.last_progress = now
            for s in lease:
                ste = self.gcs.tasks[s.task_id]
                ste.state, ste.worker_id, ste.started_at = (
                    "RUNNING", w.worker_id, now)
                if ste.submitted_at:
                    _mcat().get("ray_tpu_task_sched_latency_s").observe(
                        now - ste.submitted_at)
                self._emit("task.sched", task_id=s.task_id,
                           worker_id=w.worker_id, node_id=w.node_id,
                           name=s.name)
            if len(lease) > 1:
                self.lease_grants += 1
                self._emit("task.lease.grant",
                           f"granted worker {w.worker_id} a "
                           f"{len(lease)}-slot task lease",
                           worker_id=w.worker_id, node_id=w.node_id,
                           task_id=spec.task_id, slots=len(lease))
                if knobs.get_bool("RAY_TPU_FASTPATH_SPANS"):
                    # driver-local instant span: zero wire traffic,
                    # joined to the workers' exec spans by lease_id
                    self.trace_spans.append({
                        "trace_id": spec.trace_id,
                        "span_id": spec.lease_id,
                        "parent_span_id": spec.parent_span_id,
                        "task_id": spec.task_id,
                        "name": f"lease_grant:{len(lease)}",
                        "cat": "lease_grant",
                        "start": now, "end": now, "status": "ok",
                        "pid": os.getpid(), "worker_id": "driver",
                        "node_id": self.node_id,
                        "lease_id": spec.lease_id,
                        "slots": len(lease)})
                try:
                    _mcat().get("ray_tpu_lease_grants_total").inc()
                    _mcat().get("ray_tpu_dispatch_batch_size").observe(
                        len(lease))
                except Exception:
                    pass
        self.pending_tasks = still

        # 3. actor tasks
        for aid, q in list(self.actor_queues.items()):
            ae = self.gcs.actors.get(aid)
            if ae is None:
                continue
            if ae.state == "DEAD":
                while q:
                    spec = q.popleft()
                    err = ActorDiedError(f"actor {aid} died: {ae.death_cause}")
                    self.gcs.tasks[spec.task_id].state = "FAILED"
                    for oid in spec.return_ids:
                        self._fail_object(oid, err)
                    self._gen_settle(spec.task_id, err)
                continue
            if ae.state != "ALIVE":
                continue
            w = self._worker_for_actor(aid)
            if w is None or w.conn is None:
                continue
            maxc = self.actor_max_conc.get(aid, 1)
            group_limits = self.actor_group_conc.get(aid) or {}
            # Pipeline window: dispatch up to `pipeline` calls BEYOND
            # each lane's concurrency limit. Execution concurrency is
            # enforced in the worker (thread/group pools, async lane
            # semaphores), so the extra slots only pre-stage specs in
            # the worker's queue — one batched frame replaces a
            # dispatch round-trip per call.
            pipeline = self._actor_pipeline
            to_send: List[TaskSpec] = []

            def admit(spec, group) -> bool:
                """Validate one spec for this dispatch round. False =
                consumed without dispatch (dep-failed / cancelled)."""
                if self._deps_ready(spec.dep_object_ids) is None:
                    err = TaskError("upstream dependency failed", "",
                                    spec.name)
                    self.gcs.tasks[spec.task_id].state = "FAILED"
                    for oid in spec.return_ids:
                        self._fail_object(oid, err)
                    self._gen_settle(spec.task_id, err)
                    return False
                te = self.gcs.tasks[spec.task_id]
                if te.state == "CANCELLED":
                    return False
                self.actor_group_inflight[(aid, group)] = \
                    self.actor_group_inflight.get((aid, group), 0) + 1
                te.concurrency_group = group
                to_send.append(spec)
                return True

            if not group_limits:
                # fast path (no concurrency groups): strict-FIFO
                # popleft, O(1) per dispatch
                while q and self.actor_group_inflight.get(
                        (aid, None), 0) < maxc + pipeline:
                    dr = self._deps_ready(q[0].dep_object_ids)
                    if dr is False:
                        break
                    admit(q.popleft(), None)
            else:
                # Group-aware dispatch (reference: python/ray/actor.py
                # concurrency_groups): each named group has an
                # independent in-flight limit, so a saturated/
                # dep-blocked group is skipped while OTHER groups'
                # tasks behind it still run — a health-check method
                # never starves behind a long call. One rotation pass
                # of the deque (O(n), no remove scans); order WITHIN a
                # group stays strictly FIFO (blocked set).
                blocked: set = set()
                for _ in range(len(q)):
                    spec = q.popleft()
                    group = (spec.concurrency_group
                             if spec.concurrency_group in group_limits
                             else None)   # None = the default maxc lane
                    limit = (group_limits[group] if group else maxc) \
                        + pipeline
                    if (group in blocked
                            or self.actor_group_inflight.get(
                                (aid, group), 0) >= limit
                            or self._deps_ready(spec.dep_object_ids)
                            is False):
                        blocked.add(group)
                        q.append(spec)   # rotate to the back, order kept
                        continue
                    admit(spec, group)
            if not to_send:
                continue
            try:
                if len(to_send) == 1:
                    w.conn.send(("exec_actor_task", to_send[0]))
                else:
                    w.conn.send(("exec_actor_task_many", to_send))
            except ConnectionClosed:
                # conn died mid-dispatch: unwind the bookkeeping and put
                # the specs BACK so the actor-death path fails them with
                # ActorDiedError — dropping them here leaves their
                # return objects pending forever (observed as a flaky
                # get() timeout after actor_exit raced a method call)
                for spec in reversed(to_send):
                    te = self.gcs.tasks[spec.task_id]
                    gkey = (aid, te.concurrency_group)
                    self.actor_group_inflight[gkey] = max(
                        0, self.actor_group_inflight.get(gkey, 0) - 1)
                    q.appendleft(spec)
                continue
            self.dispatch_frames += 1
            self.dispatched_tasks += len(to_send)
            now = time.time()
            for spec in to_send:
                te = self.gcs.tasks[spec.task_id]
                te.state, te.worker_id, te.started_at = ("RUNNING",
                                                         w.worker_id,
                                                         now)
                if te.submitted_at:
                    _mcat().get("ray_tpu_task_sched_latency_s").observe(
                        now - te.submitted_at)
                self._emit("task.sched", task_id=spec.task_id,
                           worker_id=w.worker_id, node_id=w.node_id,
                           actor_id=aid, name=spec.name)
            if len(to_send) > 1:
                try:
                    _mcat().get("ray_tpu_dispatch_batch_size").observe(
                        len(to_send))
                except Exception:
                    pass

    def _pg_tpu_ids(self, pg_id: Optional[str], bundle_index: int,
                    node_id: str) -> List[int]:
        """Chip indices a placement-group task may use: its bundle's
        reserved ids (bundle pinned), else every id the group reserved on
        the task's node. These release with the GROUP, not the task."""
        pg = self.placement_groups.get(pg_id) if pg_id else None
        if pg is None or pg.state != "CREATED":
            return []
        if 0 <= bundle_index < len(pg.bundle_tpu_ids):
            return list(pg.bundle_tpu_ids[bundle_index])
        out: List[int] = []
        for nid, ids in zip(pg.bundle_nodes, pg.bundle_tpu_ids):
            if nid == node_id:
                out.extend(ids)
        return sorted(set(out))

    def _take_tpu_ids(self, node: NodeState, need: Dict[str, float],
                      w: WorkerState) -> List[int]:
        """Reserve specific chip indices for `need`'s TPU count on the
        worker; returned via _return_tpu_ids when the resources release."""
        k = int(need.get("TPU", 0))
        if k <= 0:
            return []
        ids = node.free_tpu_ids[:k]
        del node.free_tpu_ids[:k]
        w.held_tpu_ids = ids
        return ids

    def _return_tpu_ids(self, w: WorkerState) -> None:
        if not w.held_tpu_ids:
            return
        node = self.cluster_nodes.get(w.node_id or self.node_id)
        if node is not None and node.alive:
            node.free_tpu_ids = sorted(
                set(node.free_tpu_ids) | set(w.held_tpu_ids))
        w.held_tpu_ids = []

    # ---------------- worker leases ----------------
    def _lease_fill_count(self, need: Dict[str, float]) -> int:
        """How many queued tasks one lease grant may take: bounded by
        RAY_TPU_LEASE_SLOTS and by the queue's fair share of the
        cluster's parallelism for this resource shape — a 2-CPU host
        splits a fan-out across both workers instead of serializing it
        onto whichever was found first."""
        remaining = len(self.pending_tasks) + 1
        par = 0
        for n in self._alive_nodes():
            cap = None
            for r, v in need.items():
                if v <= 0:
                    continue
                c = int(n.total.get(r, 0.0) // v)
                cap = c if cap is None else min(cap, c)
            if cap is None:
                cap = int(n.total.get("CPU", 1)) or 1
            par += cap
        par = max(1, par)
        return max(1, min(self._lease_cap, -(-remaining // par)))

    def _check_lease_watchdog(self) -> None:
        """Reaper-tick backstop: a leased head that stalls WITHOUT
        parking in a driver-visible verb (a gang task spinning in a
        user-space rendezvous poll, a long compute) keeps its unstarted
        slots pinned — the blocked-head reclaim never fires because the
        driver never hears a get/wait. Past RAY_TPU_LEASE_HEAD_S of no
        completions, reclaim the followers; long tasks don't benefit
        from batching anyway, and gang peers stuck behind the head get
        to run elsewhere (pre-lease, one-task-per-dispatch gave them
        separate workers unconditionally)."""
        if self._lease_cap <= 1:
            return
        stall = knobs.get_float("RAY_TPU_LEASE_HEAD_S")
        if stall <= 0:
            return
        now = time.time()
        for w in self.workers.values():
            if (w.state == "busy" and len(w.lease) > 1
                    and not w.blocked
                    and now - w.last_progress > stall):
                self._reclaim_lease(w)

    def _revoked_add(self, wid: str, tid: str) -> None:
        self._revoked_set.add((wid, tid))
        self._revoked_q.append((wid, tid))
        while len(self._revoked_q) > 4096:
            self._revoked_set.discard(self._revoked_q.popleft())

    def _reclaim_lease(self, w: WorkerState) -> None:
        """A leased worker's running head blocked in get()/gen_next:
        slots behind it would wait on the head (or deadlock, if the
        head waits on one of them) — re-queue them for other workers
        and fence this worker with revoke_tasks. The revoke frame is
        sent BEFORE the blocking verb's reply, so on the FIFO
        connection the worker sees it before its main thread can
        resume; a result that slips through anyway (user-thread get)
        is dropped via _revoked_set."""
        if len(w.lease) <= 1:
            return
        head = w.lease.popleft()
        revoked = list(w.lease)
        w.lease = collections.deque([head])
        w.current_task = head
        for tid in revoked:
            self._revoked_add(w.worker_id, tid)
            te = self.gcs.tasks.get(tid)
            spec = self._respawnable_specs.get(tid)
            if te is not None and te.state == "RUNNING" \
                    and spec is not None:
                te.state, te.worker_id = "PENDING", None
                self.pending_tasks.append(spec)
        self.lease_revokes += 1
        self._emit("task.lease.revoke",
                   f"worker {w.worker_id} blocked in get(); "
                   f"{len(revoked)} unstarted lease slots re-queued",
                   worker_id=w.worker_id, node_id=w.node_id,
                   task_id=head, slots=len(revoked) + 1)
        try:
            _mcat().get("ray_tpu_lease_revokes_total").inc(
                tags={"reason": "worker_blocked"})
        except Exception:
            pass
        try:
            w.conn.send(("revoke_tasks", revoked))
        except (ConnectionClosed, AttributeError):
            # dying worker: the slots are already re-queued above and no
            # longer in w.lease, so the death path won't double-queue;
            # a zombie's stray results are dropped via _revoked_set
            pass

    # ---------------- node leases (two-level scheduling) ----------------
    def _grant_node_leases(self) -> None:
        """Phase-2 preamble (docs/SCHEDULING.md, two-level scheduling):
        hand the head run of same-shape leaseable tasks to node AGENTS
        in bulk — one frame per node carrying a worker set plus a task
        batch — instead of per-worker lease grants. The agent fans the
        batch across its local workers and streams completions back;
        the driver only sees the ledger shrink. Tasks the agent can't
        place spill back (nlease_spill) and re-enter this queue."""
        self._settle_node_leases()
        if not self.pending_tasks:
            return
        # agent-free cluster: don't pay the take/re-pend sweep of the
        # whole head run on every pass — there is nobody to grant to
        if not self.node_leases and not any(
                ns.conn is not None and ns.lease_capable and ns.alive
                for ns in self.cluster_nodes.values()):
            return
        head = self.pending_tasks[0]
        if not sched_mod.node_leaseable(head):
            return
        te = self.gcs.tasks.get(head.task_id)
        if te is not None and te.state == "CANCELLED":
            return
        if self._deps_ready(head.dep_object_ids) is not True:
            return
        shape = sched_mod.shape_key(head.resources)
        take: collections.deque = collections.deque()
        while self.pending_tasks:
            spec = self.pending_tasks[0]
            te = self.gcs.tasks.get(spec.task_id)
            if te is not None and te.state == "CANCELLED":
                self.pending_tasks.popleft()
                continue
            if (not sched_mod.node_leaseable(spec)
                    or sched_mod.shape_key(spec.resources) != shape
                    or self._deps_ready(spec.dep_object_ids) is not True):
                break
            take.append(self.pending_tasks.popleft())
        if not take:
            return
        try:
            now = time.time()
            # extend open same-shape leases first: a hot lease refills
            # without worker churn (the agent keeps its slots warm)
            for lease in list(self.node_leases.values()):
                if not take:
                    break
                ns = self.cluster_nodes.get(lease.node_id)
                if (lease.need_key != shape or ns is None
                        or not ns.alive or ns.conn is None):
                    continue
                # only workers that can actually make progress count
                # toward refill capacity — extending onto a lease whose
                # workers are all parked in get() would ping-pong the
                # batch through spillback forever
                active = 0
                for wid in lease.wids:
                    w = self.workers.get(wid)
                    if (w is not None and w.state != "dead"
                            and not w.blocked):
                        active += 1
                cap = (active * self._node_lease_slots
                       - len(lease.tasks))
                if cap <= 0:
                    continue
                specs = [take.popleft()
                         for _ in range(min(cap, len(take)))]
                if not self._send_node_lease(ns, lease, specs,
                                             extend=True):
                    take.extendleft(reversed(specs))
            # new grants on agent-capable remote nodes with idle workers
            for ns in self._alive_nodes():
                if not take:
                    break
                if (ns.conn is None or not ns.lease_capable
                        or self._nlease_backoff.get(ns.node_id, 0.0)
                        > now):
                    continue
                need = dict(head.resources)
                wids: List[str] = []
                for w in self.workers.values():
                    if (w.node_id != ns.node_id or w.state != "idle"
                            or w.conn is None or w.tpu_capable
                            or w.purpose is not None):
                        continue
                    if not res_mod.fits(ns.avail, need):
                        break
                    res_mod.acquire(ns.avail, need)
                    wids.append(w.worker_id)
                    if (len(wids) * self._node_lease_slots
                            >= len(take)):
                        break
                if not wids:
                    continue
                lease = self._new_node_lease(ns, need, wids,
                                             standing=False)
                n = min(len(wids) * self._node_lease_slots, len(take))
                specs = [take.popleft() for _ in range(n)]
                if not self._send_node_lease(ns, lease, specs,
                                             extend=False):
                    take.extendleft(reversed(specs))
        finally:
            # whatever didn't fit stays at the queue head for the
            # per-worker path below, order preserved
            self.pending_tasks.extendleft(reversed(take))

    def _settle_node_leases(self) -> None:
        """Close drained non-standing leases whose shape no longer
        matches the queue head — their workers return to the pool
        instead of idling reserved for a shape that's gone."""
        if not self.node_leases:
            return
        head_shape = None
        if self.pending_tasks:
            head = self.pending_tasks[0]
            if sched_mod.node_leaseable(head):
                head_shape = sched_mod.shape_key(head.resources)
        for lid, lease in list(self.node_leases.items()):
            if (not lease.standing and not lease.tasks
                    and lease.need_key != head_shape):
                self._close_node_lease(lid, notify=True)

    def _new_node_lease(self, ns: NodeState, need: Dict[str, float],
                        wids: List[str], standing: bool) -> NodeLease:
        """Record a lease and mark its workers busy-for-the-lease: each
        holds one `need` of the node's resources (acquired by the
        caller) until the lease closes or the worker dies. w.lease
        stays empty — the driver doesn't know which task runs where;
        the agent owns per-worker assignment."""
        self._nlease_counter += 1
        lid = f"nlease-{ns.node_id[-6:]}-{self._nlease_counter}"
        lease = NodeLease(lid, ns.node_id, need, wids, standing)
        self.node_leases[lid] = lease
        now = time.time()
        for wid in wids:
            w = self.workers.get(wid)
            if w is None:
                continue
            w.state = "busy"
            w.node_lease = lid
            w.current_task = None
            w.lease = collections.deque()
            w.held_resources = dict(need)
            w.last_progress = now
        return lease

    def _send_node_lease(self, ns: NodeState, lease: NodeLease,
                         specs: List[TaskSpec], extend: bool) -> bool:
        """One wire frame carrying a whole batch. False = conn died;
        the caller re-queues `specs` (a fresh lease is also torn down —
        its node is about to be declared dead)."""
        lid = lease.lease_id
        for s in specs:
            s.lease_id = lid
        try:
            if extend:
                ns.conn.send(("nlease_extend", lid, specs))
            else:
                ns.conn.send(("nlease_grant", lid, dict(lease.need),
                              list(lease.wids), specs, lease.standing))
        except ConnectionClosed:
            if not extend:
                self._close_node_lease(lid, notify=False)
            return False
        now = time.time()
        lease.last_activity = now
        for s in specs:
            lease.tasks[s.task_id] = s
            te = self.gcs.tasks[s.task_id]
            # worker_id stays None until completion: the agent decides
            # placement; death/cancel paths key off the lease ledger
            te.state, te.worker_id, te.started_at = "RUNNING", None, now
            if te.submitted_at:
                _mcat().get("ray_tpu_task_sched_latency_s").observe(
                    now - te.submitted_at)
            self._emit("task.sched", task_id=s.task_id,
                       node_id=ns.node_id, name=s.name)
            self._pending_since.pop(s.task_id, None)
        self.dispatch_frames += 1
        self.dispatched_tasks += len(specs)
        self.node_lease_tasks += len(specs)
        if extend:
            self.node_lease_extends += 1
        else:
            self.node_lease_grants += 1
            self._emit("task.lease.node_grant",
                       f"granted node lease {lid} to {ns.node_id}: "
                       f"{len(lease.wids)} workers, {len(specs)} tasks"
                       + (" (standing)" if lease.standing else ""),
                       node_id=ns.node_id, lease_id=lid,
                       slots=len(specs), workers=len(lease.wids))
            try:
                _mcat().get("ray_tpu_node_lease_grants_total").inc()
            except Exception:
                pass
        if specs:
            try:
                _mcat().get("ray_tpu_agent_dispatch_batch_size").observe(
                    len(specs))
            except Exception:
                pass
        return True

    def _close_node_lease(self, lid: str, notify: bool) -> None:
        """Release the lease's worker claims. Outstanding ledger tasks
        (if any) are the caller's problem — revoke first when they must
        re-queue."""
        lease = self.node_leases.pop(lid, None)
        if lease is None:
            return
        for wid in lease.wids:
            w = self.workers.get(wid)
            if w is None or w.state == "dead" or w.node_lease != lid:
                continue
            if w.blocked:
                # CPU already lent back while parked in a driver verb:
                # only the non-CPU remainder is still held (mirrors
                # _on_worker_dead / _on_task_done)
                res_mod.release(self._wnode_avail(w),
                                _non_cpu(w.held_resources))
            else:
                res_mod.release(self._wnode_avail(w), w.held_resources)
            w.held_resources = {}
            w.node_lease = None
            w.state, w.current_task, w.blocked = "idle", None, False
        if notify:
            ns = self.cluster_nodes.get(lease.node_id)
            if ns is not None and ns.alive and ns.conn is not None:
                try:
                    ns.conn.send(("nlease_close", lid))
                except ConnectionClosed:
                    pass

    def _revoke_node_lease(self, lid: str, reason: str,
                           fence: bool = False,
                           charge: int = 0) -> None:
        """Re-pend every outstanding ledger task WITHOUT burning a
        retry — a revoked bulk lease means zero lost tasks, exactly
        like a revoked per-worker lease (docs/FAULT_TOLERANCE.md). With
        fence=True, late results from a zombie agent are dropped via
        the (lease_id, task_id) revocation set. With charge=N, the N
        OLDEST outstanding entries (grant order — the ones that can
        have reached a worker's FIFO head and started executing)
        follow normal worker-death retry accounting instead: burn a
        retry, or FAIL when none remain. The driver can't see agent-
        local worker assignment, so this is the same conservative
        bound the per-worker path applies to its lease head."""
        lease = self.node_leases.get(lid)
        if lease is None or not lease.tasks:
            return
        n = 0
        charged = 0
        for tid, spec in list(lease.tasks.items()):
            lease.tasks.pop(tid, None)
            if fence:
                self._revoked_add(lid, tid)
            te = self.gcs.tasks.get(tid)
            if te is None or te.state != "RUNNING":
                continue
            if charged < charge:
                charged += 1
                # Streaming tasks never retry: already-consumed items
                # would replay and duplicate the stream.
                streaming = getattr(spec, "streaming", False)
                if not streaming and te.retries_left > 0:
                    te.retries_left -= 1
                    te.state, te.worker_id = "PENDING", None
                    spec.lease_id = ""
                    self.pending_tasks.append(spec)
                    self._emit("task.retry",
                               f"node lease {lid} revoked ({reason}) "
                               f"while {te.name} may have started; "
                               "resubmitting",
                               task_id=tid, node_id=lease.node_id,
                               name=te.name,
                               retries_left=te.retries_left)
                else:
                    te.state = "FAILED"
                    err = WorkerCrashedError(
                        f"node {lease.node_id} died while running "
                        f"{te.name}")
                    self._emit("task.fail", str(err), task_id=tid,
                               node_id=lease.node_id, name=te.name)
                    for oid in self._return_ids_of(tid):
                        self._fail_object(oid, err)
                    self._gen_settle(tid, err)
                continue
            te.state, te.worker_id = "PENDING", None
            spec.lease_id = ""
            self.pending_tasks.append(spec)
            n += 1
        self.lease_revokes += 1
        self._emit("task.lease.revoke",
                   f"node lease {lid} revoked ({reason}); {n} granted "
                   "tasks re-queued without burning a retry"
                   + (f", {charged} possibly-started slots charged"
                      if charged else ""),
                   node_id=lease.node_id, lease_id=lid, slots=n,
                   reason=reason)
        try:
            _mcat().get("ray_tpu_lease_revokes_total").inc(
                tags={"reason": reason})
        except Exception:
            pass

    def _check_node_lease_watchdog(self) -> None:
        """Reaper-tick backstop for the agent plane: (a) standing
        leases parked on capacity the driver now needs are reclaimed
        when driver-visible work starves; (b) a lease whose agent stops
        making progress entirely (wedged process that still heartbeats)
        is force-revoked with fencing."""
        if not self.node_leases:
            return
        now = time.time()
        spill_s = knobs.get_float("RAY_TPU_NODE_LEASE_SPILL_S")
        idle_s = knobs.get_float("RAY_TPU_NODE_LEASE_IDLE_S")
        starving = any(now - t > 1.0
                       for t in self._pending_since.values())
        for lid, lease in list(self.node_leases.items()):
            if not lease.tasks:
                # drained: reclaim when queued work can't place, or
                # when a standing lease outlives the agent's own idle
                # release by a wide margin (lost nlease_release frame)
                if starving or (lease.standing and now
                                - lease.last_activity
                                > max(10.0, 5 * idle_s)):
                    self._close_node_lease(lid, notify=True)
                continue
            if now - lease.last_activity > max(10.0, 4 * spill_s):
                self._revoke_node_lease(lid, "agent_stalled",
                                        fence=True)
                self._close_node_lease(lid, notify=True)

    def _on_nlease_done(self, lid: str, tid: str, wid: str, sealed,
                        error) -> None:
        lease = self.node_leases.get(lid)
        if (lid, tid) in self._revoked_set:
            # force-revoked lease whose agent finished the task anyway:
            # it was already re-queued — drop this result
            self._revoked_set.discard((lid, tid))
            if lease is not None:
                lease.tasks.pop(tid, None)
            return
        if lease is not None:
            # pop BEFORE the state guard: cancelled/stale tasks must
            # still drain the ledger or the lease never closes
            lease.tasks.pop(tid, None)
            lease.last_activity = time.time()
        te = self.gcs.tasks.get(tid)
        if te is None or te.state != "RUNNING":
            return
        te.worker_id = wid
        w = self.workers.get(wid)
        if w is not None:
            w.last_progress = time.time()
        # release_worker=False: the worker stays claimed by the lease
        # (the agent immediately refills it); resources release at
        # lease close or worker death
        self._on_task_done(wid, tid, sealed, error,
                           release_worker=False)

    def _on_nlease_spill(self, nid: str, lid: str, entries,
                         reason: str) -> None:
        """Agent couldn't place (or lost) granted tasks: re-queue them
        here. started=False (never began executing) re-pends free;
        started=True (its worker died mid-run) burns a retry — same
        at-least-once contract as the per-worker death path."""
        lease = self.node_leases.get(lid)
        n = 0
        for tid, started in entries:
            spec = None
            if lease is not None:
                spec = lease.tasks.pop(tid, None)
            if spec is None:
                spec = self._respawnable_specs.get(tid)
            te = self.gcs.tasks.get(tid)
            if te is None or te.state != "RUNNING" or spec is None:
                continue
            if started:
                if te.retries_left <= 0:
                    te.state = "FAILED"
                    err = WorkerCrashedError(
                        f"worker died while running {te.name} under "
                        f"node lease {lid}")
                    self._emit("task.fail", str(err), task_id=tid,
                               node_id=nid, name=te.name)
                    for oid in self._return_ids_of(tid):
                        self._fail_object(oid, err)
                    self._gen_settle(tid, err)
                    continue
                te.retries_left -= 1
            te.state, te.worker_id = "PENDING", None
            spec.lease_id = ""
            self.pending_tasks.append(spec)
            n += 1
        if lease is not None:
            lease.last_activity = time.time()
        if n:
            self.spillbacks += n
            # brief grant backoff: the node just told us it can't
            # place this shape — don't re-grant into the same wall
            self._nlease_backoff[nid] = time.time() + 1.0
            self._emit("task.spillback",
                       f"node {nid} spilled {n} tasks back "
                       f"({reason}); re-queued",
                       node_id=nid, lease_id=lid, slots=n,
                       reason=reason)
            try:
                _mcat().get("ray_tpu_spillbacks_total").inc(
                    n, tags={"reason": reason})
            except Exception:
                pass

    def _on_nlease_want(self, nid: str, need: Dict[str, float],
                        count: int) -> None:
        """Agent asks for standing capacity to place nested
        submissions locally. Granted only from workers the driver's
        own queue doesn't need — driver work always wins."""
        if not self._node_leases_enabled:
            return
        ns = self.cluster_nodes.get(nid)
        if ns is None or not ns.alive or ns.conn is None:
            return
        now = time.time()
        if any(now - t > 1.0 for t in self._pending_since.values()):
            return   # driver-visible work is starving: refuse
        need = dict(need)
        wids: List[str] = []
        for w in self.workers.values():
            if len(wids) >= max(1, int(count)):
                break
            if (w.node_id != nid or w.state != "idle"
                    or w.conn is None or w.tpu_capable
                    or w.purpose is not None):
                continue
            if not res_mod.fits(ns.avail, need):
                break
            res_mod.acquire(ns.avail, need)
            wids.append(w.worker_id)
        if not wids:
            return
        lease = self._new_node_lease(ns, need, wids, standing=True)
        self._send_node_lease(ns, lease, [], extend=False)

    def _wnode_avail(self, w: WorkerState) -> Dict[str, float]:
        """The avail dict of the worker's node (a throwaway dict if the
        node is gone — releases to dead nodes must not corrupt others)."""
        node = self.cluster_nodes.get(w.node_id or self.node_id)
        if node is None or not node.alive:
            return {}
        return node.avail

    def _pick_node(self, need: Dict[str, float], allowed: List[str],
                   spread: bool = False) -> Optional[NodeState]:
        """First alive node (driver-first) where `need` fits; `allowed`
        non-empty restricts to those node ids (placement groups /
        affinity). spread=True round-robins across the fitting nodes
        instead of driver-first."""
        candidates = [n for n in self._alive_nodes()
                      if (not allowed or n.node_id in allowed)
                      and res_mod.fits(n.avail, need)]
        if not candidates:
            return None
        if spread:
            # Round-robin across fitting nodes (reference SPREAD
            # semantics): load-based choice degenerates for sub-second
            # tasks, which always observe every node idle.
            self._spread_rr += 1
            return candidates[self._spread_rr % len(candidates)]
        return candidates[0]

    def _dep_locality_nodes(self, spec) -> List[str]:
        """Nodes holding this task's dep payloads, largest byte total
        first — only deps big enough that moving them would cost more
        than an off-node placement (> inline threshold) count."""
        from .object_store import INLINE_MAX  # noqa: PLC0415
        sizes: Dict[str, int] = {}
        for oid in spec.dep_object_ids:
            e = self.gcs.objects.get(oid)
            if e is None or e.state != "ready":
                continue
            for loc in [e.loc, *e.copies]:
                if loc is None or getattr(loc, "kind", None) in (
                        "inline", "device"):
                    continue
                nid = loc.node_id or self.node_id
                sizes[nid] = sizes.get(nid, 0) + int(
                    getattr(loc, "size", 0) or 0)
        big = {n: s for n, s in sizes.items() if s > INLINE_MAX}
        return sorted(big, key=big.get, reverse=True)

    def _device_locality_worker(self, spec, need, needs_tpu: bool,
                                allowed_nodes,
                                allow_tpu_fallback: bool = True
                                ) -> "Optional[WorkerState]":
        """The idle worker holding this task's device-resident deps, if
        eligible — else None (normal placement takes over; the dep then
        materializes through the shm store on first remote read)."""
        holder = None
        for oid in spec.dep_object_ids:
            e = self.gcs.objects.get(oid)
            if (e is not None and e.state == "ready"
                    and getattr(e.loc, "kind", None) == "device"):
                holder = e.loc.name
                break
        if holder is None:
            return None
        w = self.workers.get(holder)
        if w is None or w.state != "idle" or w.conn is None:
            return None
        if allowed_nodes and w.node_id not in allowed_nodes:
            return None
        node = self.cluster_nodes.get(w.node_id)
        if node is None or not node.alive:
            return None
        if need and not res_mod.fits(node.avail, need):
            return None
        if needs_tpu and not w.tpu_capable:
            return None
        if (not needs_tpu and w.tpu_capable and not allow_tpu_fallback):
            # queued TPU demand reserves TPU-capable workers — locality
            # must not let a CPU consumer starve them (same rule as
            # _find_idle_worker's allow_tpu_fallback)
            return None
        return w

    def _find_idle_worker(self, needs_tpu: bool = False,
                          allow_tpu_fallback: bool = True,
                          allowed_nodes: Optional[List[str]] = None,
                          need: Optional[Dict[str, float]] = None
                          ) -> Optional[WorkerState]:
        # Prefer an exact capability match; a CPU task may fall back to an
        # idle TPU-capable worker (running plain Python there is harmless)
        # so capacity is never stranded — unless the caller knows TPU
        # demand is queued. A TPU task never runs on a worker without the
        # device. The worker's node must also fit `need`.
        fallback = None
        for w in self.workers.values():
            if w.state != "idle" or w.conn is None:
                continue
            if allowed_nodes and w.node_id not in allowed_nodes:
                continue
            node = self.cluster_nodes.get(w.node_id)
            if node is None or not node.alive:
                continue
            if need and not res_mod.fits(node.avail, need):
                continue
            if w.tpu_capable == needs_tpu:
                return w
            if not needs_tpu and w.tpu_capable and allow_tpu_fallback:
                fallback = w
        return fallback

    def _can_spawn(self, node: NodeState, needs_tpu: bool = False) -> bool:
        # max_workers (bounded by the node's CPU capacity for general
        # workers) is a per-node hard ceiling — it applies even when no
        # starting/idle worker of the needed kind exists, otherwise
        # sustained load with all workers busy would spawn one more worker
        # per scheduling pass.
        on_node = [w for w in self.workers.values()
                   if w.node_id == node.node_id]
        # Blocked workers lent their CPU back (parked in get()/gen_next)
        # — they don't count against the cap, or a consumer task holding
        # the node's only CPU slot could never get a producer spawned.
        general_alive = len([w for w in on_node
                             if w.state != "dead" and w.purpose is None
                             and not w.blocked])
        cpu_cap = int(node.total.get("CPU", 1)) or 1
        under_cap = general_alive < min(self.max_workers, cpu_cap)
        ready = sum(1 for w in on_node
                    if w.state in ("starting", "idle")
                    and w.tpu_capable == needs_tpu)
        if ready == 0:
            # Demand with no ready worker of this kind: spawn if under the
            # cap, or if the cap is consumed entirely by the other
            # capability kind and none of this kind is alive (a TPU task
            # must always be able to get at least one TPU worker).
            alive_kind = sum(1 for w in on_node
                             if w.state != "dead" and w.purpose is None
                             and w.tpu_capable == needs_tpu)
            return under_cap or alive_kind == 0
        return under_cap

    def _spawn_worker(self, purpose, tpu_capable: bool = False,
                      node_id: Optional[str] = None) -> str:
        self._wid_counter += 1
        wid = f"w{self._wid_counter:04d}"
        node_id = node_id or self.node_id
        node = self.cluster_nodes[node_id]
        acspec = self._actor_create_specs.get(purpose) if purpose else None
        if acspec is not None and acspec.resources.get("TPU", 0) > 0:
            tpu_capable = True
        self._emit("worker.start", worker_id=wid, node_id=node_id,
                   actor_id=purpose, tpu_capable=bool(tpu_capable))
        if node.conn is not None:
            # remote node: its agent spawns the worker, which connects
            # straight back to our TCP listener
            node.conn.send(("spawn_worker", wid, bool(tpu_capable),
                            self.job_id))
            self.workers[wid] = WorkerState(wid, None, purpose=purpose,
                                            tpu_capable=tpu_capable,
                                            node_id=node_id)
            return wid
        env = dict(os.environ)
        env["RAY_TPU_JOB_ID"] = self.job_id
        env["RAY_TPU_LOG_DIR"] = self.log_dir
        env.setdefault("PYTHONPATH", "")
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        # Propagate the driver's full sys.path so by-reference pickles of
        # driver-side modules (test files, user scripts next to the driver)
        # resolve in workers — the single-host analogue of the reference's
        # runtime_env working_dir shipping (python/ray/runtime_env).
        driver_paths = [p for p in sys.path
                        if p and os.path.isdir(p) and p != repo_root]
        env["PYTHONPATH"] = os.pathsep.join(
            [repo_root, *driver_paths,
             *[p for p in env["PYTHONPATH"].split(os.pathsep) if p]])
        # Workers run CPU JAX unless the actor explicitly holds TPU
        # resources: the chip belongs to the driver-side SPMD step
        # (single-controller model), and letting every worker claim the
        # backend would deadlock the TPU tunnel.
        if not tpu_capable:
            from ..util.jaxenv import subprocess_env_cpu  # noqa: PLC0415
            subprocess_env_cpu(env)
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.worker",
             self.socket_path, wid],
            env=env, cwd=os.getcwd())
        self.workers[wid] = WorkerState(wid, proc, purpose=purpose,
                                        tpu_capable=tpu_capable,
                                        node_id=node_id)
        return wid

    def _worker_for_actor(self, aid: str) -> Optional[WorkerState]:
        for w in self.workers.values():
            if w.actor_id == aid and w.state == "actor":
                return w
        return None

    # ---------------- compiled-DAG placement (docs/DAG.md) -----------
    def _process_dag_acquires(self):
        rest = []
        for acq in self._dag_acquires:
            if not self._try_place_dag(acq):
                if time.time() > acq["deadline"]:
                    acq["reply"].put({"error": (
                        "placement timed out: not enough idle workers "
                        "for the compiled-DAG stages")})
                else:
                    rest.append(acq)
        self._dag_acquires = rest

    def _dag_pick_worker(self, pref_node: str,
                         need: Dict[str, float],
                         used: set) -> Optional[WorkerState]:
        # dependency-local first, then any node; plain CPU workers
        # before idle TPU-capable ones (same fallback rule as tasks)
        best = None
        for w in self.workers.values():
            if (w.state != "idle" or w.conn is None or w.purpose
                    or w.worker_id in used):
                continue
            node = self.cluster_nodes.get(w.node_id)
            if node is None or not node.alive:
                continue
            if need and not res_mod.fits(node.avail, need):
                continue
            score = (w.node_id == pref_node, not w.tpu_capable)
            if best is None or score > best[0]:
                best = (score, w)
        return best[1] if best else None

    def _try_place_dag(self, acq: dict) -> bool:
        """True when the acquire resolved (placement committed or a
        terminal error was replied); False keeps it queued."""
        placement: Dict[Any, dict] = {}
        node_of: Dict[Any, str] = {}
        used: set = set()
        spawn_nodes: List[str] = []
        for r in acq["reqs"]:
            sid = r["sid"]
            if r["kind"] == "method":
                aid = r["actor_id"]
                ae = self.gcs.actors.get(aid)
                if ae is None or ae.state == "DEAD":
                    acq["reply"].put({"error": f"actor:{aid}:dead"})
                    return True
                w = self._worker_for_actor(aid)
                if w is None or ae.state != "ALIVE" or w.conn is None:
                    return False     # still starting: retry next pass
                placement[sid] = {"wid": w.worker_id,
                                  "node_id": w.node_id, "conn": w.conn,
                                  "pinned": False}
                node_of[sid] = w.node_id
            else:
                pref = sched_mod.compiled_stage_node(
                    r.get("deps") or (), node_of, self.node_id)
                need = {"CPU": float(r.get("num_cpus") or 1)}
                w = self._dag_pick_worker(pref, need, used)
                if w is None:
                    spawn_nodes.append(pref)
                    continue
                used.add(w.worker_id)
                placement[sid] = {"wid": w.worker_id,
                                  "node_id": w.node_id, "conn": w.conn,
                                  "pinned": True, "need": need}
                node_of[sid] = w.node_id
        if spawn_nodes:
            for nid in spawn_nodes:
                node = self.cluster_nodes.get(nid)
                if node is None or not node.alive:
                    node = self.cluster_nodes[self.node_id]
                starting = sum(
                    1 for w in self.workers.values()
                    if w.node_id == node.node_id
                    and w.state == "starting" and w.purpose is None)
                # one outstanding spawn per node per pass: registration
                # re-triggers _schedule, which retries this acquire
                if starting == 0 and self._can_spawn(node):
                    self._spawn_worker(None, node_id=node.node_id)
            return False
        # every stage has a worker: commit atomically
        for sid, p in placement.items():
            if not p["pinned"]:
                continue
            w = self.workers[p["wid"]]
            w.state = "dag"
            w.current_task = f"dag:{acq['dag_id']}"
            need = p.pop("need")
            res_mod.acquire(self._wnode_avail(w), need)
            w.held_resources = dict(need)
        acq["reply"].put({"placement": placement})
        return True

    def _dag_release(self, dag_id: str, wids: List[str], info: dict):
        for wid in wids:
            w = self.workers.get(wid)
            if w is None or w.state != "dag":
                continue
            res_mod.release(self._wnode_avail(w), w.held_resources)
            w.held_resources = {}
            w.state = "idle"
            w.current_task = None
        self._emit("dag.channel.close", dag_id=dag_id,
                   channels=int(info.get("channels", 0)))
        self._emit("dag.teardown", dag_id=dag_id,
                   reason=str(info.get("reason", "")),
                   workers=len(wids))

    # ---------------- completions ----------------
    def _on_task_done(self, wid: str, task_id: str, sealed, error,
                      release_worker: bool = True):
        te = self.gcs.tasks.get(task_id)
        w = self.workers.get(wid)
        if (wid, task_id) in self._revoked_set:
            # reclaimed lease slot that executed anyway (the revoke
            # raced a user thread in the worker): the task was already
            # re-queued elsewhere — drop this result
            self._revoked_set.discard((wid, task_id))
            return
        if te is None:
            return
        spec_returns = []
        if error is None:
            te.state = "FINISHED"
            for oid, loc in sealed:
                self._seal(oid, loc)
                spec_returns.append(oid)
            self._gen_settle(task_id)
        elif error == "cancelled":
            te.state = "CANCELLED"
            err = TaskCancelledError(f"task {task_id} cancelled")
            for oid in self._return_ids_of(task_id):
                self._fail_object(oid, err)
            self._gen_settle(task_id, err)
        else:
            te.state = "FAILED"
            for oid in self._return_ids_of(task_id):
                self._fail_object(oid, error)
            self._gen_settle(task_id, error)
        te.finished_at = time.time()
        _mcat().get("ray_tpu_tasks_finished_total").inc(
            tags={"state": te.state})
        if te.started_at:
            _mcat().get("ray_tpu_task_run_s").observe(
                te.finished_at - te.started_at)
        if te.state == "FINISHED":
            self._emit("task.finish", task_id=task_id, worker_id=wid,
                       actor_id=te.actor_id, name=te.name,
                       duration_s=round(
                           te.finished_at - te.started_at, 6)
                       if te.started_at else None)
        elif te.state == "CANCELLED":
            self._emit("task.cancel", task_id=task_id, worker_id=wid,
                       actor_id=te.actor_id, name=te.name)
        else:
            self._emit("task.fail", repr(error)[:500], task_id=task_id,
                       worker_id=wid, actor_id=te.actor_id,
                       name=te.name)
        spec = self._respawnable_specs.pop(task_id, None)
        if spec is not None and error is None and spec.actor_id is None:
            # retain for lineage reconstruction of this task's outputs
            # (byte- and count-bounded: oldest lineage drops first)
            self._retain_lineage(task_id, spec)
        if te.actor_id is not None:
            gkey = (te.actor_id, getattr(te, "concurrency_group", None))
            self.actor_group_inflight[gkey] = max(
                0, self.actor_group_inflight.get(gkey, 0) - 1)
        elif w is not None and release_worker:
            w.last_progress = time.time()
            if task_id in w.lease:
                try:
                    w.lease.remove(task_id)
                except ValueError:
                    pass
            if w.state == "busy" and w.lease:
                # more leased slots queued behind this one: the worker
                # keeps its resource slot and runs the next task
                w.current_task = w.lease[0]
                return
            if w.blocked:
                # its CPU was already lent while parked (dwait/get) and
                # the symmetric unblock never arrived: release only the
                # non-CPU remainder (mirrors _on_worker_dead)
                res_mod.release(self._wnode_avail(w),
                                _non_cpu(w.held_resources))
            else:
                res_mod.release(self._wnode_avail(w), w.held_resources)
            self._return_tpu_ids(w)
            w.held_resources = {}
            w.state, w.current_task, w.blocked = "idle", None, False
            if spec is not None and getattr(spec, "max_calls", 0) > 0:
                # worker recycling (@remote(max_calls=N)): retire the
                # process once it has run this function N times — the
                # escape hatch for leaky native libraries
                w.func_calls[spec.func_id] = \
                    w.func_calls.get(spec.func_id, 0) + 1
                if w.func_calls[spec.func_id] >= spec.max_calls:
                    self._terminate_worker(w)

    def _return_ids_of(self, task_id: str) -> List[str]:
        return [oid for oid, e in self.gcs.objects.items()
                if e.owner_task == task_id]

    def _on_actor_created(self, wid: str, actor_id: str, ok: bool, err):
        ae = self.gcs.actors.get(actor_id)
        if ae is None:
            return
        if ok:
            ae.state, ae.worker_id = "ALIVE", wid
            self._persist_actor_state(ae)
            self._emit("actor.alive", actor_id=actor_id, worker_id=wid,
                       class_name=ae.class_name)
        else:
            ae.state, ae.death_cause = "DEAD", repr(err)
            self._persist_actor_state(ae)
            self._actor_checkpoints.pop(actor_id, None)
            self._emit("actor.death",
                       f"constructor failed: {repr(err)[:400]}",
                       actor_id=actor_id, worker_id=wid,
                       class_name=ae.class_name)
            w = self.workers.get(wid)
            if w is not None:
                res_mod.release(self._wnode_avail(w), w.held_resources)
                self._return_tpu_ids(w)
                w.held_resources = {}
                self._terminate_worker(w)
            # propagate the constructor error to queued method calls
            for spec in self.actor_queues.get(actor_id, []):
                self.gcs.tasks[spec.task_id].state = "FAILED"
                for oid in spec.return_ids:
                    self._fail_object(oid, err)
                self._gen_settle(spec.task_id, err)
            self.actor_queues.pop(actor_id, None)

    def _on_worker_dead(self, wid: str):
        w = self.workers.get(wid)
        if w is None or w.state == "dead":
            return
        w.state = "dead"
        # a compiled-DAG participant died: fail that pipeline's
        # in-flight executions (typed CompiledDagError) and tear its
        # channels down; the next execute() re-compiles transparently
        for ctl in list(self.compiled_dags.values()):
            try:
                ctl.on_worker_dead(wid)
            except Exception:
                traceback.print_exc()
        # a dead worker's gauge series would otherwise report its last
        # "current state" forever (counters/histograms stay: history)
        self.cluster_metrics.drop_source({"worker_id": wid})
        # ghost waits from a dead process must not poison the wait
        # graph (its waits died with it; the CAUSES live elsewhere)
        self.cluster_waits.drop_source(wid)
        if w.node_lease is not None:
            # node-leased worker: the AGENT owns its task assignment —
            # it spills the in-flight task back (nlease_spill,
            # started=True) and redistributes the rest, so the driver
            # neither retries nor fails anything here (the lease
            # watchdog backstops a wedged agent). Just drop the claim.
            lease = self.node_leases.get(w.node_lease)
            if lease is not None:
                try:
                    lease.wids.remove(wid)
                except ValueError:
                    pass
            w.node_lease = None
        if w.blocked:
            # Blocked workers already returned their CPU when they entered
            # get() — release only the non-CPU remainder they still hold.
            res_mod.release(self._wnode_avail(w),
                            _non_cpu(w.held_resources))
        else:
            res_mod.release(self._wnode_avail(w), w.held_resources)
        self._return_tpu_ids(w)
        w.held_resources = {}
        w.blocked = False
        self._conn_by_wid.pop(wid, None)
        self._emit("worker.death", task_id=w.current_task,
                   actor_id=w.actor_id, worker_id=wid,
                   node_id=w.node_id)
        # running / leased normal tasks -> retry or fail. Only the
        # lease HEAD can have started (the worker executes its lease
        # strictly FIFO), so slots behind it re-queue without burning a
        # retry — a revoked lease must mean zero lost tasks even at
        # max_retries=0.
        leased = list(w.lease) if w.lease else (
            [w.current_task] if w.current_task else [])
        w.lease = collections.deque()
        if len(leased) > 1:
            self.lease_revokes += 1
            self._emit("task.lease.revoke",
                       f"worker {wid} died holding a {len(leased)}-slot "
                       f"lease; unstarted slots re-queue without "
                       f"burning a retry",
                       worker_id=wid, node_id=w.node_id,
                       task_id=leased[0], slots=len(leased))
            try:
                _mcat().get("ray_tpu_lease_revokes_total").inc(
                    tags={"reason": "worker_death"})
            except Exception:
                pass
        for idx, tid in enumerate(leased):
            te = self.gcs.tasks.get(tid)
            if te is None or te.state != "RUNNING":
                continue
            spec = self._respawnable_specs.get(tid)
            # Streaming tasks never retry: already-consumed items
            # would replay and duplicate the stream.
            streaming = spec is not None and getattr(spec, "streaming",
                                                     False)
            if spec is not None and not streaming and (
                    idx > 0 or te.retries_left > 0):
                if idx == 0:
                    te.retries_left -= 1
                te.state = "PENDING"
                te.worker_id = None
                self.pending_tasks.append(spec)
                self._emit("task.retry",
                           (f"worker {wid} died while running "
                            f"{te.name}; resubmitting") if idx == 0 else
                           (f"lease on dead worker {wid} revoked before "
                            f"{te.name} started; resubmitting"),
                           task_id=tid, worker_id=wid,
                           node_id=w.node_id, name=te.name,
                           retries_left=te.retries_left)
            else:
                te.state = "FAILED"
                err = WorkerCrashedError(
                    f"worker {wid} died while running {te.name}")
                self._emit("task.fail", str(err),
                           task_id=tid, worker_id=wid,
                           node_id=w.node_id, name=te.name)
                for oid in self._return_ids_of(tid):
                    self._fail_object(oid, err)
                self._gen_settle(tid, err)
        # actor hosted here -> restart or mark dead FIRST: sealed
        # objects this worker still held (device-resident returns) must
        # fail with the actor's death_cause, not a bare ObjectLostError
        # — the two paths used to race on ordering
        if w.actor_id:
            self._on_actor_worker_dead(w.actor_id, wid)
        # device-resident objects held by this worker are gone:
        # reconstruct from lineage or fail (mirrors node-death handling)
        for oid, e in list(self.gcs.objects.items()):
            if (e.state == "ready"
                    and getattr(e.loc, "kind", None) == "device"
                    and e.loc.name == wid):
                self._device_object_lost(oid, e)

    def _fail_inflight_actor_tasks(self, aid: str, cause: str) -> None:
        err = ActorDiedError(f"actor {aid} {cause}")
        for task_id, te in self.gcs.tasks.items():
            if te.actor_id == aid and te.state == "RUNNING":
                te.state = "FAILED"
                for oid in self._return_ids_of(task_id):
                    self._fail_object(oid, err)
                self._gen_settle(task_id, err)
        for key in [k for k in self.actor_group_inflight if k[0] == aid]:
            self.actor_group_inflight[key] = 0

    def _drain_actor_queue(self, aid: str, cause: str) -> None:
        err = ActorDiedError(f"actor {aid} {cause}")
        for spec in self.actor_queues.get(aid, []):
            self.gcs.tasks[spec.task_id].state = "FAILED"
            for oid in spec.return_ids:
                self._fail_object(oid, err)
            self._gen_settle(spec.task_id, err)
        self.actor_queues.pop(aid, None)

    def _on_actor_exit(self, aid: str) -> None:
        """Graceful self-exit (ray_tpu.actor_exit()): DEAD before the
        socket-close event so no restart happens; any OTHER in-flight or
        queued calls fail like a death (the exiting call itself already
        completed)."""
        ae = self.gcs.actors.get(aid)
        if ae is None or ae.state == "DEAD":
            return
        ae.state = "DEAD"
        ae.death_cause = "actor_exit() called"
        self._persist_actor_state(ae)
        self._actor_checkpoints.pop(aid, None)
        self._emit("actor.death", ae.death_cause, actor_id=aid,
                   class_name=ae.class_name)
        self._fail_inflight_actor_tasks(aid, "exited via actor_exit()")
        self._drain_actor_queue(aid, "exited via actor_exit()")

    def _on_actor_ckpt(self, wid: str, aid: str, blob) -> None:
        """Latest __ray_save__ state from the actor's worker; handed to
        the replacement worker's __ray_restore__ around a restart."""
        ae = self.gcs.actors.get(aid)
        if ae is None or ae.state == "DEAD" or blob is None:
            return
        self._actor_checkpoints[aid] = blob
        if self._persist is not None:
            self._persist.actor_ckpt(aid, blob)
        self._emit("actor.checkpoint", actor_id=aid, worker_id=wid,
                   size=len(blob))

    def _on_actor_worker_dead(self, aid: str, wid: str):
        ae = self.gcs.actors.get(aid)
        if ae is None or ae.state == "DEAD":
            return
        self._fail_inflight_actor_tasks(aid, "worker died")
        if ae.num_restarts < ae.max_restarts:
            ae.num_restarts += 1
            ae.state = "RESTARTING"
            self._persist_actor_state(ae)
            self._emit("actor.restart",
                       f"worker {wid} died; restart "
                       f"{ae.num_restarts}/{ae.max_restarts}",
                       actor_id=aid, worker_id=wid,
                       class_name=ae.class_name)
            # Restart placement goes through the scheduler (phase 1.5):
            # spawning here unconditionally could land the actor on a
            # node that lacks its resources (or violate its placement
            # group) and drive that node's avail negative.
            self.pending_restarts.append(aid)
            # _on_actor_created flips state back to ALIVE on success.
        else:
            ae.state = "DEAD"
            ae.death_cause = ae.death_cause or f"worker {wid} died"
            self._persist_actor_state(ae)
            self._actor_checkpoints.pop(aid, None)
            self._emit("actor.death", ae.death_cause, actor_id=aid,
                       worker_id=wid, class_name=ae.class_name)
            self._drain_actor_queue(aid, "died")

    # ---------------- worker-side blocking verbs ----------------
    def _worker_get(self, w: Optional[WorkerState], rid, oids, timeout):
        def cb(results, ready, w=w, rid=rid, oids=oids):
            full = {}
            for oid in oids:
                full[oid] = results.get(
                    oid, ("error", ObjectLostError(f"{oid} unavailable")))
            # Cross-node payloads can't be read from the requester's
            # shm. Peer path (core/object_transfer.py): the requester's
            # node agent pulls the bytes STRAIGHT from the holder's
            # transfer server and re-hosts them in its own arena — the
            # reply then carries a local location and the driver's
            # sockets never see the payload. The location directory is
            # consulted first (a copy may already live on the
            # requester's node), and the old driver relay remains the
            # instrumented fallback. Pulls block on other nodes, so they
            # run on a helper thread — never the dispatcher.
            wnode = w.node_id if w is not None else self.node_id
            cross = [oid for oid, (kind, p) in full.items()
                     if kind == "loc" and p.kind != "inline"
                     and (p.node_id or self.node_id) != wnode]
            # candidates snapshot on the dispatcher thread (GCS tables
            # are dispatcher-owned); the helper thread only reads it
            cand = {oid: self._object_candidates(oid) for oid in cross}

            def serve_one(oid, loc, cands, w=w, rid=rid, wnode=wnode):
                """Move one cross-node payload to the requester; returns
                the reply tuple. Raises (notably ObjectLostError) on an
                unreachable holder — the caller then triggers lineage
                reconstruction and retries with the fresh location."""
                chunk_sz = knobs.get_int("RAY_TPU_FETCH_CHUNK")
                if getattr(loc, "kind", None) == "inline" or \
                        (loc.node_id or self.node_id) == wnode:
                    return ("loc", loc)  # reconstructed copy came local
                # 0. directory: a copy already on the requester's node
                # serves as a plain local read
                local = next(
                    (c for c, _a in cands
                     if (c.node_id or self.node_id) == wnode), None)
                if local is not None:
                    return ("loc", local)
                if wnode != self.node_id:
                    # 1. peer path: requester's agent pulls direct from
                    # the holder
                    newloc = self._request_node_pull(wnode, oid, cands)
                    if newloc is not None:
                        self.inbox.put(("object_copied", oid, newloc))
                        return ("loc", newloc)
                # 2. relay fallback (also the driver-node requester
                # path, where fetch_bytes itself pulls peer-direct from
                # the holder's server)
                if (loc.node_id or self.node_id) == self.node_id:
                    data = self.store.get_bytes(loc)
                else:
                    data = self.fetch_bytes(loc, oid=oid)
                    try:
                        newloc = self.store.put_packed(oid, data)
                    except Exception:
                        newloc = None
                    if newloc is not None:
                        self.inbox.put(("object_copied", oid, newloc))
                        if wnode == self.node_id:
                            return ("loc", newloc)
                if (w is not None and w.conn is not None
                        and len(data) > chunk_sz):
                    for off in range(0, len(data), chunk_sz):
                        w.conn.send(("value_chunk", rid, oid, off,
                                     len(data),
                                     data[off:off + chunk_sz]))
                    if wnode != self.node_id:
                        self._count_relay(len(data))
                    return ("value_staged", len(data))
                if wnode != self.node_id:
                    # payload leaves over the worker's control
                    # connection: driver relay
                    self._count_relay(len(data))
                return ("value", data)

            def finish(full=full, cross=cross, w=w, rid=rid, wnode=wnode,
                       cand=cand):
                # First pass: serve what's reachable; report EVERY lost
                # object up front so the dispatcher reconstructs them
                # concurrently (a serial report-and-wait would make the
                # wall clock the SUM of the reconstructions, not the
                # max).
                retry: List[str] = []
                for oid in cross:
                    _, loc = full[oid]
                    try:
                        full[oid] = serve_one(oid, loc,
                                              cand.get(oid, []))
                    except ObjectLostError:
                        # every recorded copy failed us: the dispatcher
                        # prunes the bad copies and re-executes the
                        # producer from lineage
                        self.inbox.put((
                            "object_unreachable", oid,
                            getattr(loc, "node_id", None)
                            or self.node_id,
                            getattr(loc, "seal_seq", None)))
                        retry.append(oid)
                    except BaseException as e:  # noqa: BLE001
                        full[oid] = ("error", e)
                # Second pass: wait for the re-seals (overlapping — the
                # first await covers the others' reconstruction time)
                # and serve each ONCE more.
                for oid in retry:
                    kind2, payload2 = self._await_object(
                        oid, timeout=self._reconstruct_wait)
                    if kind2 == "timeout":
                        full[oid] = ("error", ObjectLostError(
                            f"object {oid} did not reconstruct within "
                            f"{self._reconstruct_wait}s"))
                        continue
                    if kind2 != "loc":
                        full[oid] = ("error", payload2)
                        continue
                    # fresh location; rebuild ONE candidate with its
                    # holder's transfer address so the peer path (not
                    # the driver relay) still serves the reconstructed
                    # payload
                    loc = payload2
                    addr = self.transfer_addrs.get(
                        getattr(loc, "node_id", None) or self.node_id)
                    try:
                        full[oid] = serve_one(
                            oid, loc, [(loc, addr)] if addr else [])
                    except BaseException as e:  # noqa: BLE001
                        full[oid] = ("error", e)
                if w is not None and w.conn is not None:
                    try:
                        w.conn.send(("get_reply", rid, full))
                    except ConnectionClosed:
                        pass

            if cross:
                threading.Thread(target=finish, daemon=True).start()
            else:
                finish()
            if w is not None and w.blocked:
                w.blocked = False
                res_mod.acquire(self._wnode_avail(w),
                                _cpu_only(w.held_resources))
        waiter = Waiter(oids, None, cb)
        if w is not None and w.state == "busy" and not w.blocked:
            # Worker blocks in user get(): release its CPU so other tasks
            # can run (reference: raylet "blocked worker" CPU release,
            # src/ray/raylet/node_manager.cc HandleTaskBlocked). TPU chips
            # stay held — the blocked process still owns the device and
            # its HBM; lending the chip out would double-book it.
            w.blocked = True
            res_mod.release(self._wnode_avail(w),
                            _cpu_only(w.held_resources))
        self._add_waiter(waiter, timeout=timeout)
        if w is not None and w.blocked and not waiter.done \
                and len(w.lease) > 1:
            # The get actually PARKED (args-already-ready gets — every
            # leased task resolving its arg refs — fire synchronously
            # above and never reach here): leased slots behind the
            # blocked head would wait on it, or deadlock if the head
            # waits on one of THEM via a nested ref — pull them back
            # for other workers. Still ordered before the eventual
            # get_reply, so the worker is fenced first.
            self._reclaim_lease(w)

    def _worker_wait(self, w, rid, oids, num_returns, timeout):
        def cb(results, ready, w=w, rid=rid):
            if w is not None and w.conn is not None:
                try:
                    w.conn.send(("get_reply", rid, ready))
                except ConnectionClosed:
                    pass
        waiter = Waiter(oids, num_returns, cb, needs_bytes=False)
        self._add_waiter(waiter, timeout=timeout)
        if not waiter.done and w is not None and w.state == "busy" \
                and len(w.lease) > 1:
            # a lease head parked in wait() pins its unstarted slots
            # exactly like a parked get() — and can deadlock the same
            # way if it waits on one of them via a nested ref
            self._reclaim_lease(w)

    # ---------------- control ----------------
    def _cancel(self, task_id: str, force: bool):
        te = self.gcs.tasks.get(task_id)
        if te is None or te.state in ("FINISHED", "FAILED", "CANCELLED"):
            return
        if te.state in ("PENDING", "SCHEDULED"):
            te.state = "CANCELLED"
            self._respawnable_specs.pop(task_id, None)
            self._emit("task.cancel", "cancelled before dispatch",
                       task_id=task_id, name=te.name)
            err = TaskCancelledError(f"task {task_id} cancelled")
            for oid in self._return_ids_of(task_id):
                self._fail_object(oid, err)
            self._gen_settle(task_id, err)
        elif te.state == "RUNNING" and te.worker_id is None and any(
                task_id in nl.tasks for nl in self.node_leases.values()):
            # node-leased and not yet (knowably) started: the driver
            # doesn't know which worker — if any — holds it. Mark it
            # terminal and settle its objects now; _on_nlease_done
            # drains the agent's eventual result via the ledger pop +
            # state guard, so nothing double-settles.
            te.state = "CANCELLED"
            self._respawnable_specs.pop(task_id, None)
            self._emit("task.cancel", "cancelled while node-leased",
                       task_id=task_id, name=te.name)
            err = TaskCancelledError(f"task {task_id} cancelled")
            for oid in self._return_ids_of(task_id):
                self._fail_object(oid, err)
            self._gen_settle(task_id, err)
        elif te.state == "RUNNING":
            w = self.workers.get(te.worker_id or "")
            if w and w.conn:
                try:
                    w.conn.send(("cancel", task_id))
                except ConnectionClosed:
                    pass
            if force and w is not None and te.actor_id is None:
                # Mark terminal first so the death handler neither retries
                # nor double-fails this task.
                te.state = "CANCELLED"
                self._respawnable_specs.pop(task_id, None)
                err = TaskCancelledError(f"task {task_id} cancelled (force)")
                for oid in self._return_ids_of(task_id):
                    self._fail_object(oid, err)
                self._gen_settle(task_id, err)
                w.current_task = None
                self._terminate_worker(w)

    def _kill_actor(self, actor_id: str, no_restart: bool):
        ae = self.gcs.actors.get(actor_id)
        if ae is None or ae.state == "DEAD":
            return
        if no_restart:
            ae.max_restarts = ae.num_restarts  # block further restarts
            ae.death_cause = "killed via ray_tpu.kill"
        w = self._worker_for_actor(actor_id)
        if w is not None:
            # The death handler (run inline by _terminate_worker) fails
            # in-flight tasks and either restarts the actor or marks it DEAD,
            # honoring the remaining restart budget.
            self._terminate_worker(w)
        else:
            ae.state = "DEAD"
            ae.death_cause = ae.death_cause or "killed before start"
            self._persist_actor_state(ae)
            self._emit("actor.death", ae.death_cause,
                       actor_id=actor_id, class_name=ae.class_name)
            for spec in self.actor_queues.pop(actor_id, []):
                self.gcs.tasks[spec.task_id].state = "FAILED"
                err = ActorDiedError(f"actor {actor_id} was killed")
                for oid in spec.return_ids:
                    self._fail_object(oid, err)

    def _terminate_worker(self, w: WorkerState):
        """Forcefully stop a worker process and run its death cleanup inline.

        The reader thread will also post a worker_dead event when the socket
        drops; _on_worker_dead dedupes on state == "dead"."""
        try:
            if w.conn:
                w.conn.close()
        except Exception:
            pass
        try:
            w.proc.terminate()
        except Exception:
            pass
        self._on_worker_dead(w.worker_id)

    def _free(self, oids: List[str]):
        for oid in oids:
            e = self.gcs.objects.pop(oid, None)
            if e is None or e.loc is None:
                continue
            if self._persist is not None:
                self._persist.object_free(oid)
            self._emit("object.free", object_id=oid,
                       task_id=e.owner_task)
            for loc in [e.loc, *e.copies]:
                if loc.kind == "device":
                    holder = self.workers.get(loc.name)
                    if holder is not None and holder.conn is not None:
                        try:
                            holder.conn.send(("drop_device", oid))
                        except ConnectionClosed:
                            pass
                    continue
                holder = loc.node_id or self.node_id
                if holder == self.node_id:
                    if loc.kind in ("shm", "native"):
                        self.store.delete_segment(loc.name, loc.size)
                else:
                    ns = self.cluster_nodes.get(holder)
                    if ns is not None and ns.alive and ns.conn is not None:
                        try:
                            ns.conn.send(("free_object", loc))
                        except ConnectionClosed:
                            pass
                self._spill.on_free(loc, oid)

    def _create_pg(self, pg: PlacementGroupState):
        # Registration only; admission happens in _schedule phase 0.
        self.placement_groups[pg.pg_id] = pg

    def _remove_pg(self, pg_id: str):
        pg = self.placement_groups.pop(pg_id, None)
        if pg is not None and pg.state == "CREATED":
            for i, (b, nid) in enumerate(zip(pg.bundles, pg.bundle_nodes)):
                node = self.cluster_nodes.get(nid)
                if node is not None and node.alive:
                    res_mod.release(node.avail, b)
                    ids = (pg.bundle_tpu_ids[i]
                           if i < len(pg.bundle_tpu_ids) else [])
                    if ids:
                        node.free_tpu_ids = sorted(
                            set(node.free_tpu_ids) | set(ids))

    # ================= public API (called from any thread) =================
    def submit(self, spec: TaskSpec) -> List[ObjectRef]:
        """Register one task. Submits coalesce into api_submit_many
        batches under a size (RAY_TPU_BATCH_FLUSH_N) + time
        (RAY_TPU_BATCH_FLUSH_S) flush window, so a `[f.remote() for ...]`
        fan-out costs the dispatcher one inbox frame per batch — and one
        scheduling pass per batch — instead of one per call. Verbs whose
        semantics depend on a prior submit having landed (get/cancel/
        gen_next/...) flush first; otherwise the pending-object
        machinery tolerates the ≤1ms reorder."""
        self._respawnable_specs[spec.task_id] = spec
        if not self._batch_enabled:
            self.inbox.put(("api_submit", spec))
            return [ObjectRef(oid) for oid in spec.return_ids]
        with self._submit_buf_lock:
            self._submit_buf.append(spec)
            n = len(self._submit_buf)
        if n >= self._flush_n:
            self._flush_submits()
        else:
            self._submit_buf_event.set()
        return [ObjectRef(oid) for oid in spec.return_ids]

    def _flush_submits(self) -> None:
        with self._submit_buf_lock:
            if not self._submit_buf:
                return
            buf, self._submit_buf = self._submit_buf, []
        self.inbox.put(("api_submit_many", buf))
        self.submit_batches += 1
        self.batched_submits += len(buf)
        try:
            _mcat().get("ray_tpu_submit_batch_size").observe(len(buf))
        except Exception:
            pass

    def _submit_flush_loop(self) -> None:
        """Time bound of the flush window: a solo .remote() with no
        follow-up verb still lands within ~RAY_TPU_BATCH_FLUSH_S."""
        while not self._shutdown.is_set():
            if not self._submit_buf_event.wait(timeout=0.5):
                continue
            self._submit_buf_event.clear()
            if self._flush_window > 0:
                time.sleep(self._flush_window)
            self._flush_submits()

    def submit_actor_task(self, spec: TaskSpec) -> List[ObjectRef]:
        return self.submit(spec)

    def submit_many(self, specs: List[TaskSpec]) -> List[List[ObjectRef]]:
        """Submit a batch of (task or actor-method) specs in ONE
        dispatcher round-trip — compiled DAG levels come through here
        (SURVEY C16: batched submissions; vs one inbox message per
        .remote() call)."""
        self._flush_submits()   # keep inter-batch submission order
        specs = list(specs)
        for spec in specs:
            self._respawnable_specs[spec.task_id] = spec
        self.inbox.put(("api_submit_many", specs))
        self.submit_many_calls += 1
        return [[ObjectRef(oid) for oid in s.return_ids] for s in specs]

    def gen_next(self, task_id: str,
                 timeout: Optional[float] = None) -> Optional[ObjectRef]:
        """Next item ref of a streaming-generator task; None when the
        stream is exhausted; raises the task's error if it failed."""
        ev = threading.Event()
        box: Dict[str, Any] = {}
        abandoned = [False]

        def cb(result):
            box["r"] = result
            ev.set()

        self._flush_submits()   # the stream's submit may still be buffered
        self.inbox.put(("api_gen_next", task_id, cb, abandoned))
        if not ev.wait(timeout):
            abandoned[0] = True
            raise GetTimeoutError(
                f"generator next() timed out after {timeout}s")
        kind, payload = box["r"]
        if kind == "item":
            return ObjectRef(payload)
        if kind == "error":
            if isinstance(payload, BaseException):
                raise payload
            raise TaskError(str(payload))
        return None

    def create_actor(self, acspec: ActorCreationSpec) -> None:
        self.inbox.put(("api_submit_actor", acspec))

    def put(self, value: Any) -> ObjectRef:
        from .spilling import put_value_or_spill  # noqa: PLC0415
        oid = new_object_id()
        loc = put_value_or_spill(self.store, oid, value)
        # Register for spilling NOW (not at dispatch): a burst of puts
        # must not evict an object the dispatcher hasn't sealed yet.
        self._spill.on_seal(oid, loc)
        self.inbox.put(("api_seal", oid, loc))
        return ObjectRef(oid)

    def get(self, refs: List[ObjectRef], timeout: Optional[float] = None) -> List[Any]:
        oids = [r.id for r in refs]
        ev = threading.Event()
        box: Dict[str, Any] = {}

        def cb(results, ready):
            box.update(results)
            ev.set()

        self._flush_submits()   # no flush-window latency on submit->get
        waiter = Waiter(oids, None, cb)
        self.inbox.put(("api_waiter", waiter))
        wtok = _waits().park("object", oids[0] if oids else "",
                             waiter="driver", n=len(oids))
        try:
            settled = ev.wait(timeout)
        finally:
            _waits().unpark(wtok)
        if not settled:
            waiter.done = True
            raise GetTimeoutError(
                f"get() timed out after {timeout}s on {len(oids)} objects")
        out = []
        for oid in oids:
            kind, payload = box.get(oid, ("error",
                                          ObjectLostError(f"{oid} missing")))
            if kind == "error":
                if isinstance(payload, BaseException):
                    raise payload
                raise TaskError(str(payload))
            try:
                out.append(self._load_location(payload))
            except ObjectLostError:
                # the holder died between the waiter firing and the
                # read: report the unreachable copy (the dispatcher
                # prunes it and re-executes the producer from lineage
                # when no live copy remains), then one fresh round-trip
                # picks up the reconstructed/re-hosted copy — mirrors
                # the worker-side _get_one_fresh retry
                self.inbox.put(("object_unreachable", oid,
                                getattr(payload, "node_id", None)
                                or self.node_id,
                                getattr(payload, "seal_seq", None)))
                out.append(self._reload_one(oid, timeout))
        return out

    def _reload_one(self, oid: str, timeout: Optional[float]) -> Any:
        """Single-object re-resolve after a stale-location read failed;
        lineage reconstruction resets the entry to pending, so a fresh
        waiter round-trip (_await_object) blocks until the re-run
        reseals it."""
        kind, payload = self._await_object(oid, timeout=timeout)
        if kind == "timeout":
            raise GetTimeoutError(
                f"get() timed out re-resolving lost object {oid}")
        if kind == "error":
            if isinstance(payload, BaseException):
                raise payload
            raise TaskError(str(payload))
        return self._load_location(payload)

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        ev = threading.Event()
        box: Dict[str, Any] = {"ready": []}

        def cb(results, ready):
            box["ready"] = ready
            ev.set()

        self._flush_submits()
        waiter = Waiter([r.id for r in refs], num_returns, cb,
                        needs_bytes=False)
        self.inbox.put(("api_waiter", waiter))
        # emulate timeout by a timer event so the dispatcher fires partial
        if timeout is not None:
            t = threading.Timer(timeout, lambda: self.inbox.put(
                ("waiter_timeout", waiter.waiter_id)))
            t.daemon = True
            t.start()
        wtok = _waits().park("object", refs[0].id if refs else "",
                             waiter="driver", op="wait", n=len(refs))
        try:
            ev.wait(None if timeout is None else timeout + 1.0)
        finally:
            _waits().unpark(wtok)
        ready_ids = set(box["ready"])
        ready = [r for r in refs if r.id in ready_ids]
        not_ready = [r for r in refs if r.id not in ready_ids]
        return ready, not_ready

    def kill_actor(self, actor_id: str, no_restart: bool = True) -> None:
        self._flush_submits()   # queued calls must land before the kill
        self.inbox.put(("api_kill_actor", actor_id, no_restart))

    def cancel(self, ref: ObjectRef, force: bool = False) -> None:
        # cancel resolves object -> producing task in the dispatcher:
        # the submit that created the object must be in the inbox first
        self._flush_submits()
        self.inbox.put(("api_cancel_obj", ref.id, force))

    def cancel_task(self, task_id: str, force: bool = False) -> None:
        """Cancel by task id (streaming-generator handles)."""
        self._flush_submits()
        self.inbox.put(("api_cancel", task_id, force))

    def free(self, refs: List[ObjectRef]) -> None:
        self._flush_submits()
        self.inbox.put(("api_free", [r.id for r in refs]))

    def report(self, channel: str, payload: Any) -> None:
        h = self.report_handlers.get(channel)
        if h:
            h("driver", payload)

    def register_report_handler(self, channel: str, fn: Callable) -> None:
        self.report_handlers[channel] = fn

    def _kv_op(self, op: str, *args):
        """Internal KV (ray_tpu.experimental.internal_kv). Locked: driver
        API threads call this directly while the dispatcher serves worker
        sys.kv requests, and iteration (list/del-by-prefix) plus put's
        check-then-set are not atomic under the GIL."""
        with self._kv_lock:
            kv = self.gcs.kv
            if op == "put":
                key, value, overwrite = args
                existed = key in kv
                if overwrite or not existed:
                    kv[key] = value
                    if self._persist is not None:
                        # WAL via the dispatcher: an API-thread append
                        # racing a snapshot rotation could land in the
                        # WAL generation being deleted and vanish
                        # raylint: disable=RT001 self.inbox is an
                        # unbounded queue.Queue; put never blocks
                        self.inbox.put(
                            ("wal", ("kvput", key, value)))
                return existed
            if op == "get":
                return kv.get(args[0])
            if op == "exists":
                return args[0] in kv
            if op == "del":
                key, by_prefix = args
                if self._persist is not None:
                    # raylint: disable=RT001 self.inbox is an
                    # unbounded queue.Queue; put never blocks
                    self.inbox.put(
                        ("wal", ("kvdel", key, by_prefix)))
                if by_prefix:
                    doomed = [k for k in kv if k.startswith(key)]
                    for k in doomed:
                        del kv[k]
                    return len(doomed)
                return 1 if kv.pop(key, None) is not None else 0
            if op == "list":
                # args[0] is the namespaced prefix "ns\x00p"; return the
                # un-namespaced key names, reference-style (bytes)
                return [k.split("\x00", 1)[1].encode() for k in kv
                        if k.startswith(args[0])]
            raise ValueError(f"unknown kv op {op!r}")

    def _on_worker_metrics(self, wid: str, payload) -> None:
        w = self.workers.get(wid)
        node = (w.node_id if w is not None and w.node_id else None) \
            or self.node_id
        self.cluster_metrics.ingest(
            {"node_id": node, "worker_id": wid}, payload)

    def drain_fastpath_spans(self) -> None:
        """Flush deferred driver-side span rings (compiled-DAG submit
        and result markers) into trace_spans. Runs when worker spans
        are ingested and when the timeline is exported, so readers see
        the complete parented tree without the execute() hot path ever
        paying dict-build or id-derivation costs."""
        for fn in list(self._span_drains):
            try:
                fn()
            except Exception:
                pass

    def _on_worker_spans(self, wid: str, payload) -> None:
        self.drain_fastpath_spans()
        w = self.workers.get(wid)
        node = (w.node_id if w is not None and w.node_id else None) \
            or self.node_id
        for sp in payload or ():
            sp = dict(sp)
            if not sp.get("worker_id"):
                sp["worker_id"] = wid
            if not sp.get("node_id"):
                sp["node_id"] = node
            self.trace_spans.append(sp)

    def _on_worker_profile(self, wid: str, payload) -> None:
        self.profile_store.ingest(wid, payload)

    def _on_worker_waits(self, wid: str, payload) -> None:
        w = self.workers.get(wid)
        node = (w.node_id if w is not None and w.node_id else None) \
            or self.node_id
        self.cluster_waits.ingest(
            wid, {"node_id": node, "worker_id": wid}, payload)

    def profile_ctl(self, worker_id: str, action: str,
                    arg: Any = None, timeout: float = 5.0) -> dict:
        """Drive one worker's sampling profiler over the control plane:
        action in {"start", "stop", "snapshot", "status"} (arg = hz for
        start). Blocks for the worker's reply (sub-ms handler on its
        reader thread) and returns the reply payload."""
        conn = self._conn_by_wid.get(worker_id)
        w = self.workers.get(worker_id)
        if conn is None or w is None or w.state == "dead":
            raise ValueError(f"no live worker {worker_id!r}")
        ev = threading.Event()
        box: dict = {}
        with self._profile_lock:
            self._profile_counter += 1
            rid = self._profile_counter
            self._profile_replies[rid] = (ev, box)
        try:
            conn.send(("profile_ctl", rid, action, arg))
            if not ev.wait(timeout):
                raise TimeoutError(
                    f"profile_ctl({action}) to {worker_id} timed out "
                    f"after {timeout}s")
        finally:
            with self._profile_lock:
                self._profile_replies.pop(rid, None)
        return box.get("payload", {})

    # ---------------- event plane ----------------
    def _emit(self, event_type: str, message: str = "", **fields) -> None:
        """Driver-side lifecycle event into the process-local buffer
        (drained into cluster_events on the tick / on query). Never
        raises — a telemetry failure must not break scheduling."""
        try:
            _ev().emit(event_type, message, **fields)
        except Exception:
            pass

    def _on_worker_events(self, wid: str, payload) -> None:
        w = self.workers.get(wid)
        node = (w.node_id if w is not None and w.node_id else None) \
            or self.node_id
        self.cluster_events.ingest(
            {"node_id": node, "worker_id": wid}, payload or ())

    def drain_local_events(self) -> None:
        """Move this process's buffered events into the cluster store.
        Called from the dispatcher tick and lazily by queries (so a
        just-emitted driver-side event is visible immediately)."""
        batch = _ev().drain()
        if batch:
            self.cluster_events.ingest(
                {"node_id": self.node_id, "worker_id": "driver"}, batch)

    def _check_node_heartbeats(self) -> None:
        """Flag remote nodes whose agent stopped pinging: the
        node.heartbeat_miss event precedes the socket-level death
        determination (reference: gcs health-check manager)."""
        if self._node_hb_timeout <= 0:
            return
        now = time.time()
        for ns in list(self.cluster_nodes.values()):
            if ns.conn is None or not ns.alive:
                continue
            stale = now - ns.last_heartbeat
            if not ns.heartbeat_missed and stale > self._node_hb_timeout:
                ns.heartbeat_missed = True
                self._emit(
                    "node.heartbeat_miss",
                    f"no heartbeat from node {ns.node_id} for "
                    f"{stale:.1f}s",
                    node_id=ns.node_id)
            if 0 < self._node_death_timeout < stale:
                # heartbeat-DECLARED death: don't wait for the socket to
                # close — prune the node's object copies and start
                # lineage reconstruction now. Closing the conn fences a
                # stalled-but-alive agent and prompts it to rejoin under
                # a new incarnation.
                conn = ns.conn
                self._on_node_dead(ns.node_id)
                try:
                    conn.close()
                except Exception:
                    pass

    def _update_builtin_gauges(self) -> None:
        """Periodic (reaper-tick) refresh of the driver-side pool/store
        gauges; failures must never take down the dispatcher."""
        try:
            by_state: Dict[str, int] = {}
            for w in self.workers.values():
                by_state[w.state] = by_state.get(w.state, 0) + 1
            g = _mcat().get("ray_tpu_workers")
            for state in ("starting", "idle", "busy", "actor", "dead"):
                g.set(float(by_state.get(state, 0)),
                      tags={"state": state})
            _mcat().get("ray_tpu_pending_tasks").set(
                float(len(self.pending_tasks)))
            _mcat().get("ray_tpu_object_store_used_bytes").set(
                float(self.store.used_bytes()))
            cap = getattr(self.store, "capacity", None)
            if cap:
                _mcat().get("ray_tpu_object_store_capacity_bytes").set(
                    float(cap))
            nobj = getattr(self.store, "num_objects", None)
            if callable(nobj):
                _mcat().get("ray_tpu_object_store_objects").set(
                    float(nobj()))
            if self._persist is not None:
                _mcat().get("ray_tpu_driver_incarnation").set(
                    float(self.incarnation))
                _mcat().get("ray_tpu_wal_records").set(
                    float(self._persist.records_appended))
                _mcat().get("ray_tpu_wal_bytes").set(
                    float(self._persist.wal_bytes))
        except Exception:
            pass

    def _sys_lookup_actor(self, _wid, payload) -> Optional[tuple]:
        """Built-in report_sync channel backing get_actor() from workers."""
        ns, name = payload
        if ns is None:
            ns = self.namespace
        aid = self.gcs.lookup_named_actor(ns, name)
        if aid is None:
            return None
        ae = self.gcs.actors[aid]
        return (aid, ae.class_name,
                getattr(ae.create_spec, "method_opts", {}) or {})

    def _sys_actor_addr(self, _wid, actor_id):
        """GCS actor directory (report_sync): the callee's direct-call
        address for driver-bypass actor calls. One lookup per
        (caller, actor) pair steady-state. None = never reachable
        direct (dead, or its worker runs no direct server — the caller
        backs off for a while); "pending" = constructing/restarting
        (the caller retries almost immediately, so the first calls of a
        fresh actor don't condemn a whole burst to the driver path)."""
        ae = self.gcs.actors.get(actor_id)
        if ae is None or ae.state == "DEAD":
            return None
        if ae.state != "ALIVE" or not ae.worker_id:
            return "pending"
        w = self.workers.get(ae.worker_id)
        if w is None or w.state == "dead":
            return "pending"   # death determination/restart in flight
        if not w.direct_addr:
            return None        # worker has no direct-call listener
        return (ae.worker_id, w.direct_addr, ae.num_restarts)

    def dispatch_stats(self) -> Dict[str, Any]:
        """Dispatch-plane counters for the state API / CLI / bench:
        submit batching, lease lifecycle, frame and logical-message
        counts (messages-per-task is the control-plane amplification
        the batching exists to kill)."""
        from .protocol import wire_enabled  # noqa: PLC0415
        return {
            "batching_enabled": self._batch_enabled,
            "binary_wire_enabled": wire_enabled(),
            "flush_max_tasks": self._flush_n,
            "flush_window_s": self._flush_window,
            "lease_slots": self._lease_cap,
            "actor_pipeline": self._actor_pipeline,
            "submit_many_calls": self.submit_many_calls,
            "submit_batches": self.submit_batches,
            "batched_submits": self.batched_submits,
            "avg_submit_batch": round(
                self.batched_submits / self.submit_batches, 2)
            if self.submit_batches else None,
            "lease_grants": self.lease_grants,
            "lease_revokes": self.lease_revokes,
            "node_leases_enabled": self._node_leases_enabled,
            "node_lease_slots": self._node_lease_slots,
            "node_lease_grants": self.node_lease_grants,
            "node_lease_extends": self.node_lease_extends,
            "node_lease_tasks": self.node_lease_tasks,
            "node_leases_open": len(self.node_leases),
            "spillbacks": self.spillbacks,
            "dispatch_frames": self.dispatch_frames,
            "dispatched_tasks": self.dispatched_tasks,
            "ctrl_frames_in": self.ctrl_frames,
            "ctrl_msgs_in": dict(self.ctrl_msgs),
        }

    def _sys_cluster_view(self, _wid, _payload) -> List[Dict]:
        """report_sync channel: live node capacity views for worker-side
        schedulers (the serve autoscaler's bin-pack feasibility)."""
        views = []
        for ns in list(self.cluster_nodes.values()):
            if not ns.alive:
                continue
            views.append({"id": ns.node_id, "total": dict(ns.total),
                          "avail": dict(ns.avail),
                          "labels": dict(getattr(ns, "labels", {}) or {}),
                          "is_driver": ns.node_id == self.node_id})
        return views

    def _sys_pg(self, _wid, payload):
        """report_sync channel: placement-group create/remove/table from
        worker processes (actors only get `.pg_id` back — bundle node
        resolution happens at scheduling time like every other pg)."""
        op = payload[0]
        if op == "create":
            _, bundles, strategy, name = payload
            pg = self.placement_group(bundles, strategy, name)
            return {"pg_id": pg.pg_id}
        if op == "remove":
            self.remove_placement_group(payload[1])
            return True
        if op == "table":
            return {pg.pg_id: {"name": pg.name, "strategy": pg.strategy,
                               "state": pg.state,
                               "bundles": list(pg.bundles)}
                    for pg in list(self.placement_groups.values())}
        raise ValueError(f"unknown sys.pg op {op!r}")

    def placement_group(self, bundles, strategy="PACK", name="") -> "PlacementGroupState":
        from .ids import new_placement_group_id  # noqa: PLC0415
        pg = PlacementGroupState(new_placement_group_id(), bundles, strategy,
                                 name)
        pg.ready_ref = new_object_id()
        self.gcs.add_pending_object(pg.ready_ref)
        self.inbox.put(("api_create_pg", pg))
        return pg

    def remove_placement_group(self, pg_id: str) -> None:
        self.inbox.put(("api_remove_pg", pg_id))

    # ---------------- compiled DAGs (docs/DAG.md) ----------------
    def dag_acquire(self, dag_id: str, reqs: List[dict],
                    timeout: float) -> Dict[Any, dict]:
        """Pin one worker per compiled-DAG stage (dependency-local).
        Blocks the calling API thread; placement itself happens on the
        dispatcher. Raises CompiledDagError when placement fails."""
        reply: "queue.Queue" = queue.Queue()
        self.inbox.put(("api_dag_acquire", {
            "dag_id": dag_id, "reqs": reqs, "reply": reply,
            "deadline": time.time() + timeout}))
        try:
            res = reply.get(timeout=timeout + 5.0)
        except queue.Empty:
            raise CompiledDagError("compiled-DAG placement timed out",
                                   cause="dispatcher unresponsive") \
                from None
        if "error" in res:
            raise CompiledDagError("compiled-DAG placement failed",
                                   cause=res["error"])
        return res["placement"]

    def dag_release(self, dag_id: str, wids: List[str],
                    channels: int = 0, reason: str = "") -> None:
        self.inbox.put(("api_dag_release", dag_id, list(wids),
                        {"channels": channels, "reason": reason}))

    def get_resources(self) -> Dict[str, float]:
        total: Dict[str, float] = {}
        for n in self.cluster_nodes.values():
            if n.alive:
                for k, v in n.total.items():
                    total[k] = total.get(k, 0.0) + v
        return total

    def available_resources(self) -> Dict[str, float]:
        avail: Dict[str, float] = {}
        for n in self.cluster_nodes.values():
            if n.alive:
                for k, v in n.avail.items():
                    avail[k] = avail.get(k, 0.0) + v
        return avail

    def actor_state(self, actor_id: str) -> Optional[str]:
        ae = self.gcs.actors.get(actor_id)
        return ae.state if ae else None

    def wait_actor_alive(self, actor_id: str, timeout: float = 60.0) -> None:
        deadline = time.time() + timeout
        while time.time() < deadline:
            ae = self.gcs.actors.get(actor_id)
            if ae is not None and ae.state == "ALIVE":
                return
            if ae is not None and ae.state == "DEAD":
                raise ActorDiedError(
                    f"actor failed to start: {ae.death_cause}")
            time.sleep(0.005)
        raise GetTimeoutError(f"actor {actor_id} not alive in {timeout}s")

    # ---------------- shutdown ----------------
    def shutdown(self) -> None:
        if self._shutdown.is_set():
            return
        self._flush_submits()
        for ctl in list(self.compiled_dags.values()):
            try:
                ctl.close()
            except Exception:
                pass
        self._shutdown.set()
        self._submit_buf_event.set()   # unblock the flush loop
        if self._persist is not None:
            # final snapshot BEFORE teardown: it must capture the live
            # cluster (ALIVE actors, sealed objects), not the storm of
            # worker/actor deaths the shutdown itself is about to
            # cause — and it must run ON the dispatcher thread, where
            # the tables are consistent. close() then stops further
            # WAL appends, so those teardown deaths never reach the
            # persisted state and a planned restart resumes the job as
            # it last ran.
            done = threading.Event()
            self.inbox.put(("final_snapshot", done))
            snapped = done.wait(timeout=5.0)
            # dispatcher wedged/dead: degrade to a caller-side snapshot
            # attempt (snapshot() tolerates a racing mutation by
            # failing closed) rather than skipping the final state
            self._persist.close(
                None if snapped else self._snapshot_tables)
        for n in list(self.cluster_nodes.values()):
            if n.conn is not None:
                try:
                    n.conn.send(("shutdown",))
                except Exception:
                    pass
        for w in list(self.workers.values()):
            try:
                if w.conn:
                    w.conn.send(("shutdown",))
            except Exception:
                pass
        time.sleep(0.05)
        for w in list(self.workers.values()):
            try:
                w.proc.terminate()
            except Exception:
                pass
        deadline = time.time() + 2.0
        for w in list(self.workers.values()):
            try:
                w.proc.wait(timeout=max(0.01, deadline - time.time()))
            except Exception:
                try:
                    w.proc.kill()
                except Exception:
                    pass
        try:
            self._listener.close()
        except Exception:
            pass
        if self._tcp_listener is not None:
            try:
                self._tcp_listener.close()
            except Exception:
                pass
        if self._transfer_server is not None:
            try:
                self._transfer_server.close()
            except Exception:
                pass
        if self._log_streamer is not None:
            self._log_streamer.stop()
        self.inbox.put(None)
        self.store.shutdown()
        # Undo env we set so a later init() in this process gets a fresh
        # spill dir / node id instead of this runtime's dead paths.
        if self._spill_env_owned:
            os.environ.pop("RAY_TPU_SPILL_DIR", None)
        if knobs.get_raw("RAY_TPU_NODE_ID") == self.node_id:
            os.environ.pop("RAY_TPU_NODE_ID", None)
        import shutil
        shutil.rmtree(self._tmpdir, ignore_errors=True)
        global _runtime
        with _runtime_lock:
            if _runtime is self:
                _runtime = None
