"""Actor API: ActorClass (decorated class) and ActorHandle.

Reference parity: python/ray/actor.py (ActorClass.remote, ActorHandle
method invocation, .options, named actors, max_restarts/max_concurrency).
"""
from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional

from . import runtime as runtime_mod
from . import serialization
from .ids import new_actor_id, new_task_id, new_object_id
from .object_ref import ObjectRef
from .task import TaskSpec, ActorCreationSpec, extract_arg_deps


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str,
                 num_returns: int = 1):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns

    def remote(self, *args, **kwargs):
        return self._handle._invoke(self._method_name, args, kwargs,
                                    self._num_returns)

    def options(self, num_returns: int = 1):
        return ActorMethod(self._handle, self._method_name, num_returns)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor methods cannot be called directly; use "
            f"`.{self._method_name}.remote()`")


class ActorHandle:
    """Serializable handle to a running actor (pass freely between tasks)."""

    def __init__(self, actor_id: str, class_name: str = "",
                 method_opts: Optional[Dict[str, Dict[str, Any]]] = None):
        self._actor_id = actor_id
        self._class_name = class_name
        # per-method defaults declared with @ray_tpu.method(...) on the
        # class (reference: python/ray/actor.py ray.method decorator)
        self._method_opts = method_opts or {}

    @property
    def actor_id(self) -> str:
        return self._actor_id

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        opts = self.__dict__.get("_method_opts", {}).get(name, {})
        # concurrency_group is applied at TaskSpec build, not here
        m = ActorMethod(self, name,
                        num_returns=opts.get("num_returns", 1))
        # cache on the instance: hot call loops (`h.ping.remote()` per
        # request) stop paying __getattr__ + an ActorMethod alloc per
        # call; __reduce__ controls pickling, so the cache never ships
        self.__dict__[name] = m
        return m

    def _make_task_spec(self, method_name: str, args, kwargs,
                        num_returns=1):
        """Build the method-call TaskSpec without submitting (compiled
        DAGs batch these through runtime.submit_many). Returns
        (spec, streaming)."""
        from ..util import tracing  # noqa: PLC0415
        streaming = num_returns in ("streaming", "dynamic")
        n = 1 if streaming else num_returns
        trace_id, span_id, parent_span_id = tracing.submit_context()
        spec = TaskSpec(
            task_id=new_task_id(),
            name=f"{self._class_name}.{method_name}",
            func_bytes=b"",
            args=tuple(args),
            kwargs=dict(kwargs),
            num_returns=n,
            return_ids=[] if streaming
            else [new_object_id() for _ in range(max(n, 1))],
            resources={},
            actor_id=self._actor_id,
            method_name=method_name,
            concurrency_group=(self._method_opts.get(method_name)
                               or {}).get("concurrency_group"),
            streaming=streaming,
            dep_object_ids=extract_arg_deps(args, kwargs),
            trace_id=trace_id, span_id=span_id,
            parent_span_id=parent_span_id,
        )
        return spec, streaming

    def _invoke(self, method_name: str, args, kwargs,
                num_returns=1) -> Any:
        rt = runtime_mod.get_runtime()
        spec, streaming = self._make_task_spec(method_name, args, kwargs,
                                               num_returns)
        refs = rt.submit_actor_task(spec)
        if streaming:
            from .object_ref import ObjectRefGenerator  # noqa: PLC0415
            return ObjectRefGenerator(spec.task_id)
        return refs[0] if num_returns == 1 else refs

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._class_name,
                              self._method_opts))

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id})"


def _collect_method_opts(cls) -> Dict[str, Dict[str, Any]]:
    opts: Dict[str, Dict[str, Any]] = {}
    for name in dir(cls):
        if name.startswith("__"):
            continue
        fn = getattr(cls, name, None)
        mo = getattr(fn, "__ray_tpu_method_opts__", None)
        if mo:
            opts[name] = dict(mo)
    return opts


class ActorClass:
    def __init__(self, cls, *, num_cpus=None, num_tpus=None, resources=None,
                 max_restarts=0, max_concurrency=1, concurrency_groups=None,
                 name=None, namespace=None, lifetime=None, runtime_env=None,
                 placement_group=None, bundle_index=-1,
                 scheduling_strategy=None, get_if_exists=False,
                 checkpoint_interval_s=None):
        from . import runtime_env as renv_mod
        runtime_env = renv_mod.validate(runtime_env) or None
        self._cls = cls
        if concurrency_groups and any(
                n < 1 for n in concurrency_groups.values()):
            raise ValueError("concurrency_groups limits must be >= 1")
        self._default_opts = dict(
            num_cpus=num_cpus, num_tpus=num_tpus, resources=resources,
            max_restarts=max_restarts, max_concurrency=max_concurrency,
            concurrency_groups=dict(concurrency_groups or {}),
            name=name, namespace=namespace, lifetime=lifetime,
            runtime_env=runtime_env, placement_group=placement_group,
            bundle_index=bundle_index,
            scheduling_strategy=scheduling_strategy,
            get_if_exists=get_if_exists,
            checkpoint_interval_s=checkpoint_interval_s)
        self._class_bytes: Optional[bytes] = None

    def options(self, **opts) -> "ActorClass":
        merged = dict(self._default_opts)
        merged.update(opts)
        ac = ActorClass(self._cls, **{k: v for k, v in merged.items()
                                      if k in self._default_opts})
        ac._class_bytes = self._class_bytes
        return ac

    def remote(self, *args, **kwargs) -> ActorHandle:
        opts = self._default_opts
        if opts.get("get_if_exists") and opts.get("name"):
            # Reference: .options(name=..., get_if_exists=True) — return the
            # live named actor instead of failing on the name collision.
            # Name registration is async in the dispatcher, so a miss here
            # may be a race with an in-flight creation: create our own, then
            # re-resolve through the registry — the first registrant wins and
            # a losing duplicate dies on the name collision unreferenced.
            from .. import api as _api  # noqa: PLC0415
            try:
                return _api.get_actor(opts["name"], opts["namespace"],
                                      timeout=0.0)
            except ValueError:
                self._create(args, kwargs)
                return _api.get_actor(opts["name"], opts["namespace"])
        return self._create(args, kwargs)

    def _create(self, args, kwargs) -> ActorHandle:
        from . import resources as res_mod  # noqa: PLC0415
        from ..api import _resolve_pg_strategy  # noqa: PLC0415
        rt = runtime_mod.get_runtime()
        opts = _resolve_pg_strategy(self._default_opts)
        if self._class_bytes is None:
            self._class_bytes = serialization.dumps_call(self._cls)
        actor_id = new_actor_id()
        pg = opts.get("placement_group")
        req = res_mod.normalize_task_resources(
            num_cpus=opts["num_cpus"], num_tpus=opts["num_tpus"],
            resources=opts["resources"], default_cpus=1.0)
        method_opts = _collect_method_opts(self._cls)
        acspec = ActorCreationSpec(
            actor_id=actor_id,
            class_bytes=self._class_bytes,
            class_name=self._cls.__name__,
            method_opts=method_opts,
            args=tuple(args),
            kwargs=dict(kwargs),
            resources={} if pg is not None else req,
            max_restarts=opts["max_restarts"] or 0,
            max_concurrency=opts["max_concurrency"] or 1,
            concurrency_groups=dict(opts.get("concurrency_groups") or {}),
            name=opts["name"],
            namespace=opts["namespace"] or getattr(rt, "namespace", "default"),
            checkpoint_interval_s=opts.get("checkpoint_interval_s"),
            placement_group_id=getattr(pg, "pg_id", None),
            bundle_index=opts.get("bundle_index", -1),
            scheduling_strategy=opts.get("scheduling_strategy"),
            runtime_env=opts["runtime_env"],
            dep_object_ids=extract_arg_deps(args, kwargs),
        )
        rt.create_actor(acspec)
        return ActorHandle(actor_id, self._cls.__name__,
                           method_opts=method_opts)

    def bind(self, *args, **kwargs):
        """Record a lazy actor-construction DAG node (ray.dag ClassNode)."""
        from ..dag import ClassNode
        return ClassNode(self, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            "Actor classes must be instantiated with `.remote()`")
