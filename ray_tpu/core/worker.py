"""Worker process: executes tasks and hosts actors.

Reference parity: src/ray/core_worker/core_worker.cc (task execution,
arg resolution, return-object sealing) + python/ray/_private/worker.py
(the Python worker loop). One OS process per worker; a reader thread
demultiplexes driver messages into an execution queue and reply slots, so
user code can block in `get()` while new messages keep flowing.

Run as: python -m ray_tpu.core.worker <socket_path> <worker_id>
"""
from __future__ import annotations

import os
import queue
import sys
import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional

from . import logging as logging_mod
from . import serialization
from .ids import new_object_id
from .object_ref import ObjectRef
from .object_store import ShmStore, ObjectLocation, INLINE_MAX, make_store
from .protocol import Connection, ConnectionClosed, connect_address
from .task import TaskSpec, ActorCreationSpec
from ..exceptions import TaskError, GetTimeoutError, ObjectLostError
from ..util import events as events_mod
from ..util import metrics as metrics_mod
from ..util import metrics_catalog as mcat
from ..util import tracing


class WorkerRuntime:
    """The runtime visible to user code running inside this worker.

    Implements the same verbs as the driver runtime so `ray_tpu.get/put/
    remote` work transparently in nested tasks.
    """

    is_driver = False

    def __init__(self, conn: Connection, worker_id: str, store: ShmStore):
        self.conn = conn
        self.worker_id = worker_id
        self.store = store
        self._replies: Dict[str, queue.Queue] = {}
        self._replies_lock = threading.Lock()
        # (rid, oid) -> bytearray for cross-node values streamed in
        # chunks ahead of the final get_reply (same socket => in order)
        self._value_chunks: Dict[tuple, bytearray] = {}
        self._req_counter = 0
        self._func_cache: Dict[str, Any] = {}
        self.current_task_id: Optional[str] = None
        self.current_actor_id: Optional[str] = None
        self.current_tpu_ids: list = []
        # this worker's actor began life via __ray_restore__ (surfaced
        # as RuntimeContext.was_current_actor_reconstructed)
        self.actor_restored = False
        self.job_id = os.environ.get("RAY_TPU_JOB_ID", "job-default")

    # ---- request/reply over the driver connection -------------------------
    def _new_req(self) -> str:
        with self._replies_lock:
            self._req_counter += 1
            rid = f"{self.worker_id}:{self._req_counter}"
            q: queue.Queue = queue.Queue(maxsize=1)
            self._replies[rid] = q
        return rid

    def _take_reply(self, rid: str, timeout: Optional[float]) -> Any:
        q = self._replies[rid]
        try:
            return q.get(timeout=timeout)
        except queue.Empty:
            raise GetTimeoutError(f"request {rid} timed out") from None
        finally:
            with self._replies_lock:
                self._replies.pop(rid, None)

    def stash_value_chunk(self, rid: str, oid: str, off: int,
                          total: int, chunk: bytes) -> None:
        buf = self._value_chunks.get((rid, oid))
        if buf is None:
            buf = self._value_chunks[(rid, oid)] = bytearray(total)
        buf[off:off + len(chunk)] = chunk

    def take_staged_value(self, rid: str, oid: str) -> bytes:
        return bytes(self._value_chunks.pop((rid, oid)))

    def deliver_reply(self, rid: str, payload: Any) -> None:
        with self._replies_lock:
            q = self._replies.get(rid)
        if q is not None:
            q.put(payload)

    # ---- core verbs -------------------------------------------------------
    def get(self, refs: List[ObjectRef], timeout: Optional[float] = None) -> List[Any]:
        oids = [r.id for r in refs]
        # device-resident fast path: objects THIS worker produced are
        # served from the in-process table — no driver round-trip, no
        # D2H, no deserialization (core/device_store.py)
        from . import device_store  # noqa: PLC0415
        local = {}
        for oid in oids:
            try:
                local[oid] = device_store.get(oid)
            except KeyError:
                pass
        if len(local) == len(oids):
            return [local[oid] for oid in oids]
        remote_oids = [oid for oid in oids if oid not in local]
        rid = self._new_req()
        self.conn.send(("get_request", rid, remote_oids, timeout))
        results = self._take_reply(rid, timeout)  # {oid: (kind, payload)}
        out = []
        for oid in oids:
            if oid in local:
                out.append(local[oid])
                continue
            kind, payload = results[oid]
            if kind == "error":
                raise payload if isinstance(payload, BaseException) else TaskError(str(payload))
            if kind == "value":
                # cross-node object: the driver shipped the packed bytes
                # (its node fetched them from the holder's store)
                out.append(serialization.unpack(payload))
            elif kind == "value_staged":
                # big cross-node object: bytes arrived ahead of the reply
                # as value_chunk frames
                out.append(serialization.unpack(
                    self.take_staged_value(rid, oid)))
            else:
                try:
                    out.append(self.store.get_value(payload))
                except ObjectLostError:
                    # The spiller (or arena LRU) dropped the segment after
                    # this loc was serialized but before we read it; a
                    # fresh request returns a spill-aware loc (or the
                    # re-hosted bytes). One retry closes the race.
                    out.append(self._get_one_fresh(oid, timeout))
        return out

    def _get_one_fresh(self, oid: str, timeout: Optional[float],
                       _retried: bool = False) -> Any:
        t0 = time.monotonic()
        rid = self._new_req()
        self.conn.send(("get_request", rid, [oid], timeout))
        kind, payload = self._take_reply(rid, timeout)[oid]
        if kind == "error":
            raise payload if isinstance(payload, BaseException) \
                else TaskError(str(payload))
        if kind == "value":
            return serialization.unpack(payload)
        if kind == "value_staged":
            return serialization.unpack(self.take_staged_value(rid, oid))
        try:
            return self.store.get_value(payload)
        except ObjectLostError:
            if _retried:
                raise
            # segment gone without a spill copy: report the unreachable
            # location (the driver prunes it and reconstructs from
            # lineage when no live copy remains) and take ONE more
            # round-trip — on the REMAINING timeout budget, so
            # get(timeout=T) still bounds at ~T, not 2T
            self.conn.send(("object_unreachable", oid,
                            getattr(payload, "node_id", None)
                            or os.environ.get("RAY_TPU_NODE_ID"),
                            getattr(payload, "seal_seq", None)))
            remaining = None if timeout is None else max(
                0.1, timeout - (time.monotonic() - t0))
            return self._get_one_fresh(oid, remaining, _retried=True)

    def put(self, value: Any) -> ObjectRef:
        from . import device_store  # noqa: PLC0415
        oid = new_object_id()
        # jax.Arrays stay device-resident here; the driver pulls a
        # materialized copy only if a consumer elsewhere needs it
        loc = device_store.try_keep(self.store, self.worker_id, oid,
                                    value)
        self.conn.send(("put", oid, loc))
        return ObjectRef(oid)

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        rid = self._new_req()
        self.conn.send(("wait_request", rid, [r.id for r in refs],
                        num_returns, timeout))
        ready_ids = set(self._take_reply(rid, None))
        ready = [r for r in refs if r.id in ready_ids]
        not_ready = [r for r in refs if r.id not in ready_ids]
        return ready, not_ready

    def submit(self, spec: TaskSpec) -> List[ObjectRef]:
        self.conn.send(("submit", spec))
        return [ObjectRef(oid) for oid in spec.return_ids]

    def create_actor(self, acspec: ActorCreationSpec) -> None:
        self.conn.send(("submit_actor", acspec))

    def submit_actor_task(self, spec: TaskSpec) -> List[ObjectRef]:
        self.conn.send(("submit", spec))
        return [ObjectRef(oid) for oid in spec.return_ids]

    def kill_actor(self, actor_id: str, no_restart: bool = True) -> None:
        self.conn.send(("kill_actor", actor_id, no_restart))

    def cancel(self, ref: ObjectRef, force: bool = False) -> None:
        self.conn.send(("cancel", ref.id, force))

    def cancel_task(self, task_id: str, force: bool = False) -> None:
        self.conn.send(("cancel", task_id, force))

    def report(self, channel: str, payload: Any) -> None:
        """Out-of-band message to the driver (train session, metrics...)."""
        self.conn.send(("report", channel, payload))

    def report_sync(self, channel: str, payload: Any, timeout=None) -> Any:
        rid = self._new_req()
        self.conn.send(("report_sync", rid, channel, payload))
        return self._take_reply(rid, timeout)

    def gen_next(self, task_id: str, timeout=None):
        """Worker-side consumption of a streaming generator: ask the
        driver for the next item ref (blocks until one streams in)."""
        from .object_ref import ObjectRef  # noqa: PLC0415
        from ..exceptions import TaskError  # noqa: PLC0415
        rid = self._new_req()
        self.conn.send(("gen_next_request", rid, task_id))
        try:
            kind, payload = self._take_reply(rid, timeout)
        except GetTimeoutError:
            # Tell the driver to drop the parked waiter (and restore the
            # CPU it lent back) so a later item isn't popped into a
            # reply nobody consumes.
            self.conn.send(("gen_abandon", rid))
            raise
        if kind == "item":
            return ObjectRef(payload)
        if kind == "error":
            if isinstance(payload, BaseException):
                raise payload
            raise TaskError(str(payload))
        return None

    def get_resources(self) -> Dict[str, float]:
        return {}

    def shutdown(self) -> None:
        pass

    # ---- function cache ---------------------------------------------------
    def load_func(self, spec: TaskSpec):
        if spec.func_id and spec.func_id in self._func_cache:
            return self._func_cache[spec.func_id]
        fn = serialization.loads_call(spec.func_bytes)
        if spec.func_id:
            self._func_cache[spec.func_id] = fn
        return fn


def _resolve_args(rt: WorkerRuntime, args, kwargs):
    """Fetch top-level ObjectRef args (deps are ready by scheduling time)."""
    refs = [a for a in list(args) + list(kwargs.values())
            if isinstance(a, ObjectRef)]
    if not refs:
        return args, kwargs
    vals = rt.get(refs)
    table = {r.id: v for r, v in zip(refs, vals)}
    new_args = tuple(table[a.id] if isinstance(a, ObjectRef) else a
                     for a in args)
    new_kwargs = {k: (table[v.id] if isinstance(v, ObjectRef) else v)
                  for k, v in kwargs.items()}
    return new_args, new_kwargs


class WorkerLoop:
    def __init__(self, socket_path: str, worker_id: str):
        # socket_path is a unix path for same-host workers or
        # "tcp://host:port" for workers spawned by a remote node agent.
        self.conn = connect_address(socket_path)
        self.store = make_store(capacity_bytes=int(
            os.environ.get("RAY_TPU_STORE_BYTES", str(8 << 30))), is_owner=False)
        self.rt = WorkerRuntime(self.conn, worker_id, self.store)
        self.worker_id = worker_id
        self._task_q: "queue.Queue" = queue.Queue()
        self._shutdown = threading.Event()
        self._actor_instance: Any = None
        self._actor_spec: Optional[ActorCreationSpec] = None
        self._actor_pool: Optional[ThreadPoolExecutor] = None
        self._group_pools: Dict[str, ThreadPoolExecutor] = {}
        self._async_loop = None
        self._cancelled: set = set()
        # telemetry plane: metric deltas + execution spans ship to the
        # driver over the existing conn (report channels sys.metrics /
        # sys.spans) after each task and on a periodic heartbeat, so
        # the driver's /metrics exposes series recorded IN this process
        self._delta_exporter = metrics_mod.DeltaExporter()
        self._spans: List[dict] = []
        self._telemetry_lock = threading.Lock()
        self._last_flush = 0.0
        self._heartbeat_on = True   # set from env in run()
        # __ray_save__ checkpoint shipping (actors that define the hook)
        self._ckpt_lock = threading.Lock()
        self._last_ckpt = 0.0

    # ---- main -------------------------------------------------------------
    def run(self) -> None:
        from . import runtime as runtime_mod  # noqa: PLC0415
        runtime_mod.set_runtime(self.rt)
        self.conn.send(("register", self.worker_id, os.getpid()))
        reader = threading.Thread(target=self._read_loop, daemon=True)
        reader.start()
        interval = float(os.environ.get("RAY_TPU_METRICS_INTERVAL_S",
                                        "1.0"))
        self._heartbeat_on = interval > 0
        if interval > 0:
            threading.Thread(target=self._telemetry_loop,
                             args=(interval,), daemon=True,
                             name="worker-telemetry").start()
        while not self._shutdown.is_set():
            try:
                item = self._task_q.get(timeout=0.2)
            except queue.Empty:
                continue
            kind, payload = item
            if kind == "task":
                self._run_task(payload)
            elif kind == "create_actor":
                self._create_actor(payload)
            elif kind == "actor_task":
                self._dispatch_actor_task(payload)
        try:
            self.conn.close()
        except Exception:
            pass

    def _read_loop(self) -> None:
        from .protocol import RECV_ERROR  # noqa: PLC0415
        while True:
            try:
                msg = self.conn.recv()
            except ConnectionClosed:
                self._shutdown.set()
                os._exit(0)
            mtype = msg[0]
            if mtype == RECV_ERROR:
                sys.stderr.write(
                    f"[ray_tpu worker {self.worker_id}] dropped "
                    f"undeserializable message:\n{msg[1]}")
                continue
            if mtype == "exec_task":
                self._task_q.put(("task", msg[1]))
            elif mtype == "create_actor":
                # (acspec, checkpoint|None) — the checkpoint is the
                # actor's latest __ray_save__ state around a restart
                self._task_q.put(("create_actor",
                                  (msg[1],
                                   msg[2] if len(msg) > 2 else None)))
            elif mtype == "exec_actor_task":
                self._task_q.put(("actor_task", msg[1]))
            elif mtype == "get_reply":
                self.rt.deliver_reply(msg[1], msg[2])
            elif mtype == "value_chunk":
                self.rt.stash_value_chunk(msg[1], msg[2], msg[3], msg[4],
                                          msg[5])
            elif mtype == "cancel":
                self._cancelled.add(msg[1])
            elif mtype == "materialize":
                self._materialize(msg[1])
            elif mtype == "drop_device":
                from . import device_store  # noqa: PLC0415
                device_store.drop(msg[1])
            elif mtype == "shutdown":
                self._shutdown.set()

    # ---- telemetry --------------------------------------------------------
    def _telemetry_loop(self, interval: float) -> None:
        """Heartbeat shipping for long-running work (an actor hosting an
        LLM engine records tokens continuously with no task boundary)."""
        while not self._shutdown.is_set():
            time.sleep(interval)
            self._flush_telemetry()

    def _record_span(self, spec: TaskSpec, span_id: str, start: float,
                     end: float, status: str) -> None:
        with self._telemetry_lock:
            self._spans.append({
            "trace_id": getattr(spec, "trace_id", "") or "",
            "span_id": span_id,
            "parent_span_id": getattr(spec, "span_id", "") or "",
            "task_id": spec.task_id, "name": spec.name,
                "start": start, "end": end, "status": status,
                "pid": os.getpid(), "worker_id": self.worker_id,
                "node_id": os.environ.get("RAY_TPU_NODE_ID"),
            })

    def _flush_telemetry(self, min_interval: float = 0.0) -> None:
        """Ship buffered spans + registry deltas. Never raises — a
        telemetry failure must not fail user work. min_interval > 0
        throttles the registry walk (sub-millisecond task storms must
        not pay a full delta collect per task; the heartbeat thread
        ships whatever a throttled call left buffered)."""
        with self._telemetry_lock:
            now = time.monotonic()
            if min_interval and now - self._last_flush < min_interval:
                return
            self._last_flush = now
            spans, self._spans = self._spans, []
            try:
                payload = self._delta_exporter.collect()
            except Exception:
                payload = None
        try:
            events = events_mod.drain()
        except Exception:
            events = None
        try:
            if spans:
                self.conn.send(("report", "sys.spans", spans))
            if payload:
                self.conn.send(("report", "sys.metrics", payload))
            if events:
                self.conn.send(("report", "sys.events", events))
        except Exception:  # ConnectionClosed included: driver is gone
            pass

    def _finish_task_telemetry(self, spec: TaskSpec, span_id: str,
                               start: float, status: str) -> None:
        end = time.time()
        try:
            mcat.get("ray_tpu_worker_task_run_s").observe(end - start)
            mcat.get("ray_tpu_worker_tasks_total").inc(
                tags={"status": status})
        except Exception:
            pass
        try:
            self._record_span(spec, span_id, start, end, status)
        except Exception:
            pass
        # throttle only when the heartbeat will sweep the leftovers
        self._flush_telemetry(
            min_interval=0.2 if self._heartbeat_on else 0.0)

    # ---- execution --------------------------------------------------------
    def _seal_returns(self, spec: TaskSpec, result: Any):
        """Pack return values; small ones ride inline in task_done.

        Values holding live jax.Arrays stay DEVICE-RESIDENT in this
        process (core/device_store.py): the sealed location is a device
        handle; same-worker consumers read the live value with no D2H,
        and the driver asks us to materialize only when a consumer
        elsewhere needs the bytes."""
        n = spec.num_returns
        values = (result,) if n == 1 else tuple(result)
        if n > 1 and len(values) != n:
            raise ValueError(
                f"task {spec.name} declared num_returns={n} but returned "
                f"{len(values)} values")
        from . import device_store  # noqa: PLC0415
        sealed = []
        for oid, val in zip(spec.return_ids, values):
            sealed.append((oid, device_store.try_keep(
                self.store, self.worker_id, oid, val)))
        return sealed

    def _materialize(self, oid: str) -> None:
        """Driver asked for a device-resident object's bytes (a consumer
        is elsewhere): serialize to the shm store and re-seal. Runs on
        the reader thread (Connection.send is locked; the shm arena is
        process-shared-mutex guarded), so a long-running task here can't
        stall a remote consumer."""
        from . import device_store  # noqa: PLC0415
        from .spilling import put_value_or_spill  # noqa: PLC0415
        val = device_store.peek(oid)
        if val is None:
            self.conn.send(("materialize_failed", oid,
                            "not resident on this worker"))
            return
        try:
            loc = put_value_or_spill(self.store, oid, val)
        except BaseException as e:  # noqa: BLE001
            self.conn.send(("materialize_failed", oid, repr(e)))
            return
        device_store.COUNTERS["materialized"] += 1
        # the host copy now serves every consumer (local ones included):
        # drop the device entry so HBM is reclaimed and the table never
        # pins long-dead values. A distinct message type (not "put")
        # lets the driver detect an object freed mid-materialize and
        # reclaim the fresh shm copy instead of resurrecting a ghost.
        device_store.drop(oid)
        self.conn.send(("materialized", oid, loc))

    def _run_task(self, spec: TaskSpec) -> None:
        if spec.task_id in self._cancelled:
            self.conn.send(("task_done", spec.task_id, [], "cancelled"))
            return
        self.rt.current_task_id = spec.task_id
        # Dispatcher-assigned chip indices (disjoint across concurrent
        # workloads; placement-group tasks get their bundle's ids)
        self.rt.current_tpu_ids = list(getattr(spec, "tpu_ids", []) or [])
        logging_mod.mark_current_task(spec.task_id)
        t0 = time.time()
        exec_span = tracing.new_span_id()
        status = "ok"
        try:
            from . import runtime_env as renv_mod  # noqa: PLC0415
            fn = self.rt.load_func(spec)
            args, kwargs = _resolve_args(self.rt, spec.args, spec.kwargs)
            # execution runs under this task's span so nested .remote()
            # submissions parent to it (cross-process trace tree)
            with renv_mod.applied(spec.runtime_env), \
                    tracing.active(getattr(spec, "trace_id", "") or "",
                                   exec_span):
                result = fn(*args, **kwargs)
                if getattr(spec, "streaming", False):
                    cancelled = self._stream_items(spec, result)
                    if cancelled:
                        status = "cancelled"
                    self.conn.send(("task_done", spec.task_id, [],
                                    "cancelled" if cancelled else None))
                    return
            sealed = self._seal_returns(spec, result)
            self.conn.send(("task_done", spec.task_id, sealed, None))
        except BaseException as e:  # noqa: BLE001
            status = "error"
            err = TaskError(repr(e), traceback.format_exc(), spec.name)
            self.conn.send(("task_done", spec.task_id, [], err))
        finally:
            self.rt.current_task_id = None
            logging_mod.mark_current_task(None)
            self._finish_task_telemetry(spec, exec_span, t0, status)

    def _create_actor(self, payload) -> None:
        acspec, ckpt = payload
        try:
            from . import runtime_env as renv_mod  # noqa: PLC0415
            # dedicated worker: the actor's runtime_env holds for its life
            renv_mod.apply_permanent(acspec.runtime_env)
            cls = serialization.loads_call(acspec.class_bytes)
            args, kwargs = _resolve_args(self.rt, acspec.args, acspec.kwargs)
            self._actor_instance = cls(*args, **kwargs)
            if ckpt is not None and hasattr(self._actor_instance,
                                            "__ray_restore__"):
                # restart of a checkpointing actor: the constructor ran
                # with the ORIGINAL args, then state resumes from the
                # last __ray_save__ snapshot instead of resetting
                self._actor_instance.__ray_restore__(
                    serialization.unpack(ckpt))
                self.rt.actor_restored = True
                events_mod.emit(
                    "actor.restore",
                    f"restored __ray_save__ checkpoint ({len(ckpt)} B)",
                    actor_id=acspec.actor_id, worker_id=self.worker_id)
            self._actor_spec = acspec
            self.rt.current_actor_id = acspec.actor_id
            self.rt.current_tpu_ids = list(
                getattr(acspec, "tpu_ids", []) or [])
            groups = getattr(acspec, "concurrency_groups", None) or {}
            if acspec.max_concurrency > 1 or groups:
                self._actor_pool = ThreadPoolExecutor(
                    max_workers=max(1, acspec.max_concurrency),
                    thread_name_prefix="actor")
            # one executor lane per named group: a slow sync method in
            # one group can never occupy another group's threads (the
            # driver already gates dispatch per-group; the lanes keep
            # the isolation inside the process too)
            self._group_pools = {
                g: ThreadPoolExecutor(max_workers=n,
                                      thread_name_prefix=f"actor-{g}")
                for g, n in groups.items()}
            self.conn.send(("actor_created", acspec.actor_id, True, None))
        except BaseException as e:  # noqa: BLE001
            err = TaskError(repr(e), traceback.format_exc(),
                            f"{acspec.class_name}.__init__")
            self.conn.send(("actor_created", acspec.actor_id, False, err))

    def _dispatch_actor_task(self, spec: TaskSpec) -> None:
        import inspect  # noqa: PLC0415
        method = getattr(self._actor_instance, spec.method_name, None)
        fn = getattr(method, "__func__", method)
        if method is not None and inspect.isasyncgenfunction(fn):
            # async streaming method: iterate on the actor's event loop
            self._ensure_async_loop()
            import asyncio  # noqa: PLC0415
            asyncio.run_coroutine_threadsafe(
                self._run_actor_task_asyncgen(spec), self._async_loop)
        elif method is not None and inspect.iscoroutinefunction(fn):
            self._ensure_async_loop()
            import asyncio  # noqa: PLC0415
            asyncio.run_coroutine_threadsafe(
                self._run_actor_task_async(spec), self._async_loop)
        else:
            pool = self._group_pools.get(
                getattr(spec, "concurrency_group", None),
                self._actor_pool)
            if pool is not None:
                pool.submit(self._run_actor_task, spec)
            else:
                self._run_actor_task(spec)

    def _put_gen_item(self, spec: TaskSpec, item) -> None:
        """Seal one streamed item and announce it to the driver (the
        single definition of the gen_item protocol — sync and async
        generator paths both go through here)."""
        from .ids import new_object_id  # noqa: PLC0415
        from .spilling import put_value_or_spill  # noqa: PLC0415
        oid = new_object_id()
        loc = put_value_or_spill(self.store, oid, item)
        self.conn.send(("gen_item", spec.task_id, oid, loc))

    def _stream_items(self, spec: TaskSpec, iterable) -> bool:
        """Put each yielded item and announce it to the driver in order
        (streaming-generator tasks, num_returns="streaming"). Returns
        True if the task was cancelled mid-stream (the generator is
        closed and no further items are emitted)."""
        for item in iterable:
            if spec.task_id in self._cancelled:
                close = getattr(iterable, "close", None)
                if close:
                    close()
                return True
            self._put_gen_item(spec, item)
        return False

    def _maybe_checkpoint(self) -> None:
        """After a completed actor call: if the actor opted into the
        checkpoint contract (defines __ray_save__), serialize its state
        and ship it to the driver for the next restart's
        __ray_restore__. Throttled by checkpoint_interval_s (actor
        option, falling back to RAY_TPU_ACTOR_CHECKPOINT_INTERVAL_S;
        0 = after every completed call). Never fails user work."""
        inst = self._actor_instance
        save = getattr(inst, "__ray_save__", None)
        if inst is None or save is None:
            return
        interval = getattr(self._actor_spec, "checkpoint_interval_s",
                           None)
        if interval is None:
            interval = float(os.environ.get(
                "RAY_TPU_ACTOR_CHECKPOINT_INTERVAL_S", "0"))
        try:
            # pack AND send under the lock: with max_concurrency > 1,
            # an older blob sent after a newer one would roll the
            # driver's retained state backwards
            with self._ckpt_lock:
                now = time.monotonic()
                if interval > 0 and now - self._last_ckpt < interval:
                    return
                blob = serialization.pack(save())
                self._last_ckpt = now
                self.conn.send(("actor_ckpt", self.rt.current_actor_id,
                                blob))
            mcat.get("ray_tpu_actor_checkpoints_total").inc()
        except Exception:
            # a failing checkpoint must not fail the call that
            # triggered it; the actor just restarts from an older one
            pass

    def _run_actor_task(self, spec: TaskSpec) -> None:
        from ..exceptions import ActorExitRequest  # noqa: PLC0415
        t0 = time.time()
        exec_span = tracing.new_span_id()
        status = "ok"
        logging_mod.mark_current_task(spec.task_id)
        try:
            method = getattr(self._actor_instance, spec.method_name)
            args, kwargs = _resolve_args(self.rt, spec.args, spec.kwargs)
            with tracing.active(getattr(spec, "trace_id", "") or "",
                                exec_span):
                result = method(*args, **kwargs)
                if getattr(spec, "streaming", False):
                    cancelled = self._stream_items(spec, result)
                    if cancelled:
                        status = "cancelled"
                    self.conn.send(("task_done", spec.task_id, [],
                                    "cancelled" if cancelled else None))
                    self._maybe_checkpoint()
                    return
            sealed = self._seal_returns(spec, result)
            self.conn.send(("task_done", spec.task_id, sealed, None))
            self._maybe_checkpoint()
        except ActorExitRequest:
            # graceful self-exit: this call returns None, then the actor
            # goes down for good (no restart)
            sealed = self._seal_returns(spec, None)
            self.conn.send(("task_done", spec.task_id, sealed, None))
            self.conn.send(("actor_exit", self.rt.current_actor_id))
            os._exit(0)  # works from threadpool threads too
        except BaseException as e:  # noqa: BLE001
            status = "error"
            err = TaskError(repr(e), traceback.format_exc(),
                            f"{type(self._actor_instance).__name__}."
                            f"{spec.method_name}")
            self.conn.send(("task_done", spec.task_id, [], err))
        finally:
            logging_mod.mark_current_task(None)
            self._finish_task_telemetry(spec, exec_span, t0, status)

    async def _run_actor_task_asyncgen(self, spec: TaskSpec) -> None:
        """Streaming from an `async def ... yield` actor method. Requires
        num_returns=\"streaming\" on the call (enforced below — a plain
        call would otherwise try to seal an async_generator object)."""
        from ..exceptions import ActorExitRequest  # noqa: PLC0415
        t0 = time.time()
        exec_span = tracing.new_span_id()
        status = "ok"
        try:
            method = getattr(self._actor_instance, spec.method_name)
            args, kwargs = _resolve_args(self.rt, spec.args, spec.kwargs)
            agen = method(*args, **kwargs)
            if not getattr(spec, "streaming", False):
                raise TypeError(
                    f"{spec.method_name} is an async generator; call it "
                    "with num_returns=\"streaming\"")
            cancelled = False
            async for item in agen:
                if spec.task_id in self._cancelled:
                    cancelled = True
                    await agen.aclose()
                    break
                self._put_gen_item(spec, item)
            if cancelled:
                status = "cancelled"
            self.conn.send(("task_done", spec.task_id, [],
                            "cancelled" if cancelled else None))
            self._maybe_checkpoint()
        except ActorExitRequest:
            self.conn.send(("task_done", spec.task_id, [], None))
            self.conn.send(("actor_exit", self.rt.current_actor_id))
            os._exit(0)
        except BaseException as e:  # noqa: BLE001
            status = "error"
            err = TaskError(repr(e), traceback.format_exc(),
                            f"asyncgen.{spec.method_name}")
            self.conn.send(("task_done", spec.task_id, [], err))
        finally:
            # no tracing.active here: interleaved coroutines share the
            # loop thread, so a thread-local context would leak between
            # requests — the span record alone keeps the timeline link
            self._finish_task_telemetry(spec, exec_span, t0, status)

    async def _run_actor_task_async(self, spec: TaskSpec) -> None:
        from ..exceptions import ActorExitRequest  # noqa: PLC0415
        t0 = time.time()
        exec_span = tracing.new_span_id()
        status = "ok"
        try:
            method = getattr(self._actor_instance, spec.method_name)
            args, kwargs = _resolve_args(self.rt, spec.args, spec.kwargs)
            result = await method(*args, **kwargs)
            sealed = self._seal_returns(spec, result)
            self.conn.send(("task_done", spec.task_id, sealed, None))
            self._maybe_checkpoint()
        except ActorExitRequest:
            sealed = self._seal_returns(spec, None)
            self.conn.send(("task_done", spec.task_id, sealed, None))
            self.conn.send(("actor_exit", self.rt.current_actor_id))
            os._exit(0)
        except BaseException as e:  # noqa: BLE001
            status = "error"
            err = TaskError(repr(e), traceback.format_exc(),
                            f"async.{spec.method_name}")
            self.conn.send(("task_done", spec.task_id, [], err))
        finally:
            self._finish_task_telemetry(spec, exec_span, t0, status)

    def _ensure_async_loop(self):
        if self._async_loop is None:
            import asyncio  # noqa: PLC0415
            self._async_loop = asyncio.new_event_loop()
            t = threading.Thread(target=self._async_loop.run_forever,
                                 daemon=True, name="actor-asyncio")
            t.start()


def main() -> None:
    socket_path, worker_id = sys.argv[1], sys.argv[2]
    log_dir = os.environ.get("RAY_TPU_LOG_DIR")
    if log_dir:
        from .logging import redirect_process_output  # noqa: PLC0415
        redirect_process_output(
            os.path.join(log_dir, f"worker-{worker_id}.log"))
    try:
        loop = WorkerLoop(socket_path, worker_id)
    except (ConnectionRefusedError, FileNotFoundError):
        # Driver died between spawning us and our connect: exit quietly.
        sys.exit(0)
    loop.run()


if __name__ == "__main__":
    main()
