"""Worker process: executes tasks and hosts actors.

Reference parity: src/ray/core_worker/core_worker.cc (task execution,
arg resolution, return-object sealing) + python/ray/_private/worker.py
(the Python worker loop). One OS process per worker; a reader thread
demultiplexes driver messages into an execution queue and reply slots, so
user code can block in `get()` while new messages keep flowing.

Run as: python -m ray_tpu.core.worker <socket_path> <worker_id>
"""
from __future__ import annotations

import collections
import os
import queue
import sys
import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional

from . import logging as logging_mod
from . import scheduling as sched_mod
from . import serialization
from .ids import new_object_id
from .object_ref import ObjectRef
from .object_store import ShmStore, ObjectLocation, INLINE_MAX, make_store
from .protocol import Connection, ConnectionClosed, connect_address
from .task import TaskSpec, ActorCreationSpec
from ..exceptions import (ActorDiedError, TaskError, GetTimeoutError,
                          ObjectLostError)
from ..util import events as events_mod
from ..util import metrics as metrics_mod
from ..util import knobs
from ..util import metrics_catalog as mcat
from ..util import tracing
from ..util import waits as waits_mod


class _MsgBatcher:
    """Coalesces worker->driver control messages (task_done / put /
    gen_item / submit) into ("batch", [...]) frames under a size + time
    flush window, so a map-style fan-out (or a storm of sub-millisecond
    completions) costs one frame per batch instead of one per message.
    Send order is preserved across message kinds — dependent verbs
    (get_request after a buffered put) flush first. urgent=True flushes
    synchronously: the task queue drained, or a verb the driver must
    see NOW (actor_exit's final result) depends on the message."""

    def __init__(self, conn: Connection, max_n: int = 64,
                 window: float = 0.001, enabled: bool = True):
        self.conn = conn
        self.max_n = max_n
        self.window = window
        self.enabled = enabled and max_n > 1
        self._buf: List[tuple] = []
        self._lock = threading.Lock()
        # serializes swap+send so flush() only returns once every
        # message buffered BEFORE the call is on the socket — the
        # ordering fences (actor_exit / kill / get after buffered put)
        # rely on that, and a bare buffer-swap in the loop thread would
        # let flush() return with the frame still unsent
        self._send_lock = threading.Lock()
        self._event = threading.Event()
        if self.enabled:
            threading.Thread(target=self._loop, daemon=True,
                             name="worker-msg-flush").start()

    def send(self, msg: tuple, urgent: bool = False) -> None:
        if not self.enabled:
            self.conn.send(msg)
            return
        with self._lock:
            self._buf.append(msg)
            n = len(self._buf)
        if urgent or n >= self.max_n:
            self.flush()
        else:
            self._event.set()

    def flush(self) -> None:
        with self._send_lock:
            with self._lock:
                if not self._buf:
                    return
                buf, self._buf = self._buf, []
            if len(buf) == 1:
                # raylint: disable=RT001 deliberate: swap+send serialize under
                # the send lock so the flush ordering fence holds (PR 8,
                # SCHEDULING.md); the one re-entry path (_publish_direct)
                # bypasses the batcher and sends straight on the Connection
                self.conn.send(buf[0])
            else:
                # raylint: disable=RT001 deliberate: same ordering fence as the
                # single-message branch above
                self.conn.send(("batch", buf))

    def _loop(self) -> None:
        while True:
            if not self._event.wait(timeout=0.5):
                continue
            self._event.clear()
            if self.window > 0:
                time.sleep(self.window)
            try:
                self.flush()
            except Exception:
                pass   # ConnectionClosed: read loop handles the death


class _DirectFuture:
    """Local future for one driver-bypass actor call (the caller owns
    the result; the driver never hears about the task). `failover`
    flips when the channel died and the spec was resubmitted through
    the driver — the oid then resolves via the normal get path."""
    __slots__ = ("ev", "payload", "error", "failover", "publish",
                 "_published", "actor_id")

    def __init__(self):
        self.ev = threading.Event()
        self.payload: Optional[bytes] = None   # serialization.pack(...)
        self.error: Optional[BaseException] = None
        self.failover = False
        self.actor_id: Optional[str] = None    # callee (wait-graph edge)
        # an escaped ref (serialized out of this process) must seal the
        # value driver-side so any reader anywhere can resolve it
        self.publish = False
        self._published = False


class _DirectChannel:
    """Caller side of one worker->worker direct-call connection
    (resolved once via the sys.actor_addr directory, then every call
    rides this socket with zero driver messages)."""

    def __init__(self, rt: "WorkerRuntime", actor_id: str,
                 worker_id: str, addr: str):
        self.rt = rt
        self.actor_id = actor_id
        self.worker_id = worker_id
        self.conn = connect_address(addr, timeout=5.0)
        self.dead = False
        self._lock = threading.Lock()
        self._rid = 0
        self._pending: Dict[int, tuple] = {}   # rid -> (spec, future)
        threading.Thread(target=self._read_loop, daemon=True,
                         name=f"dcall-{actor_id[-8:]}").start()

    def call(self, spec: TaskSpec, fut: _DirectFuture) -> bool:
        with self._lock:
            if self.dead:
                return False
            self._rid += 1
            rid = self._rid
            self._pending[rid] = (spec, fut)
        try:
            self.conn.send(("dcall", rid, spec))
        except ConnectionClosed as e:
            with self._lock:
                self._pending.pop(rid, None)
            self._fail(f"send failed: {e}")
            return False
        return True

    def _read_loop(self) -> None:
        while True:
            try:
                # raylint: disable=RT003 daemon reader; in-flight calls
                # settle via driver-path failover once the callee's
                # death is determined (SCHEDULING.md), so a half-open
                # channel parks only this thread, never a caller
                m = self.conn.recv()
            except ConnectionClosed as e:
                self._fail(f"connection lost: {e}")
                return
            if m[0] == "dresult":
                _, rid, ok, payload = m
                with self._lock:
                    entry = self._pending.pop(rid, None)
                if entry is None:
                    continue
                _spec, fut = entry
                if ok:
                    fut.payload = payload
                else:
                    fut.error = payload if isinstance(
                        payload, BaseException) else TaskError(str(payload))
                self.rt._direct_resolved(fut)
            elif m[0] == "dreject":
                # stale address (the actor moved / died): every pending
                # call fails over through the driver and the channel is
                # retired — the next call re-resolves the directory
                self._fail("callee rejected (stale address)")
                return

    def _fail(self, reason: str = "") -> None:
        with self._lock:
            if self.dead:
                return
            self.dead = True
            pending, self._pending = self._pending, {}
        if pending or reason:
            sys.stderr.write(
                f"[ray_tpu worker] direct channel to actor "
                f"{self.actor_id} failed ({reason}); "
                f"{len(pending)} in-flight calls fail over to the "
                f"driver path\n")
        self.rt._drop_direct_channel(self.actor_id, self)
        try:
            self.conn.close()
        except Exception:
            pass
        # in-flight direct calls FAIL OVER to the driver path: the
        # driver then applies its normal actor semantics (queue behind
        # a restart, or ActorDiedError with the death cause)
        for _rid, (spec, fut) in pending.items():
            fut.failover = True
            try:
                self.rt._batch.send(("submit", spec), urgent=True)
            except Exception:
                fut.failover = False
                fut.error = ActorDiedError(
                    f"direct call to actor {self.actor_id} lost its "
                    f"channel and the driver connection is gone")
            self.rt._direct_resolved(fut)


# How long a get() on an agent-placed result stays silent before telling
# the driver this worker is blocked (dwait CPU lend). Longer than the
# direct-call grace: short fan-outs must finish with ZERO driver frames
# (the two-level scheduling steady-state property), while anything slower
# still lends its CPU so capacity-tight gangs keep their liveness.
_AGENT_GRACE_S = 0.2


class _AgentFuture:
    """Local future for one task this worker submitted to its NODE AGENT
    (two-level scheduling, docs/SCHEDULING.md — the driver never hears
    about it). Resolves to a host-kind seal location in the node's
    shared arena. `failover` flips when the result must resolve through
    the driver instead: the agent forwarded the spec upward, or the
    agent plane died and the spec was resubmitted."""
    __slots__ = ("ev", "loc", "error", "failover", "publish",
                 "_published", "spec")

    def __init__(self, spec: TaskSpec):
        self.ev = threading.Event()
        self.loc = None                        # sealed ObjectLocation
        self.error: Optional[BaseException] = None
        self.failover = False
        self.publish = False
        self._published = False
        self.spec = spec                       # retained for failover


class _AgentPlane:
    """Worker side of the node agent's local dispatch plane (two-level
    scheduling, docs/SCHEDULING.md). One unix-socket connection to the
    agent that spawned this worker: the agent pushes bulk-lease tasks
    down (`aexec`) and this worker's own fan-outs go up (`asubmit`) for
    node-local placement — zero driver messages steady-state. On plane
    death every unresolved submission fails over to the driver path."""

    def __init__(self, loop: "WorkerLoop", addr: str):
        self.loop = loop
        self.rt = loop.rt
        self.conn = connect_address(addr)
        self.dead = False
        # completions coalesce like the worker->driver batcher does:
        # a pipelined backlog of sub-millisecond tasks acks in one
        # frame per window instead of one per task. urgent=True
        # flushes in order, so routing every verb through the batcher
        # keeps adone/asubmit ordering intact.
        self._batch = _MsgBatcher(
            self.conn,
            max_n=knobs.get_int("RAY_TPU_BATCH_FLUSH_N"),
            window=knobs.get_float("RAY_TPU_BATCH_FLUSH_S"),
            enabled=knobs.get_bool("RAY_TPU_BATCH"))
        self._batch.send(("aregister", loop.worker_id), urgent=True)
        threading.Thread(target=self._read_loop, daemon=True,
                         name="agent-plane").start()

    def _read_loop(self) -> None:
        rt = self.rt
        while True:
            try:
                # raylint: disable=RT003 node-local peer: agent death
                # closes the socket, and _fail() fails every unresolved
                # future over to the driver path
                m = self.conn.recv()
            except (ConnectionClosed, OSError):
                self._fail()
                return
            k = m[0]
            if k == "aexec":
                # one frame carries the worker's whole refill batch
                for spec, dep_locs, host_seal in m[1]:
                    spec._via_agent = True
                    spec._host_seal = bool(host_seal)
                    if dep_locs:
                        # pre-resolved dependency locations (node-local
                        # results): arg resolution reads them straight
                        # from the shared arena, no driver get_request
                        rt._agent_locs_update(dep_locs)
                    self.loop._task_q.put(("task", spec))
            elif k == "aresult":
                rt._agent_resolve(m[1], m[2], m[3])
            elif k == "aspill":
                rt._agent_spilled(m[1])

    def submit(self, spec: TaskSpec) -> List[ObjectRef]:
        rt = self.rt
        with rt._agent_lock:
            for oid in spec.return_ids:
                rt._register_agent_future(oid, _AgentFuture(spec))
            rt._agent_tasks[spec.task_id] = list(spec.return_ids)
        try:
            # urgent: the child's placement latency is on the parent's
            # critical path, and the ordered flush pushes any buffered
            # adone (a dep the child needs recorded) out first
            self._batch.send(("asubmit", [spec]), urgent=True)
        except (ConnectionClosed, OSError):
            self._fail()   # flips these futures to driver resubmission
        return [ObjectRef(oid) for oid in spec.return_ids]

    def task_done(self, tid: str, sealed, error) -> bool:
        """Route one agent-dispatched completion back to the agent.
        False when the plane is dead — the caller falls back to the
        driver connection so the result is not lost."""
        if self.dead:
            return False
        try:
            # flush NOW only when the local backlog drained — the
            # agent is waiting to refill; mid-backlog acks coalesce
            self._batch.send(("adone", tid, sealed, error),
                             urgent=self.loop._task_q.empty())
            return True
        except (ConnectionClosed, OSError):
            self._fail()
            return False

    def _fail(self) -> None:
        """Agent plane died: resubmit every unresolved agent-placed
        spec through the driver (at-least-once, like a direct-call
        channel death) and flip its futures to driver-path resolution."""
        rt = self.rt
        with rt._agent_lock:
            if self.dead:
                return
            self.dead = True
            pending = []
            for tid, oids in rt._agent_tasks.items():
                for oid in oids:
                    f = rt._agent_results.get(oid)
                    if f is not None and not f.ev.is_set():
                        pending.append((tid, oids))
                        break
        if pending:
            sys.stderr.write(
                f"[ray_tpu worker] agent dispatch plane lost; "
                f"{len(pending)} in-flight nested tasks fail over to "
                f"the driver path\n")
        for _tid, oids in pending:
            spec = None
            for oid in oids:
                f = rt._agent_results.get(oid)
                if f is not None and not f.ev.is_set():
                    f.failover = True
                    spec = spec or f.spec
            if spec is not None:
                try:
                    rt._batch.send(("submit", spec), urgent=True)
                except Exception:
                    err = TaskError(
                        "agent plane and driver connection both lost",
                        "", spec.name)
                    for oid in oids:
                        f = rt._agent_results.get(oid)
                        if f is not None and not f.ev.is_set():
                            f.failover = False
                            f.error = err
            for oid in oids:
                f = rt._agent_results.get(oid)
                if f is not None:
                    f.ev.set()
        with rt._direct_cv:
            rt._direct_cv.notify_all()


class WorkerRuntime:
    """The runtime visible to user code running inside this worker.

    Implements the same verbs as the driver runtime so `ray_tpu.get/put/
    remote` work transparently in nested tasks.
    """

    is_driver = False

    # resolved direct-call results retained past this bound evict
    # oldest-first (their refs were never re-read); a late get of an
    # evicted one raises ObjectLostError naming the bound
    _DIRECT_RESULT_RETAIN = 8192

    def __init__(self, conn: Connection, worker_id: str, store: ShmStore):
        self.conn = conn
        self.worker_id = worker_id
        self.store = store
        self._replies: Dict[str, queue.Queue] = {}
        self._replies_lock = threading.Lock()
        # (rid, oid) -> bytearray for cross-node values streamed in
        # chunks ahead of the final get_reply (same socket => in order)
        self._value_chunks: Dict[tuple, bytearray] = {}
        self._req_counter = 0
        self._func_cache: Dict[str, Any] = {}
        self.current_task_id: Optional[str] = None
        self.current_actor_id: Optional[str] = None
        self.current_tpu_ids: list = []
        # this worker's actor began life via __ray_restore__ (surfaced
        # as RuntimeContext.was_current_actor_reconstructed)
        self.actor_restored = False
        self.job_id = knobs.get_str("RAY_TPU_JOB_ID")
        # outbound control-message batcher (WorkerLoop swaps in the
        # real one before the first task runs); the default passthrough
        # keeps early sends working
        self._batch = _MsgBatcher(conn, enabled=False)
        # WorkerLoop points this at its span buffer so fast-path
        # instrumentation (direct-call submits, DAG stages) can record
        # spans that ride the telemetry heartbeat — never the control
        # plane
        self._span_sink = None
        # ---- driver-bypass actor calls (docs/SCHEDULING.md) ----
        self._direct_enabled = knobs.get_bool("RAY_TPU_DIRECT_CALLS")
        self._direct_lock = threading.Lock()
        self._direct_chans: Dict[str, _DirectChannel] = {}
        self._direct_retry_after: Dict[str, float] = {}
        # oid -> _DirectFuture for calls this process fired direct;
        # insertion-ordered so resolution-retention can evict oldest
        self._direct_results: "collections.OrderedDict[str, _DirectFuture]" \
            = collections.OrderedDict()
        self._direct_evicted: set = set()
        self._direct_cv = threading.Condition()
        # threads inside force_driver_path() route actor calls through
        # the driver (rendezvous/polling patterns whose LIVENESS depends
        # on the scheduler seeing their blocking verbs — the driver path
        # lends the worker's CPU while it waits; util/collective.py)
        self._no_direct = threading.local()
        self.direct_calls = 0
        self.direct_fallbacks = 0
        # ---- agent-local dispatch (two-level scheduling) ----
        # set by WorkerLoop when a node agent spawned this worker
        self._agent_plane: Optional[_AgentPlane] = None
        self._agent_lock = threading.Lock()
        # oid -> _AgentFuture for fan-out tasks routed to the node agent
        self._agent_results: "collections.OrderedDict[str, _AgentFuture]" \
            = collections.OrderedDict()
        # task_id -> its return oids (error fan-in, failover resubmit)
        self._agent_tasks: "collections.OrderedDict[str, list]" \
            = collections.OrderedDict()
        self._agent_evicted: set = set()
        # oids known node-resolvable (agent-placed results, agent-stamped
        # dep locations): a fan-out whose ref args all live here may
        # route to the agent without a cross-connection ordering hazard
        # (the driver may not know these oids at all)
        self._agent_known: set = set()
        # oid -> host-kind location the agent stamped at dispatch
        self._agent_locs: dict = {}

    def force_driver_path(self):
        """Context manager: actor calls from this thread take the
        driver dispatch path even when a direct channel exists."""
        import contextlib  # noqa: PLC0415
        rt = self

        @contextlib.contextmanager
        def cm():
            prev = getattr(rt._no_direct, "on", False)
            rt._no_direct.on = True
            try:
                yield
            finally:
                rt._no_direct.on = prev
        return cm()

    # ---- request/reply over the driver connection -------------------------
    def _new_req(self) -> str:
        with self._replies_lock:
            self._req_counter += 1
            rid = f"{self.worker_id}:{self._req_counter}"
            q: queue.Queue = queue.Queue(maxsize=1)
            self._replies[rid] = q
        return rid

    def _take_reply(self, rid: str, timeout: Optional[float]) -> Any:
        q = self._replies[rid]
        try:
            return q.get(timeout=timeout)
        except queue.Empty:
            raise GetTimeoutError(f"request {rid} timed out") from None
        finally:
            with self._replies_lock:
                self._replies.pop(rid, None)

    def stash_value_chunk(self, rid: str, oid: str, off: int,
                          total: int, chunk: bytes) -> None:
        buf = self._value_chunks.get((rid, oid))
        if buf is None:
            buf = self._value_chunks[(rid, oid)] = bytearray(total)
        buf[off:off + len(chunk)] = chunk

    def take_staged_value(self, rid: str, oid: str) -> bytes:
        return bytes(self._value_chunks.pop((rid, oid)))

    def deliver_reply(self, rid: str, payload: Any) -> None:
        with self._replies_lock:
            q = self._replies.get(rid)
        if q is not None:
            q.put(payload)

    # ---- direct actor calls ----------------------------------------------
    def _direct_resolved(self, fut: _DirectFuture) -> None:
        """Channel-reader-side resolution: wake waiters and run the
        escape publication if this result's ref left the process."""
        fut.ev.set()
        with self._direct_cv:
            self._direct_cv.notify_all()
        if fut.publish and not fut.failover:
            for oid, f in list(self._direct_results.items()):
                if f is fut:
                    self._publish_direct(oid, fut)
                    break

    def _publish_direct(self, oid: str, fut: _DirectFuture) -> None:
        """Seal a direct-call result into the driver's object table: its
        ref escaped this process (was serialized into a spec / put /
        return value), so readers anywhere must be able to resolve it."""
        if fut._published or fut.failover:
            return
        fut._published = True
        try:
            # straight to the socket, NOT through the batcher: this can
            # run from inside a batch flush (ObjectRef.__reduce__ fires
            # while the flush pickles a buffered spec, under the
            # batcher's non-reentrant send lock — an urgent batched send
            # here would self-deadlock). Connection.send encodes outside
            # its socket lock, so the nested frame is safe and lands
            # BEFORE the spec that references the oid.
            if fut.error is not None:
                self.conn.send(("put_error", oid, fut.error))
            else:
                loc = self.store.put_packed(oid, fut.payload)
                self.conn.send(("put", oid, loc))
        except Exception:
            pass   # driver gone: nothing to publish to

    def on_ref_serialized(self, oid: str) -> None:
        """ObjectRef.__reduce__ hook: a ref leaving this process by
        serialization may reach readers that resolve through the
        driver — publish direct-call and agent-placed results so they
        can."""
        fut = self._direct_results.get(oid)
        if fut is not None and not fut.publish and not fut.failover:
            fut.publish = True
            if fut.ev.is_set():
                self._publish_direct(oid, fut)
            return
        af = self._agent_results.get(oid)
        if af is not None and not af.publish and not af.failover:
            af.publish = True
            if af.ev.is_set():
                self._publish_agent(oid, af)

    def _register_direct_future(self, oid: str, fut: _DirectFuture) -> None:
        self._direct_results[oid] = fut
        while len(self._direct_results) > self._DIRECT_RESULT_RETAIN:
            old_oid, old = next(iter(self._direct_results.items()))
            if not old.ev.is_set():
                break   # oldest still in flight: don't evict live calls
            del self._direct_results[old_oid]
            if old._published or old.failover:
                # the value lives driver-side (escaped-ref publication /
                # failover resubmit): later local gets resolve it over
                # the normal driver path — only a never-published local
                # result is actually lost
                continue
            self._direct_evicted.add(old_oid)
            while len(self._direct_evicted) > 4 * self._DIRECT_RESULT_RETAIN:
                self._direct_evicted.pop()

    # ---- agent-local dispatch (two-level scheduling) ----------------------
    def _register_agent_future(self, oid: str, fut: _AgentFuture) -> None:
        """Caller holds _agent_lock. Same oldest-first resolution
        retention as direct-call results; an evicted never-published
        result raises ObjectLostError on a late get."""
        self._agent_results[oid] = fut
        while len(self._agent_results) > self._DIRECT_RESULT_RETAIN:
            old_oid, old = next(iter(self._agent_results.items()))
            if not old.ev.is_set():
                break   # oldest still in flight: don't evict live tasks
            del self._agent_results[old_oid]
            self._agent_known.discard(old_oid)
            if old._published or old.failover:
                continue   # resolvable through the driver path
            self._agent_evicted.add(old_oid)
            while len(self._agent_evicted) > 4 * self._DIRECT_RESULT_RETAIN:
                self._agent_evicted.pop()
        while len(self._agent_tasks) > self._DIRECT_RESULT_RETAIN:
            old_tid, oids = next(iter(self._agent_tasks.items()))
            if any((f := self._agent_results.get(o)) is not None
                   and not f.ev.is_set() for o in oids):
                break
            del self._agent_tasks[old_tid]

    def _agent_locs_update(self, pairs) -> None:
        locs = self._agent_locs
        for oid, loc in pairs:
            locs[oid] = loc
            self._agent_known.add(oid)
        while len(locs) > 8192:
            # values still live in the node arena; a later get falls
            # back to the driver path
            del locs[next(iter(locs))]
        while len(self._agent_known) > 8 * 8192:
            self._agent_known.pop()

    def _agent_resolve(self, tid: str, sealed, error) -> None:
        """Agent-plane reader: one nested task this worker submitted
        completed on a sibling worker."""
        with self._agent_lock:
            oids = list(self._agent_tasks.get(tid, ()))
        err = None
        if error is not None:
            err = error if isinstance(error, BaseException) \
                else TaskError(str(error), "", tid)
        locs = dict(sealed or ())
        to_publish = []
        for oid in oids:
            fut = self._agent_results.get(oid)
            if fut is None or fut.ev.is_set():
                continue
            if err is not None:
                fut.error = err
            else:
                fut.loc = locs.get(oid)
                if fut.loc is None:
                    fut.error = TaskError(
                        f"agent-placed task sealed no location for {oid}",
                        "", tid)
                else:
                    self._agent_known.add(oid)
            fut.ev.set()
            if fut.publish:
                to_publish.append((oid, fut))
        with self._direct_cv:
            self._direct_cv.notify_all()
        for oid, fut in to_publish:
            self._publish_agent(oid, fut)

    def _agent_spilled(self, tids) -> None:
        """The agent forwarded these worker-submitted specs to the
        driver (deps not node-local, or no capacity in time): their
        results resolve through the driver path. No resubmit here —
        the agent already handed the spec up."""
        for tid in tids:
            for oid in self._agent_tasks.get(tid, ()):
                fut = self._agent_results.get(oid)
                if fut is not None and not fut.ev.is_set():
                    fut.failover = True
                    fut.ev.set()
        with self._direct_cv:
            self._direct_cv.notify_all()

    def _publish_agent(self, oid: str, fut: _AgentFuture) -> None:
        """Escape publication for an agent-placed result: its ref left
        this process, so readers that resolve through the driver must
        find it. The seal is host-kind (node arena / spill file), so
        the location itself is globally resolvable — no byte copy."""
        if fut._published or fut.failover:
            return
        fut._published = True
        try:
            # straight to the socket, NOT through the batcher — same
            # re-entrancy rule as _publish_direct
            if fut.error is not None:
                self.conn.send(("put_error", oid, fut.error))
            else:
                self.conn.send(("put", oid, fut.loc))
        except Exception:
            pass   # driver gone: nothing to publish to

    def _resolve_agent(self, oid: str, fut: _AgentFuture,
                       deadline: Optional[float]) -> Any:
        if not fut.ev.is_set():
            # silent grace first (the zero-driver-frame steady state),
            # then the same dwait CPU lend a blocked driver-path get
            # performs — capacity-tight gangs rely on it for liveness
            grace = _AGENT_GRACE_S if deadline is None \
                else max(0.0, min(_AGENT_GRACE_S,
                                  deadline - time.monotonic()))
            if not fut.ev.wait(grace):
                notified = False
                try:
                    self.conn.send(("dwait", True))
                    notified = True
                except Exception:
                    pass
                tok = waits_mod.park("object", oid, via="agent")
                try:
                    remaining = None if deadline is None \
                        else max(0.0, deadline - time.monotonic())
                    ok = fut.ev.wait(remaining)
                finally:
                    waits_mod.unpark(tok)
                    if notified:
                        try:
                            self.conn.send(("dwait", False))
                        except Exception:
                            pass
                if not ok:
                    raise GetTimeoutError(
                        f"get() timed out waiting for agent-placed "
                        f"task result {oid}")
        if fut.failover:
            remaining = None if deadline is None \
                else max(0.1, deadline - time.monotonic())
            return self._get_one_fresh(oid, remaining)
        if fut.error is not None:
            raise fut.error
        try:
            return self.store.get_value(fut.loc)
        except ObjectLostError:
            remaining = None if deadline is None \
                else max(0.1, deadline - time.monotonic())
            return self._get_one_fresh(oid, remaining)

    def _drop_direct_channel(self, actor_id: str,
                             ch: _DirectChannel) -> None:
        with self._direct_lock:
            if self._direct_chans.get(actor_id) is ch:
                del self._direct_chans[actor_id]

    def _direct_channel(self, actor_id: str) -> Optional[_DirectChannel]:
        with self._direct_lock:
            ch = self._direct_chans.get(actor_id)
            if ch is not None and not ch.dead:
                return ch
        if self._direct_retry_after.get(actor_id, 0) > time.monotonic():
            return None
        try:
            info = self.report_sync("sys.actor_addr", actor_id,
                                    timeout=10.0)
        except Exception:
            info = None
        if info == "pending":
            # callee still constructing (or restarting): this call falls
            # back, and the NEXT call retries the directory immediately.
            # No timed backoff here — driver-path calls run in ~1ms, so
            # even a 50ms pause let entire short bursts complete before
            # the channel ever got a chance to establish; one extra
            # report_sync per call, bounded by construction time, is
            # cheaper than condemning the burst to the fallback path.
            return None
        if not info:
            self._direct_retry_after[actor_id] = time.monotonic() + 1.0
            return None
        callee_wid, addr, _epoch = info
        try:
            ch = _DirectChannel(self, actor_id, callee_wid, addr)
        except Exception:
            self._direct_retry_after[actor_id] = time.monotonic() + 1.0
            return None
        with self._direct_lock:
            live = self._direct_chans.get(actor_id)
            if live is not None and not live.dead:
                try:
                    ch.conn.close()
                except Exception:
                    pass
                return live
            self._direct_chans[actor_id] = ch
        events_mod.emit(
            "task.dispatch.local",
            f"direct call channel to actor {actor_id} "
            f"(worker {callee_wid}) established; steady-state calls "
            f"bypass the driver",
            actor_id=actor_id, worker_id=self.worker_id)
        return ch

    def _try_direct_call(self, spec: TaskSpec) -> bool:
        ch = self._direct_channel(spec.actor_id)
        if ch is None:
            self.direct_fallbacks += 1
            try:
                mcat.get("ray_tpu_direct_call_fallbacks_total").inc(
                    tags={"reason": "no_address"})
            except Exception:
                pass
            return False
        oid = spec.return_ids[0]
        fut = _DirectFuture()
        fut.actor_id = spec.actor_id
        self._register_direct_future(oid, fut)
        if not ch.call(spec, fut):
            self._direct_results.pop(oid, None)
            self.direct_fallbacks += 1
            try:
                mcat.get("ray_tpu_direct_call_fallbacks_total").inc(
                    tags={"reason": "channel_died"})
            except Exception:
                pass
            return False
        self.direct_calls += 1
        try:
            mcat.get("ray_tpu_direct_actor_calls_total").inc()
        except Exception:
            pass
        # flight recorder: the SUBMIT span of a driver-bypass call is
        # recorded by the CALLER (the driver never sees the task); the
        # callee's exec span parents to spec.span_id as usual, so the
        # timeline stays a single tree with zero driver hops
        if self._span_sink is not None \
                and knobs.get_bool("RAY_TPU_FASTPATH_SPANS"):
            try:
                now = time.time()
                self._span_sink({
                    "trace_id": getattr(spec, "trace_id", "") or "",
                    "span_id": getattr(spec, "span_id", "") or "",
                    "parent_span_id":
                        getattr(spec, "parent_span_id", "") or "",
                    "task_id": spec.task_id,
                    "name": f"dcall:{spec.method_name}",
                    "cat": "dcall_submit",
                    "start": now, "end": now, "status": "ok",
                    "pid": os.getpid(), "worker_id": self.worker_id,
                    "node_id": knobs.get_raw("RAY_TPU_NODE_ID"),
                })
            except Exception:
                pass
        return True

    # ---- core verbs -------------------------------------------------------
    def get(self, refs: List[ObjectRef], timeout: Optional[float] = None) -> List[Any]:
        oids = [r.id for r in refs]
        deadline = None if timeout is None else time.monotonic() + timeout
        # device-resident fast path: objects THIS worker produced are
        # served from the in-process table — no driver round-trip, no
        # D2H, no deserialization (core/device_store.py)
        from . import device_store  # noqa: PLC0415
        local = {}
        direct: Dict[str, _DirectFuture] = {}
        agent: Dict[str, _AgentFuture] = {}
        for oid in oids:
            try:
                local[oid] = device_store.get(oid)
                continue
            except KeyError:
                pass
            fut = self._direct_results.get(oid)
            if fut is not None:
                direct[oid] = fut
                continue
            afut = self._agent_results.get(oid)
            if afut is not None:
                agent[oid] = afut
                continue
            aloc = self._agent_locs.get(oid)
            if aloc is not None:
                # agent-stamped dependency location: the value is in
                # this node's arena, no driver round-trip
                try:
                    local[oid] = self.store.get_value(aloc)
                    continue
                except Exception:
                    self._agent_locs.pop(oid, None)
            if oid in self._direct_evicted or oid in self._agent_evicted:
                raise ObjectLostError(
                    f"locally-owned result {oid} was evicted (held past "
                    f"the {self._DIRECT_RESULT_RETAIN}-entry retention "
                    f"bound without being read)")
        if len(local) == len(oids):
            return [local[oid] for oid in oids]
        remote_oids = [oid for oid in oids
                       if oid not in local and oid not in direct
                       and oid not in agent]
        results: Dict[str, tuple] = {}
        rid = None
        if remote_oids:
            self._batch.flush()   # a buffered put/submit may feed this
            rid = self._new_req()
            self.conn.send(("get_request", rid, remote_oids, timeout))
            tok = waits_mod.park("object", remote_oids[0],
                                 n=len(remote_oids))
            try:
                results = self._take_reply(rid, timeout)
            finally:
                waits_mod.unpark(tok)
        out = []
        for oid in oids:
            if oid in local:
                out.append(local[oid])
                continue
            if oid in direct:
                out.append(self._resolve_direct(oid, direct[oid],
                                                deadline))
                continue
            if oid in agent:
                out.append(self._resolve_agent(oid, agent[oid],
                                               deadline))
                continue
            kind, payload = results[oid]
            if kind == "error":
                raise payload if isinstance(payload, BaseException) else TaskError(str(payload))
            if kind == "value":
                # cross-node object: the driver shipped the packed bytes
                # (its node fetched them from the holder's store)
                out.append(serialization.unpack(payload))
            elif kind == "value_staged":
                # big cross-node object: bytes arrived ahead of the reply
                # as value_chunk frames
                out.append(serialization.unpack(
                    self.take_staged_value(rid, oid)))
            else:
                try:
                    out.append(self.store.get_value(payload))
                except ObjectLostError:
                    # The spiller (or arena LRU) dropped the segment after
                    # this loc was serialized but before we read it; a
                    # fresh request returns a spill-aware loc (or the
                    # re-hosted bytes). One retry closes the race.
                    out.append(self._get_one_fresh(oid, timeout))
        return out

    def _resolve_direct(self, oid: str, fut: _DirectFuture,
                        deadline: Optional[float]) -> Any:
        if not fut.ev.is_set():
            # short grace first: a round-trip-fast direct reply must not
            # cost driver messages (the zero-message property). Past it,
            # tell the driver we are BLOCKED so it lends this worker's
            # CPU and reclaims leased slots — exactly what a driver-path
            # get_request would have triggered (capacity-tight gang
            # workloads rely on that lend for liveness).
            grace = 0.005 if deadline is None \
                else max(0.0, min(0.005, deadline - time.monotonic()))
            if not fut.ev.wait(grace):
                notified = False
                try:
                    self.conn.send(("dwait", True))
                    notified = True
                except Exception:
                    pass
                # the target actor rides the record so the wait graph
                # can close cycles through calls the driver never saw
                tok = waits_mod.park("actor-call", oid,
                                     target_actor=fut.actor_id)
                try:
                    remaining = None if deadline is None \
                        else max(0.0, deadline - time.monotonic())
                    ok = fut.ev.wait(remaining)
                finally:
                    waits_mod.unpark(tok)
                    if notified:
                        try:
                            self.conn.send(("dwait", False))
                        except Exception:
                            pass
                if not ok:
                    raise GetTimeoutError(
                        f"get() timed out waiting for direct call "
                        f"result {oid}")
        if fut.failover:
            # the channel died mid-call and the spec was resubmitted
            # through the driver: resolve the oid the normal way
            remaining = None if deadline is None \
                else max(0.1, deadline - time.monotonic())
            return self._get_one_fresh(oid, remaining)
        if fut.error is not None:
            raise fut.error
        return serialization.unpack(fut.payload)

    def _get_one_fresh(self, oid: str, timeout: Optional[float],
                       _retried: bool = False) -> Any:
        t0 = time.monotonic()
        rid = self._new_req()
        self.conn.send(("get_request", rid, [oid], timeout))
        tok = waits_mod.park("object", oid, fresh=True)
        try:
            kind, payload = self._take_reply(rid, timeout)[oid]
        finally:
            waits_mod.unpark(tok)
        if kind == "error":
            raise payload if isinstance(payload, BaseException) \
                else TaskError(str(payload))
        if kind == "value":
            return serialization.unpack(payload)
        if kind == "value_staged":
            return serialization.unpack(self.take_staged_value(rid, oid))
        try:
            return self.store.get_value(payload)
        except ObjectLostError:
            if _retried:
                raise
            # segment gone without a spill copy: report the unreachable
            # location (the driver prunes it and reconstructs from
            # lineage when no live copy remains) and take ONE more
            # round-trip — on the REMAINING timeout budget, so
            # get(timeout=T) still bounds at ~T, not 2T
            self.conn.send(("object_unreachable", oid,
                            getattr(payload, "node_id", None)
                            or knobs.get_raw("RAY_TPU_NODE_ID"),
                            getattr(payload, "seal_seq", None)))
            remaining = None if timeout is None else max(
                0.1, timeout - (time.monotonic() - t0))
            return self._get_one_fresh(oid, remaining, _retried=True)

    def put(self, value: Any) -> ObjectRef:
        from . import device_store  # noqa: PLC0415
        oid = new_object_id()
        # jax.Arrays stay device-resident here; the driver pulls a
        # materialized copy only if a consumer elsewhere needs it
        loc = device_store.try_keep(self.store, self.worker_id, oid,
                                    value)
        self._batch.send(("put", oid, loc))
        return ObjectRef(oid)

    def _driver_wait(self, refs, num_returns, timeout):
        self._batch.flush()
        rid = self._new_req()
        self.conn.send(("wait_request", rid, [r.id for r in refs],
                        num_returns, timeout))
        tok = waits_mod.park("object", refs[0].id if refs else "",
                             op="wait", n=len(refs))
        try:
            ready_ids = set(self._take_reply(rid, None))
        finally:
            waits_mod.unpark(tok)
        ready = [r for r in refs if r.id in ready_ids]
        not_ready = [r for r in refs if r.id not in ready_ids]
        return ready, not_ready

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        direct = {r.id: f for r in refs
                  if (f := self._direct_results.get(r.id)) is not None
                  and not f.failover}
        # agent-placed futures duck-type the direct ones here (ev +
        # failover are all this loop reads), so they settle locally too
        for r in refs:
            if r.id not in direct:
                af = self._agent_results.get(r.id)
                if af is not None and not af.failover:
                    direct[r.id] = af
        if not direct:
            return self._driver_wait(refs, num_returns, timeout)
        # Mixed wait: direct-call futures settle locally (errored counts
        # as ready, like any settled object), driver-owned refs settle
        # through wait_request. The driver leg runs in bounded slices so
        # a direct completion is observed within ~0.2s.
        deadline = None if timeout is None \
            else time.monotonic() + (timeout or 0)
        others = [r for r in refs if r.id not in direct]
        ready_ids: set = set()
        # one park across the whole mixed-wait loop (the inner driver
        # slices are 0.2s — individually always younger than the ship
        # age, so only this outer record can represent a stuck wait())
        wtok = waits_mod.park("object", refs[0].id if refs else "",
                              op="wait", n=len(refs))
        try:
            return self._mixed_wait_loop(refs, direct, others,
                                         ready_ids, num_returns,
                                         deadline)
        finally:
            waits_mod.unpark(wtok)

    def _mixed_wait_loop(self, refs, direct, others, ready_ids,
                         num_returns, deadline):
        while True:
            # a channel death mid-wait flips futures to failover (the
            # spec was resubmitted through the driver): migrate those
            # refs to the driver leg or they would never settle here
            flipped = [oid for oid, f in direct.items() if f.failover]
            if flipped:
                for oid in flipped:
                    del direct[oid]
                others.extend(r for r in refs
                              if r.id in flipped and r.id not in ready_ids)
            ready_ids |= {oid for oid, f in direct.items()
                          if f.ev.is_set()}
            need = num_returns - len(ready_ids)
            remaining = None if deadline is None \
                else deadline - time.monotonic()
            if need <= 0 or (remaining is not None and remaining <= 0):
                break
            if others:
                slice_t = 0.2 if remaining is None \
                    else max(0.0, min(0.2, remaining))
                got, _ = self._driver_wait(
                    others, min(need, len(others)), slice_t)
                ready_ids |= {r.id for r in got}
                others = [r for r in others if r.id not in ready_ids]
            else:
                with self._direct_cv:
                    self._direct_cv.wait(
                        0.2 if remaining is None else min(0.2, remaining))
        ready = [r for r in refs if r.id in ready_ids]
        not_ready = [r for r in refs if r.id not in ready_ids]
        return ready, not_ready

    def submit(self, spec: TaskSpec) -> List[ObjectRef]:
        # Two-level scheduling (docs/SCHEDULING.md): a fan-out from a
        # worker goes to its OWN node agent for local placement when the
        # task is node-leaseable and every ref argument is known
        # node-resolvable — the dependency gate also prevents a put/
        # submit reorder across the two connections (the driver might
        # see the submit before the put that feeds it).
        ag = self._agent_plane
        if (ag is not None and not ag.dead
                and sched_mod.node_leaseable(spec)
                and all(oid in self._agent_known
                        for oid in spec.dep_object_ids)):
            return ag.submit(spec)
        self._batch.send(("submit", spec))
        return [ObjectRef(oid) for oid in spec.return_ids]

    def create_actor(self, acspec: ActorCreationSpec) -> None:
        self.conn.send(("submit_actor", acspec))

    def submit_actor_task(self, spec: TaskSpec) -> List[ObjectRef]:
        # Driver-bypass fast path: actor-to-actor (and any worker->
        # actor) unary calls resolve the callee's address once via the
        # GCS actor directory, then ride a direct worker->worker
        # connection — zero driver control messages steady-state. The
        # driver path stays as the instrumented fallback (streaming and
        # multi-return calls always use it).
        if (self._direct_enabled and spec.actor_id
                and not getattr(spec, "streaming", False)
                and len(spec.return_ids) == 1
                and not getattr(self._no_direct, "on", False)
                and self._try_direct_call(spec)):
            return [ObjectRef(spec.return_ids[0])]
        self._batch.send(("submit", spec))
        return [ObjectRef(oid) for oid in spec.return_ids]

    def kill_actor(self, actor_id: str, no_restart: bool = True) -> None:
        self._batch.flush()   # buffered calls must land before the kill
        self.conn.send(("kill_actor", actor_id, no_restart))

    def cancel(self, ref: ObjectRef, force: bool = False) -> None:
        self._batch.flush()
        self.conn.send(("cancel", ref.id, force))

    def cancel_task(self, task_id: str, force: bool = False) -> None:
        self._batch.flush()
        self.conn.send(("cancel", task_id, force))

    def report(self, channel: str, payload: Any) -> None:
        """Out-of-band message to the driver (train session, metrics...)."""
        self.conn.send(("report", channel, payload))

    def report_sync(self, channel: str, payload: Any, timeout=None) -> Any:
        self._batch.flush()
        rid = self._new_req()
        self.conn.send(("report_sync", rid, channel, payload))
        return self._take_reply(rid, timeout)

    def gen_next(self, task_id: str, timeout=None):
        """Worker-side consumption of a streaming generator: ask the
        driver for the next item ref (blocks until one streams in)."""
        from .object_ref import ObjectRef  # noqa: PLC0415
        from ..exceptions import TaskError  # noqa: PLC0415
        self._batch.flush()
        rid = self._new_req()
        self.conn.send(("gen_next_request", rid, task_id))
        try:
            kind, payload = self._take_reply(rid, timeout)
        except GetTimeoutError:
            # Tell the driver to drop the parked waiter (and restore the
            # CPU it lent back) so a later item isn't popped into a
            # reply nobody consumes.
            self.conn.send(("gen_abandon", rid))
            raise
        if kind == "item":
            return ObjectRef(payload)
        if kind == "error":
            if isinstance(payload, BaseException):
                raise payload
            raise TaskError(str(payload))
        return None

    def get_resources(self) -> Dict[str, float]:
        return {}

    def shutdown(self) -> None:
        pass

    # ---- function cache ---------------------------------------------------
    def load_func(self, spec: TaskSpec):
        if spec.func_id and spec.func_id in self._func_cache:
            return self._func_cache[spec.func_id]
        fn = serialization.loads_call(spec.func_bytes)
        if spec.func_id:
            self._func_cache[spec.func_id] = fn
        return fn


def _check_spec_payload(spec) -> None:
    """Fail fast on a spec whose user payload could not be unpickled on
    THIS worker (protocol.py stamps `wire_error` instead of dropping
    the frame). Raising here routes the cause through the normal
    task-failure reporting — the alternative (a silently dropped exec
    frame) leaves the task RUNNING forever and its caller parked
    (observed: a multihost rank payload referencing a module only
    importable on the driver node)."""
    we = getattr(spec, "wire_error", None)
    if we:
        raise RuntimeError(
            f"task payload could not be deserialized on this worker: "
            f"{we} — is every module the payload references importable "
            "on this node (shared filesystem / PYTHONPATH / runtime_env "
            "py_modules)?")


def _resolve_args(rt: WorkerRuntime, args, kwargs):
    """Fetch top-level ObjectRef args (deps are ready by scheduling time)."""
    if not args and not kwargs:
        return args, kwargs
    refs = [a for a in list(args) + list(kwargs.values())
            if isinstance(a, ObjectRef)]
    if not refs:
        return args, kwargs
    vals = rt.get(refs)
    table = {r.id: v for r, v in zip(refs, vals)}
    new_args = tuple(table[a.id] if isinstance(a, ObjectRef) else a
                     for a in args)
    new_kwargs = {k: (table[v.id] if isinstance(v, ObjectRef) else v)
                  for k, v in kwargs.items()}
    return new_args, new_kwargs


class DirectCallServer:
    """Per-worker listener for driver-bypass actor calls. An incoming
    ("dcall", rid, spec) enqueues into the SAME execution lanes as
    driver dispatch (main loop / thread pools / async loop), so
    max_concurrency and concurrency groups hold; the reply carries the
    packed VALUE straight back — no store seal, no driver message."""

    def __init__(self, loop: "WorkerLoop", driver_address: str):
        import tempfile  # noqa: PLC0415
        self._loop = loop
        self._conns: List[Connection] = []
        if str(driver_address).startswith("tcp://"):
            # remote-node worker: peers on other hosts must reach us
            from .protocol import tcp_listener  # noqa: PLC0415
            from ..util.netutil import routable_ip  # noqa: PLC0415
            self._listener = tcp_listener("0.0.0.0", 0)
            port = self._listener.getsockname()[1]
            self.address = f"tcp://{routable_ip()}:{port}"
        else:
            from .protocol import unix_listener  # noqa: PLC0415
            # prefer the driver's log dir (cleaned up at driver
            # shutdown) over a per-worker tmpdir that os._exit leaks
            base = knobs.get_raw("RAY_TPU_LOG_DIR")
            if not base or not os.path.isdir(base):
                base = tempfile.mkdtemp(prefix="ray_tpu_dcall_")
            path = os.path.join(
                base, f"dcall-{loop.worker_id}-{os.getpid()}.sock")
            self._listener = unix_listener(path)
            self.address = path
        threading.Thread(target=self._accept, daemon=True,
                         name="dcall-accept").start()

    def _accept(self) -> None:
        while True:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            conn = Connection(sock)
            self._conns.append(conn)
            threading.Thread(target=self._reader, args=(conn,),
                             daemon=True, name="dcall-reader").start()

    def _reader(self, conn: Connection) -> None:
        while True:
            try:
                # raylint: disable=RT003 inbound direct-call conn: a dead
                # caller's socket closes (EOF) and its calls were already
                # failed over by the driver's death determination; a parked
                # reader costs one daemon thread
                m = conn.recv()
            except ConnectionClosed:
                return
            if m[0] != "dcall":
                continue
            _, rid, spec = m
            rt = self._loop.rt
            if (spec.actor_id != rt.current_actor_id
                    or self._loop._actor_instance is None):
                # stale directory entry (actor moved/died since the
                # caller resolved it): the caller fails over and
                # re-resolves — never execute under a wrong identity
                try:
                    conn.send(("dreject", rid))
                except ConnectionClosed:
                    return
                continue
            spec._direct_ch = (conn, rid)
            self._loop._task_q.put(("actor_task", spec))

    def close(self) -> None:
        try:
            self._listener.close()
        except Exception:
            pass
        for c in self._conns:
            try:
                c.close()
            except Exception:
                pass


class WorkerLoop:
    def __init__(self, socket_path: str, worker_id: str):
        # socket_path is a unix path for same-host workers or
        # "tcp://host:port" for workers spawned by a remote node agent.
        self.conn = connect_address(socket_path)
        self.store = make_store(
            capacity_bytes=knobs.get_int("RAY_TPU_STORE_BYTES"),
            is_owner=False)
        self.rt = WorkerRuntime(self.conn, worker_id, self.store)
        self.worker_id = worker_id
        self._task_q: "queue.Queue" = queue.Queue()
        self._shutdown = threading.Event()
        self._actor_instance: Any = None
        self._actor_spec: Optional[ActorCreationSpec] = None
        self._actor_pool: Optional[ThreadPoolExecutor] = None
        self._group_pools: Dict[str, ThreadPoolExecutor] = {}
        self._async_loop = None
        self._async_sems: Dict[Optional[str], Any] = {}
        self._cancelled: set = set()
        # lease slots the driver reclaimed (blocked-head revoke): skip
        # silently when they surface in the queue. _queued_tasks mirrors
        # the ids sitting in _task_q so a revoke can tell "not started
        # yet" (fence it) from "already running/finished" (leave it to
        # the driver's revoked-pair guard) — fencing a started task
        # would leave a stale entry that silently swallows a future
        # re-dispatch of the same id to this worker (no task_done ever,
        # caller hangs)
        self._revoked: set = set()
        self._queued_tasks: set = set()
        # worker->driver control-message batcher: completions, seals
        # and nested submits coalesce into ("batch", ...) frames
        self._batch = _MsgBatcher(
            self.conn,
            max_n=knobs.get_int("RAY_TPU_BATCH_FLUSH_N"),
            window=knobs.get_float("RAY_TPU_BATCH_FLUSH_S"),
            enabled=knobs.get_bool("RAY_TPU_BATCH"))
        self.rt._batch = self._batch
        # agent-local dispatch plane (two-level scheduling): connect
        # BEFORE run() registers with the driver, so by the time the
        # driver sees this worker idle the agent can dispatch to it
        self._agent: Optional[_AgentPlane] = None
        agent_addr = knobs.get_raw("RAY_TPU_AGENT_ADDR")
        if agent_addr:
            try:
                self._agent = _AgentPlane(self, agent_addr)
                self.rt._agent_plane = self._agent
            except Exception:
                self._agent = None   # agent gone: driver path only
        # direct-call plane listener (RAY_TPU_DIRECT_CALLS=0 disables)
        self._direct_server = None
        if self.rt._direct_enabled:
            try:
                self._direct_server = DirectCallServer(self, socket_path)
            except Exception:
                self._direct_server = None
        # telemetry plane: metric deltas + execution spans ship to the
        # driver over the existing conn (report channels sys.metrics /
        # sys.spans) after each task and on a periodic heartbeat, so
        # the driver's /metrics exposes series recorded IN this process
        self._delta_exporter = metrics_mod.DeltaExporter()
        self._spans: List[dict] = []
        self._telemetry_lock = threading.Lock()
        self._last_flush = 0.0
        self._heartbeat_on = True   # set from env in run()
        self.rt._span_sink = self.record_span
        # always-on sampling profiler (off at hz=0; profile_ctl can
        # start/stop/snapshot it at runtime)
        from ..observability import sampling_profiler  # noqa: PLC0415
        self._profiler = sampling_profiler.SamplingProfiler(
            hz=knobs.get_float("RAY_TPU_PROFILE_HZ"))
        # __ray_save__ checkpoint shipping (actors that define the hook)
        self._ckpt_lock = threading.Lock()
        self._last_ckpt = 0.0
        # compiled-DAG plane (docs/DAG.md): built on first dag_install
        self._dag_ctx = None
        self.socket_path = socket_path

    def _dag(self):
        if self._dag_ctx is None:
            from .dag_runtime import WorkerDagContext  # noqa: PLC0415
            self._dag_ctx = WorkerDagContext(self)
        return self._dag_ctx

    # ---- main -------------------------------------------------------------
    def run(self) -> None:
        from . import runtime as runtime_mod  # noqa: PLC0415
        runtime_mod.set_runtime(self.rt)
        self.conn.send(("register", self.worker_id, os.getpid(),
                        self._direct_server.address
                        if self._direct_server else None))
        reader = threading.Thread(target=self._read_loop, daemon=True)
        reader.start()
        interval = knobs.get_float("RAY_TPU_METRICS_INTERVAL_S")
        self._heartbeat_on = interval > 0
        if interval > 0:
            threading.Thread(target=self._telemetry_loop,
                             args=(interval,), daemon=True,
                             name="worker-telemetry").start()
        while not self._shutdown.is_set():
            try:
                item = self._task_q.get(timeout=0.2)
            except queue.Empty:
                continue
            kind, payload = item
            if kind == "task":
                # un-queue BEFORE running so a concurrent revoke_tasks
                # classifies this id as started (program order makes
                # the discard visible before _run_task's fence check)
                self._queued_tasks.discard(payload.task_id)
                self._run_task(payload)
            elif kind == "create_actor":
                self._create_actor(payload)
            elif kind == "actor_task":
                self._dispatch_actor_task(payload)
        # graceful exit: ship whatever the batcher and the telemetry
        # buffers still hold — the final spans/events/metric deltas of
        # a completed job must not die with the process
        try:
            self._batch.flush()
        except Exception:
            pass
        self._flush_telemetry()
        try:
            self.conn.close()
        except Exception:
            pass

    def _read_loop(self) -> None:
        from .protocol import RECV_ERROR  # noqa: PLC0415
        while True:
            try:
                # raylint: disable=RT003 the worker's own driver conn: driver
                # process death closes it, and a silent driver HOST is the
                # node agent's RAY_TPU_DRIVER_SILENCE_S watchdog's job — it
                # terminates this worker when it rejoins
                msg = self.conn.recv()
            except ConnectionClosed:
                self._shutdown.set()
                os._exit(0)
            mtype = msg[0]
            if mtype == RECV_ERROR:
                sys.stderr.write(
                    f"[ray_tpu worker {self.worker_id}] dropped "
                    f"undeserializable message:\n{msg[1]}")
                continue
            if mtype == "exec_task":
                self._queued_tasks.add(msg[1].task_id)
                self._task_q.put(("task", msg[1]))
            elif mtype == "exec_task_many":
                # a multi-slot lease grant: the specs execute strictly
                # FIFO off this queue against the lease's resource slot
                for spec in msg[1]:
                    self._queued_tasks.add(spec.task_id)
                    self._task_q.put(("task", spec))
            elif mtype == "exec_actor_task_many":
                for spec in msg[1]:
                    self._task_q.put(("actor_task", spec))
            elif mtype == "revoke_tasks":
                # driver reclaimed unstarted lease slots (blocked head):
                # fence only ids still waiting in the local queue — an
                # id that already started (watchdog reclaim racing the
                # head's in-flight completion) must NOT be fenced, or
                # the stale entry would swallow a later re-dispatch of
                # the same task; its duplicate result is dropped by the
                # driver's revoked-pair guard instead
                self._revoked.update(
                    tid for tid in msg[1] if tid in self._queued_tasks)
            elif mtype == "create_actor":
                # (acspec, checkpoint|None) — the checkpoint is the
                # actor's latest __ray_save__ state around a restart
                self._task_q.put(("create_actor",
                                  (msg[1],
                                   msg[2] if len(msg) > 2 else None)))
            elif mtype == "exec_actor_task":
                self._task_q.put(("actor_task", msg[1]))
            elif mtype == "get_reply":
                self.rt.deliver_reply(msg[1], msg[2])
            elif mtype == "value_chunk":
                self.rt.stash_value_chunk(msg[1], msg[2], msg[3], msg[4],
                                          msg[5])
            elif mtype == "cancel":
                self._cancelled.add(msg[1])
            elif mtype == "materialize":
                self._materialize(msg[1])
            elif mtype == "drop_device":
                from . import device_store  # noqa: PLC0415
                device_store.drop(msg[1])
            elif mtype == "dag_install":
                # compile-time only; steady-state executions never
                # touch this socket (docs/DAG.md)
                self._dag().install(msg[1])
            elif mtype == "dag_start":
                self._dag().start(msg[1], msg[2])
            elif mtype == "dag_teardown":
                if self._dag_ctx is not None:
                    self._dag_ctx.teardown(msg[1])
            elif mtype == "profile_ctl":
                self._handle_profile_ctl(
                    msg[1], msg[2], msg[3] if len(msg) > 3 else None)
            elif mtype == "shutdown":
                if self._dag_ctx is not None:
                    self._dag_ctx.teardown_all()
                self._shutdown.set()

    # ---- telemetry --------------------------------------------------------
    def _telemetry_loop(self, interval: float) -> None:
        """Heartbeat shipping for long-running work (an actor hosting an
        LLM engine records tokens continuously with no task boundary)."""
        while not self._shutdown.is_set():
            time.sleep(interval)
            self._memory_gauges()
            self._flush_telemetry()

    def _memory_gauges(self) -> None:
        """Per-device HBM + host RSS gauges, refreshed per heartbeat
        (observability/profiler.py's memory accounting wired into the
        metrics plane; {} on backends without memory_stats)."""
        try:
            from ..observability import profiler  # noqa: PLC0415
            mcat.get("ray_tpu_worker_host_rss_bytes").set(
                profiler.host_rss_bytes())
            for dev, used in profiler.hbm_usage().items():
                mcat.get("ray_tpu_worker_hbm_used_bytes").set(
                    used, tags={"device": dev})
        except Exception:
            pass

    def _handle_profile_ctl(self, rid, action, arg) -> None:
        """On-demand profiler control (runs on the reader thread: every
        action is sub-millisecond and never blocks on user work)."""
        prof = self._profiler
        try:
            if action == "start":
                hz = float(arg) if arg else 100.0
                prof.set_hz(hz)
                events_mod.emit(
                    "worker.profile.start",
                    f"sampling profiler started at {hz:g} Hz",
                    worker_id=self.worker_id, hz=hz)
                payload = prof.status()
            elif action == "stop":
                prof.stop()
                events_mod.emit(
                    "worker.profile.stop", "sampling profiler stopped",
                    worker_id=self.worker_id)
                payload = prof.status()
            elif action == "snapshot":
                payload = prof.snapshot()
            elif action == "stack":
                # one-shot cluster stack dump (`ray_tpu stack`): walk
                # every thread's live frames with task attribution
                from ..observability import \
                    sampling_profiler as sp  # noqa: PLC0415
                payload = sp.dump_stacks()
                payload["worker_id"] = self.worker_id
            else:
                payload = prof.status()
        except Exception as e:  # noqa: BLE001
            payload = {"error": repr(e)}
        try:
            self.conn.send(("profile_reply", rid, payload))
        except Exception:
            pass   # driver gone; nothing to reply to

    def record_span(self, span: dict) -> None:
        """Buffer an externally-built span record (fast-path
        instrumentation: dcall submits, compiled-DAG stages) for the
        next telemetry flush — spans ride sys.spans on the heartbeat,
        never the control plane."""
        with self._telemetry_lock:
            self._spans.append(span)

    def _record_span(self, spec: TaskSpec, span_id: str, start: float,
                     end: float, status: str) -> None:
        entry = {
            "trace_id": getattr(spec, "trace_id", "") or "",
            "span_id": span_id,
            "parent_span_id": getattr(spec, "span_id", "") or "",
            "task_id": spec.task_id, "name": spec.name,
            "start": start, "end": end, "status": status,
            "pid": os.getpid(), "worker_id": self.worker_id,
            "node_id": knobs.get_raw("RAY_TPU_NODE_ID"),
        }
        lease = getattr(spec, "lease_id", "") or ""
        if lease:
            entry["lease_id"] = lease
        with self._telemetry_lock:
            self._spans.append(entry)

    def _flush_telemetry(self, min_interval: float = 0.0) -> None:
        """Ship buffered spans + registry deltas. Never raises — a
        telemetry failure must not fail user work. min_interval > 0
        throttles the registry walk (sub-millisecond task storms must
        not pay a full delta collect per task; the heartbeat thread
        ships whatever a throttled call left buffered)."""
        with self._telemetry_lock:
            now = time.monotonic()
            if min_interval and now - self._last_flush < min_interval:
                return
            self._last_flush = now
        # compiled-DAG stage spans sit in per-dag rings as bare tuples;
        # the expensive dict/derived-id conversion runs here, at flush
        # cadence, never on the per-seqno exec loop
        dag_spans: List[dict] = []
        if self._dag_ctx is not None:
            try:
                dag_spans = self._dag_ctx.drain_stage_spans()
            except Exception:
                dag_spans = []
        with self._telemetry_lock:
            spans, self._spans = self._spans, []
            if dag_spans:
                spans.extend(dag_spans)
            try:
                payload = self._delta_exporter.collect()
            except Exception:
                payload = None
        try:
            events = events_mod.drain()
        except Exception:
            events = None
        try:
            prof = self._profiler.collect_delta()
        except Exception:
            prof = None
        # wait-state plane: collect() returns None unless the set of
        # AGED waits changed — a healthy pipeline's micro-waits never
        # produce a sys.waits frame (the zero-steady-state-frames
        # property tests/test_waits.py counter-asserts)
        try:
            wts = waits_mod.collect()
        except Exception:
            wts = None
        try:
            if spans:
                self.conn.send(("report", "sys.spans", spans))
            if payload:
                self.conn.send(("report", "sys.metrics", payload))
            if events:
                self.conn.send(("report", "sys.events", events))
            if prof:
                self.conn.send(("report", "sys.profile", prof))
            if wts is not None:
                self.conn.send(("report", "sys.waits", wts))
        except Exception:  # ConnectionClosed included: driver is gone
            pass

    def _finish_task_telemetry(self, spec: TaskSpec, span_id: str,
                               start: float, status: str) -> None:
        end = time.time()
        try:
            mcat.get("ray_tpu_worker_task_run_s").observe(end - start)
            mcat.get("ray_tpu_worker_tasks_total").inc(
                tags={"status": status})
        except Exception:
            pass
        try:
            self._record_span(spec, span_id, start, end, status)
        except Exception:
            pass
        # throttle only when the heartbeat will sweep the leftovers
        self._flush_telemetry(
            min_interval=0.2 if self._heartbeat_on else 0.0)

    # ---- execution --------------------------------------------------------
    def _seal_returns(self, spec: TaskSpec, result: Any,
                      host: bool = False):
        """Pack return values; small ones ride inline in task_done.

        Values holding live jax.Arrays stay DEVICE-RESIDENT in this
        process (core/device_store.py): the sealed location is a device
        handle; same-worker consumers read the live value with no D2H,
        and the driver asks us to materialize only when a consumer
        elsewhere needs the bytes.

        `host=True` forces host-kind seals (shared arena / spill file):
        agent-placed nested tasks use it because their consumer is a
        SIBLING worker reading straight from the node arena — a device
        handle pinned in this process would be unreadable there without
        a driver materialize round-trip."""
        n = spec.num_returns
        values = (result,) if n == 1 else tuple(result)
        if n > 1 and len(values) != n:
            raise ValueError(
                f"task {spec.name} declared num_returns={n} but returned "
                f"{len(values)} values")
        sealed = []
        if host:
            from .spilling import put_value_or_spill  # noqa: PLC0415
            for oid, val in zip(spec.return_ids, values):
                sealed.append((oid, put_value_or_spill(
                    self.store, oid, val)))
            return sealed
        from . import device_store  # noqa: PLC0415
        for oid, val in zip(spec.return_ids, values):
            sealed.append((oid, device_store.try_keep(
                self.store, self.worker_id, oid, val)))
        return sealed

    def _materialize(self, oid: str) -> None:
        """Driver asked for a device-resident object's bytes (a consumer
        is elsewhere): serialize to the shm store and re-seal. Runs on
        the reader thread (Connection.send is locked; the shm arena is
        process-shared-mutex guarded), so a long-running task here can't
        stall a remote consumer."""
        from . import device_store  # noqa: PLC0415
        from .spilling import put_value_or_spill  # noqa: PLC0415
        val = device_store.peek(oid)
        if val is None:
            self.conn.send(("materialize_failed", oid,
                            "not resident on this worker"))
            return
        try:
            loc = put_value_or_spill(self.store, oid, val)
        except BaseException as e:  # noqa: BLE001
            self.conn.send(("materialize_failed", oid, repr(e)))
            return
        device_store.COUNTERS["materialized"] += 1
        # the host copy now serves every consumer (local ones included):
        # drop the device entry so HBM is reclaimed and the table never
        # pins long-dead values. A distinct message type (not "put")
        # lets the driver detect an object freed mid-materialize and
        # reclaim the fresh shm copy instead of resurrecting a ghost.
        device_store.drop(oid)
        self.conn.send(("materialized", oid, loc))

    # sealed payloads past this size flush their completion immediately:
    # the driver's watermark spiller must learn about big arena writes
    # NOW, not a batch later — leased tasks produce back-to-back with no
    # dispatch round-trip pacing them, and a lagging spiller lets the
    # arena evict unspilled segments under pressure
    _URGENT_SEAL_BYTES = 1 << 20

    def _task_done(self, task_id: str, sealed, error) -> None:
        """Completion message via the batcher: flush immediately when
        the local queue drained (no latency added to the last result of
        a batch) or the seal is big, else coalesce with the ones right
        behind."""
        big = any((getattr(loc, "size", 0) or 0) >= self._URGENT_SEAL_BYTES
                  for _oid, loc in sealed)
        self._batch.send(("task_done", task_id, sealed, error),
                         urgent=big or self._task_q.empty())
        if big:
            self._store_backpressure()

    def _complete_task(self, spec: TaskSpec, sealed, error) -> None:
        """Route a completion to the plane that dispatched the task:
        agent-placed tasks (two-level scheduling) report to the node
        agent, everything else to the driver. A dead agent plane falls
        back to the driver connection — driver-granted lease tasks are
        in its ledger, and its death handling fences any duplicate."""
        if getattr(spec, "_via_agent", False) and self._agent is not None \
                and self._agent.task_done(spec.task_id, sealed, error):
            return
        self._task_done(spec.task_id, sealed, error)

    def _store_backpressure(self, max_wait_s: float = 2.0) -> None:
        """Bounded wait for the driver's watermark spiller after a big
        seal. Pre-lease, production was paced by the dispatch round
        trip — the spiller ran between a task's seal and the next
        dispatch, so the arena never outran it. Leased/pipelined tasks
        produce back-to-back; without this, a burst of large returns
        can fill the arena and evict not-yet-spilled segments (data
        loss turned reconstruction churn). Only engages above the
        spill watermark, and gives up after max_wait_s so a stuck
        spiller degrades to the old racy behavior instead of stalling
        the worker."""
        cap = getattr(self.store, "capacity", 0) or 0
        if cap <= 0:
            return
        from .spilling import spill_threshold  # noqa: PLC0415
        limit = cap * spill_threshold()
        if self.store.used_bytes() <= limit:
            return
        deadline = time.monotonic() + max_wait_s
        while time.monotonic() < deadline \
                and self.store.used_bytes() > limit:
            time.sleep(0.005)

    def _run_task(self, spec: TaskSpec) -> None:
        if spec.task_id in self._revoked:
            # reclaimed lease slot: the driver already re-queued it
            self._revoked.discard(spec.task_id)
            return
        if spec.task_id in self._cancelled:
            self._complete_task(spec, [], "cancelled")
            return
        self.rt.current_task_id = spec.task_id
        # Dispatcher-assigned chip indices (disjoint across concurrent
        # workloads; placement-group tasks get their bundle's ids)
        self.rt.current_tpu_ids = list(getattr(spec, "tpu_ids", []) or [])
        logging_mod.mark_current_task(spec.task_id)
        t0 = time.time()
        exec_span = tracing.new_span_id()
        status = "ok"
        try:
            from . import runtime_env as renv_mod  # noqa: PLC0415
            _check_spec_payload(spec)
            fn = self.rt.load_func(spec)
            args, kwargs = _resolve_args(self.rt, spec.args, spec.kwargs)
            # execution runs under this task's span so nested .remote()
            # submissions parent to it (cross-process trace tree)
            with renv_mod.applied(spec.runtime_env), \
                    tracing.active(getattr(spec, "trace_id", "") or "",
                                   exec_span):
                result = fn(*args, **kwargs)
                if getattr(spec, "streaming", False):
                    cancelled = self._stream_items(spec, result)
                    if cancelled:
                        status = "cancelled"
                    self._task_done(spec.task_id, [],
                                    "cancelled" if cancelled else None)
                    return
            sealed = self._seal_returns(
                spec, result, host=getattr(spec, "_host_seal", False))
            self._complete_task(spec, sealed, None)
        except BaseException as e:  # noqa: BLE001
            status = "error"
            err = TaskError(repr(e), traceback.format_exc(), spec.name)
            self._complete_task(spec, [], err)
        finally:
            self.rt.current_task_id = None
            logging_mod.mark_current_task(None)
            self._finish_task_telemetry(spec, exec_span, t0, status)

    def _create_actor(self, payload) -> None:
        acspec, ckpt = payload
        try:
            from . import runtime_env as renv_mod  # noqa: PLC0415
            # dedicated worker: the actor's runtime_env holds for its life
            renv_mod.apply_permanent(acspec.runtime_env)
            _check_spec_payload(acspec)
            cls = serialization.loads_call(acspec.class_bytes)
            args, kwargs = _resolve_args(self.rt, acspec.args, acspec.kwargs)
            self._actor_instance = cls(*args, **kwargs)
            if ckpt is not None and hasattr(self._actor_instance,
                                            "__ray_restore__"):
                # restart of a checkpointing actor: the constructor ran
                # with the ORIGINAL args, then state resumes from the
                # last __ray_save__ snapshot instead of resetting
                self._actor_instance.__ray_restore__(
                    serialization.unpack(ckpt))
                self.rt.actor_restored = True
                events_mod.emit(
                    "actor.restore",
                    f"restored __ray_save__ checkpoint ({len(ckpt)} B)",
                    actor_id=acspec.actor_id, worker_id=self.worker_id)
            self._actor_spec = acspec
            self.rt.current_actor_id = acspec.actor_id
            self.rt.current_tpu_ids = list(
                getattr(acspec, "tpu_ids", []) or [])
            groups = getattr(acspec, "concurrency_groups", None) or {}
            if acspec.max_concurrency > 1 or groups:
                self._actor_pool = ThreadPoolExecutor(
                    max_workers=max(1, acspec.max_concurrency),
                    thread_name_prefix="actor")
            # one executor lane per named group: a slow sync method in
            # one group can never occupy another group's threads (the
            # driver already gates dispatch per-group; the lanes keep
            # the isolation inside the process too)
            self._group_pools = {
                g: ThreadPoolExecutor(max_workers=n,
                                      thread_name_prefix=f"actor-{g}")
                for g, n in groups.items()}
            self.conn.send(("actor_created", acspec.actor_id, True, None))
        except BaseException as e:  # noqa: BLE001
            err = TaskError(repr(e), traceback.format_exc(),
                            f"{acspec.class_name}.__init__")
            self.conn.send(("actor_created", acspec.actor_id, False, err))

    def _dispatch_actor_task(self, spec: TaskSpec) -> None:
        import inspect  # noqa: PLC0415
        method = getattr(self._actor_instance, spec.method_name, None)
        fn = getattr(method, "__func__", method)
        if method is not None and inspect.isasyncgenfunction(fn):
            # async streaming method: iterate on the actor's event loop
            self._ensure_async_loop()
            import asyncio  # noqa: PLC0415
            asyncio.run_coroutine_threadsafe(
                self._run_actor_task_asyncgen(spec), self._async_loop)
        elif method is not None and inspect.iscoroutinefunction(fn):
            self._ensure_async_loop()
            import asyncio  # noqa: PLC0415
            asyncio.run_coroutine_threadsafe(
                self._run_actor_task_async(spec), self._async_loop)
        else:
            pool = self._group_pools.get(
                getattr(spec, "concurrency_group", None),
                self._actor_pool)
            if pool is not None:
                pool.submit(self._run_actor_task, spec)
            else:
                self._run_actor_task(spec)

    def _put_gen_item(self, spec: TaskSpec, item) -> None:
        """Seal one streamed item and announce it to the driver (the
        single definition of the gen_item protocol — sync and async
        generator paths both go through here)."""
        from .ids import new_object_id  # noqa: PLC0415
        from .spilling import put_value_or_spill  # noqa: PLC0415
        oid = new_object_id()
        loc = put_value_or_spill(self.store, oid, item)
        self._batch.send(("gen_item", spec.task_id, oid, loc))

    def _stream_items(self, spec: TaskSpec, iterable) -> bool:
        """Put each yielded item and announce it to the driver in order
        (streaming-generator tasks, num_returns="streaming"). Returns
        True if the task was cancelled mid-stream (the generator is
        closed and no further items are emitted)."""
        for item in iterable:
            if spec.task_id in self._cancelled:
                close = getattr(iterable, "close", None)
                if close:
                    close()
                return True
            self._put_gen_item(spec, item)
        return False

    def _maybe_checkpoint(self) -> None:
        """After a completed actor call: if the actor opted into the
        checkpoint contract (defines __ray_save__), serialize its state
        and ship it to the driver for the next restart's
        __ray_restore__. Throttled by checkpoint_interval_s (actor
        option, falling back to RAY_TPU_ACTOR_CHECKPOINT_INTERVAL_S;
        0 = after every completed call). Never fails user work."""
        inst = self._actor_instance
        save = getattr(inst, "__ray_save__", None)
        if inst is None or save is None:
            return
        interval = getattr(self._actor_spec, "checkpoint_interval_s",
                           None)
        if interval is None:
            interval = knobs.get_float(
                "RAY_TPU_ACTOR_CHECKPOINT_INTERVAL_S")
        try:
            # pack AND send under the lock: with max_concurrency > 1,
            # an older blob sent after a newer one would roll the
            # driver's retained state backwards
            with self._ckpt_lock:
                now = time.monotonic()
                if interval > 0 and now - self._last_ckpt < interval:
                    return
                blob = serialization.pack(save())
                self._last_ckpt = now
                # raylint: disable=RT001 deliberate pack+send
                # atomicity (PR 4): _ckpt_lock serializes checkpoints
                # only — a blocking send delays at most the next
                # checkpoint, and Connection has its own send lock
                self.conn.send(("actor_ckpt", self.rt.current_actor_id,
                                blob))
            mcat.get("ray_tpu_actor_checkpoints_total").inc()
        except Exception:
            # a failing checkpoint must not fail the call that
            # triggered it; the actor just restarts from an older one
            pass

    def _actor_reply(self, spec: TaskSpec, result, error) -> None:
        """Route one actor-call completion: direct calls reply with the
        packed VALUE over the caller's channel (no store seal, no driver
        message); driver-dispatched calls seal returns and batch a
        task_done like before."""
        direct = getattr(spec, "_direct_ch", None)
        if direct is not None:
            conn, rid = direct
            try:
                if error is not None:
                    conn.send(("dresult", rid, False, error))
                else:
                    conn.send(("dresult", rid, True,
                               serialization.pack(result)))
            except Exception:  # noqa: BLE001
                pass   # caller gone: nobody is waiting for this value
            return
        if error is not None:
            self._task_done(spec.task_id, [], error)
        else:
            self._task_done(spec.task_id, self._seal_returns(spec, result),
                            None)

    def _run_actor_task(self, spec: TaskSpec) -> None:
        from ..exceptions import ActorExitRequest  # noqa: PLC0415
        if spec.task_id in self._cancelled:
            # pipelined dispatch: a cancel can land while the call is
            # still queued in this process — honor it like _run_task
            self._cancelled.discard(spec.task_id)
            self._task_done(spec.task_id, [], "cancelled")
            return
        t0 = time.time()
        exec_span = tracing.new_span_id()
        status = "ok"
        logging_mod.mark_current_task(spec.task_id)
        try:
            _check_spec_payload(spec)
            method = getattr(self._actor_instance, spec.method_name)
            args, kwargs = _resolve_args(self.rt, spec.args, spec.kwargs)
            with tracing.active(getattr(spec, "trace_id", "") or "",
                                exec_span):
                result = method(*args, **kwargs)
                if getattr(spec, "streaming", False):
                    cancelled = self._stream_items(spec, result)
                    if cancelled:
                        status = "cancelled"
                    self._task_done(spec.task_id, [],
                                    "cancelled" if cancelled else None)
                    self._maybe_checkpoint()
                    return
            self._actor_reply(spec, result, None)
            self._maybe_checkpoint()
        except ActorExitRequest:
            # graceful self-exit: this call returns None, then the actor
            # goes down for good (no restart)
            self._actor_reply(spec, None, None)
            self._batch.flush()
            self.conn.send(("actor_exit", self.rt.current_actor_id))
            # os._exit skips the finally block: ship this call's span
            # and any buffered telemetry NOW or it dies with the process
            self._finish_task_telemetry(spec, exec_span, t0, "ok")
            self._flush_telemetry()
            os._exit(0)  # works from threadpool threads too
        except BaseException as e:  # noqa: BLE001
            status = "error"
            err = TaskError(repr(e), traceback.format_exc(),
                            f"{type(self._actor_instance).__name__}."
                            f"{spec.method_name}")
            self._actor_reply(spec, None, err)
        finally:
            logging_mod.mark_current_task(None)
            self._finish_task_telemetry(spec, exec_span, t0, status)

    def _async_sem(self, group: Optional[str]):
        """Per-lane asyncio semaphore enforcing max_concurrency /
        concurrency-group limits IN the worker. With pipelined actor
        dispatch the driver intentionally sends past the limit (the
        extra slots just pre-stage specs), so the execution bound for
        async methods — which all share one event loop — must live
        here. Loop-thread only."""
        import asyncio  # noqa: PLC0415
        groups = getattr(self._actor_spec, "concurrency_groups",
                         None) or {}
        key = group if group in groups else None
        sem = self._async_sems.get(key)
        if sem is None:
            limit = groups.get(key) if key else max(
                1, getattr(self._actor_spec, "max_concurrency", 1))
            sem = self._async_sems[key] = asyncio.Semaphore(limit or 1)
        return sem

    async def _run_actor_task_asyncgen(self, spec: TaskSpec) -> None:
        """Streaming from an `async def ... yield` actor method. Requires
        num_returns=\"streaming\" on the call (enforced below — a plain
        call would otherwise try to seal an async_generator object)."""
        from ..exceptions import ActorExitRequest  # noqa: PLC0415
        t0 = time.time()
        exec_span = tracing.new_span_id()
        status = "ok"
        try:
            _check_spec_payload(spec)
            async with self._async_sem(
                    getattr(spec, "concurrency_group", None)):
                method = getattr(self._actor_instance, spec.method_name)
                args, kwargs = _resolve_args(self.rt, spec.args,
                                             spec.kwargs)
                agen = method(*args, **kwargs)
                if not getattr(spec, "streaming", False):
                    raise TypeError(
                        f"{spec.method_name} is an async generator; "
                        "call it with num_returns=\"streaming\"")
                cancelled = False
                async for item in agen:
                    if spec.task_id in self._cancelled:
                        cancelled = True
                        await agen.aclose()
                        break
                    self._put_gen_item(spec, item)
                if cancelled:
                    status = "cancelled"
                self._task_done(spec.task_id, [],
                                "cancelled" if cancelled else None)
                self._maybe_checkpoint()
        except ActorExitRequest:
            self._task_done(spec.task_id, [], None)
            self._batch.flush()
            self.conn.send(("actor_exit", self.rt.current_actor_id))
            # os._exit skips the finally block: ship this call's span
            self._finish_task_telemetry(spec, exec_span, t0, "ok")
            self._flush_telemetry()
            os._exit(0)
        except BaseException as e:  # noqa: BLE001
            status = "error"
            err = TaskError(repr(e), traceback.format_exc(),
                            f"asyncgen.{spec.method_name}")
            self._task_done(spec.task_id, [], err)
        finally:
            # no tracing.active here: interleaved coroutines share the
            # loop thread, so a thread-local context would leak between
            # requests — the span record alone keeps the timeline link
            self._finish_task_telemetry(spec, exec_span, t0, status)

    async def _run_actor_task_async(self, spec: TaskSpec) -> None:
        from ..exceptions import ActorExitRequest  # noqa: PLC0415
        if spec.task_id in self._cancelled:
            self._cancelled.discard(spec.task_id)
            self._task_done(spec.task_id, [], "cancelled")
            return
        t0 = time.time()
        exec_span = tracing.new_span_id()
        status = "ok"
        try:
            _check_spec_payload(spec)
            async with self._async_sem(
                    getattr(spec, "concurrency_group", None)):
                method = getattr(self._actor_instance, spec.method_name)
                args, kwargs = _resolve_args(self.rt, spec.args,
                                             spec.kwargs)
                result = await method(*args, **kwargs)
            self._actor_reply(spec, result, None)
            self._maybe_checkpoint()
        except ActorExitRequest:
            self._actor_reply(spec, None, None)
            self._batch.flush()
            self.conn.send(("actor_exit", self.rt.current_actor_id))
            # os._exit skips the finally block: ship this call's span
            self._finish_task_telemetry(spec, exec_span, t0, "ok")
            self._flush_telemetry()
            os._exit(0)
        except BaseException as e:  # noqa: BLE001
            status = "error"
            err = TaskError(repr(e), traceback.format_exc(),
                            f"async.{spec.method_name}")
            self._actor_reply(spec, None, err)
        finally:
            self._finish_task_telemetry(spec, exec_span, t0, status)

    def _ensure_async_loop(self):
        if self._async_loop is None:
            import asyncio  # noqa: PLC0415
            self._async_loop = asyncio.new_event_loop()
            t = threading.Thread(target=self._async_loop.run_forever,
                                 daemon=True, name="actor-asyncio")
            t.start()


def main() -> None:
    socket_path, worker_id = sys.argv[1], sys.argv[2]
    log_dir = knobs.get_raw("RAY_TPU_LOG_DIR")
    if log_dir:
        from .logging import redirect_process_output  # noqa: PLC0415
        redirect_process_output(
            os.path.join(log_dir, f"worker-{worker_id}.log"))
    try:
        loop = WorkerLoop(socket_path, worker_id)
    except (ConnectionRefusedError, FileNotFoundError):
        # Driver died between spawning us and our connect: exit quietly.
        sys.exit(0)
    loop.run()


if __name__ == "__main__":
    main()
