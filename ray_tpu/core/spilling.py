"""Object spilling: live objects survive arena eviction via disk copies.

Reference parity: src/ray/object_manager/plasma/eviction_policy.cc +
the spill-to-external-storage path of src/ray/core_worker (objects are
spilled under memory pressure and restored transparently on get).

Design for the single-controller runtime: the shared-memory arena keeps
its silent LRU eviction (it is the memory-pressure valve that keeps puts
fast), and the DRIVER stays ahead of it — after every seal, objects are
spilled oldest-first to RAY_TPU_SPILL_DIR once the arena passes a
watermark, so by the time the LRU evicts an object its bytes already
live on disk. A get() that finds the arena copy gone falls back to the
spill file via ObjectLocation.spill_path. Spill files are deleted when
the object is freed.

Window: an object sealed by a worker is spill-protected only once the
driver processes the seal; a burst larger than (capacity - watermark)
between those two points can still evict it unspilled. The watermark
(default 60% of capacity) sizes that safety margin.
"""
from __future__ import annotations

import os
from typing import Optional

from ..util import knobs


def spill_threshold() -> float:
    return knobs.get_float("RAY_TPU_SPILL_THRESHOLD")


class SpillManager:
    """Driver-side: copies sealed local objects to disk oldest-first when
    the arena crosses the watermark. Mutates ObjectLocation.spill_path in
    place so every later reply carrying the loc advertises the copy."""

    def __init__(self, store, spill_dir: str, node_id: Optional[str]):
        import threading  # noqa: PLC0415
        self.store = store
        self.spill_dir = spill_dir
        self.node_id = node_id
        os.makedirs(spill_dir, exist_ok=True)
        # Insertion-ordered oid -> loc of live, unspilled local objects.
        # Freed objects are pruned (on_free) and duplicate seals (driver
        # puts register both synchronously and via the dispatcher) dedupe
        # on the oid key, so this tracks exactly the live set.
        self._tracked: "dict[str, object]" = {}
        # Called from both the dispatcher (worker seals) and API threads
        # (driver puts register synchronously so a burst of puts can't
        # evict an object the dispatcher hasn't seen yet).
        self._lock = threading.Lock()

    def on_seal(self, oid: str, loc) -> None:
        if loc is None or loc.kind not in ("shm", "native"):
            return
        if (loc.node_id or self.node_id) != self.node_id:
            return  # remote nodes spill on their own host
        with self._lock:
            if oid not in self._tracked:
                self._tracked[oid] = loc
            self._spill_locked()

    def _spill_locked(self) -> None:
        cap = getattr(self.store, "capacity", 0) or 0
        if cap <= 0:
            return
        limit = cap * spill_threshold()
        while self.store.used_bytes() > limit and self._tracked:
            oid = next(iter(self._tracked))        # oldest live object
            loc = self._tracked.pop(oid)
            if loc.spill_path is not None:
                continue
            try:
                data = self.store.get_bytes(loc)
            except Exception:
                continue  # already evicted: nothing left to protect
            path = os.path.join(self.spill_dir, f"{oid}.bin")
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
            loc.spill_path = path
            try:
                from ..util import events as events_mod  # noqa: PLC0415
                events_mod.emit("object.spill", object_id=oid,
                                node_id=self.node_id,
                                size=len(data), path=path)
            except Exception:
                pass
            # Drop the arena copy: the spill file is now authoritative and
            # the freed space is what keeps the next puts from evicting
            # not-yet-spilled objects.
            try:
                self.store.delete_segment(loc.name, loc.size)
            except Exception:
                pass

    def on_free(self, loc, oid: Optional[str] = None) -> None:
        if oid is not None:
            with self._lock:
                self._tracked.pop(oid, None)
        if (getattr(loc, "node_id", None) or self.node_id) != self.node_id:
            return  # remote spill files are the remote agent's to delete
        path = getattr(loc, "spill_path", None)
        if path:
            try:
                os.remove(path)
            except OSError:
                pass


def put_value_or_spill(store, oid: str, value):
    """store.put_value with a spill fallback: when the arena is full and
    nothing is evictable, the new object goes straight to this node's
    spill dir instead of failing the put. Used by workers and the driver
    alike (env RAY_TPU_SPILL_DIR names the node's dir)."""
    from ..exceptions import ObjectStoreFullError  # noqa: PLC0415
    try:
        return store.put_value(oid, value)
    except ObjectStoreFullError:
        spill_dir = knobs.get_raw("RAY_TPU_SPILL_DIR")
        if not spill_dir:
            raise
        from . import serialization  # noqa: PLC0415
        from .object_store import (ObjectLocation,  # noqa: PLC0415
                                   current_node_id)
        data = serialization.pack(value)
        os.makedirs(spill_dir, exist_ok=True)
        path = os.path.join(spill_dir, f"{oid}.bin")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
        return ObjectLocation(kind="spill", size=len(data), name=path,
                              node_id=current_node_id(), spill_path=path)
