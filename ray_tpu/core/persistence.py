"""Driver control-plane persistence: write-ahead log + snapshots.

Reference parity: the fault-tolerant GCS the Ray paper makes the
centerpiece of its architecture (gcs_server backed by a replicated
store; here src/ray/gcs/gcs_server/store_client with a Redis/memory
backend). In the single-controller runtime the driver process IS the
GCS, so a driver crash used to destroy every table. This module makes
the control plane durable under a state dir (``RAY_TPU_STATE_DIR``):

* every table mutation appends one WAL record (object seal/free, actor
  create/state/checkpoint, node register/death, lineage retain/evict,
  internal-KV put/del),
* a periodic snapshot (atomic tmp+rename) bounds replay time and
  rotates the WAL,
* ``load()`` rebuilds the tables from snapshot + WAL for
  ``ray_tpu.init(resume=True)``, stopping cleanly at a torn tail
  (a record half-written when the driver died).

Layout of the state dir::

    MANIFEST.json      # incarnation, active snapshot/wal names, listen
    snapshot-<n>.bin   # pickled table snapshot (atomic rename)
    wal-<n>.log        # records since snapshot <n> (crc32-framed)

Record framing: ``<u32 len><u32 crc32(payload)><payload>`` where the
payload is a pickled tuple ``(kind, ...)`` (plain pickle on the hot
path, cloudpickle for records only it can serialize). Replay verifies
length and CRC and stops at the first incomplete/corrupt record —
everything before the tear is recovered, nothing after it is trusted;
an intact-but-undeserializable record is skipped, not a tear.

The WAL is flushed (not fsynced) per record by default: a driver
SIGKILL loses nothing, only a whole-host power loss can drop the OS
buffer tail. ``RAY_TPU_WAL_FSYNC=1`` forces fsync per append for the
paranoid-durability case.
"""
from __future__ import annotations

import dataclasses
import json
import os
import re
import struct
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import pickle

import cloudpickle

from ..util import knobs


# Record kinds that can carry USER objects (actor constructor args in
# the create spec, by-value task args in lineage specs): these must use
# cloudpickle — plain pickle would serialize a driver-script class
# instance BY REFERENCE, which dumps fine here but fails to resolve in
# the resumed process's different __main__ (the record would then be
# skipped at replay and its entry silently lost).
_USER_CONTENT_KINDS = frozenset({"acreate", "lret"})


def _dumps(rec: tuple) -> bytes:
    """Plain pickle for framework-pure records (2.7x cheaper on the
    dispatcher hot path — object seals dominate), cloudpickle whenever
    user content may be present (and as the fallback)."""
    if rec[0] not in _USER_CONTENT_KINDS:
        try:
            return pickle.dumps(rec, protocol=5)
        except Exception:
            pass
    return cloudpickle.dumps(rec, protocol=5)


_FRAME = struct.Struct("<II")   # (payload length, crc32)
MANIFEST = "MANIFEST.json"
_GEN_RE = re.compile(r"^(?:snapshot|wal)-(\d+)\.(?:bin|log)$")


def _max_generation(state_dir: str) -> int:
    """Highest snapshot/WAL generation number present on disk. A new
    life must start PAST every leftover file: opening a prior life's
    wal-<n>.log in append mode would mix two lives' records, and a
    same-named snapshot would shadow the one the manifest names."""
    mx = 0
    try:
        names = os.listdir(state_dir)
    except OSError:
        return 0
    for name in names:
        m = _GEN_RE.match(name)
        if m:
            mx = max(mx, int(m.group(1)))
    return mx


def default_state_dir() -> Optional[str]:
    return knobs.get_raw("RAY_TPU_STATE_DIR")


@dataclasses.dataclass
class RecoveredState:
    """Control-plane tables rebuilt from snapshot + WAL replay."""
    objects: Dict[str, Any] = dataclasses.field(default_factory=dict)
    actors: Dict[str, Any] = dataclasses.field(default_factory=dict)
    checkpoints: Dict[str, bytes] = dataclasses.field(
        default_factory=dict)
    named_actors: Dict[Tuple[str, str], str] = dataclasses.field(
        default_factory=dict)
    nodes: Dict[str, dict] = dataclasses.field(default_factory=dict)
    lineage: Dict[str, Any] = dataclasses.field(default_factory=dict)
    kv: Dict[str, bytes] = dataclasses.field(default_factory=dict)
    # manifest metadata
    incarnation: int = 0
    job_id: str = ""
    node_id: str = ""                 # the DEAD driver's node id
    listen: Optional[str] = None      # bound control address to re-bind
    clean: bool = False               # graceful shutdown wrote this
    snapshot_ts: float = 0.0
    # replay forensics
    replayed_records: int = 0
    torn_tail: bool = False


def _apply(st: RecoveredState, rec: tuple) -> None:
    """Apply one WAL record to the recovered tables. Snapshot load and
    WAL replay share this single definition of record semantics."""
    kind = rec[0]
    if kind == "oseal":
        e = rec[1]
        st.objects[e.object_id] = e
    elif kind == "ofree":
        st.objects.pop(rec[1], None)
    elif kind == "acreate":
        ae = rec[1]
        st.actors[ae.actor_id] = ae
        if ae.name and ae.state != "DEAD":
            st.named_actors[(ae.namespace, ae.name)] = ae.actor_id
    elif kind == "astate":
        aid, state, cause, num_restarts = rec[1:5]
        ae = st.actors.get(aid)
        if ae is not None:
            ae.state = state
            if cause:
                ae.death_cause = cause
            ae.num_restarts = num_restarts
            if state == "DEAD":
                st.checkpoints.pop(aid, None)
    elif kind == "ackpt":
        st.checkpoints[rec[1]] = rec[2]
    elif kind == "nreg":
        info = dict(rec[1])
        info["alive"] = True
        st.nodes[info["node_id"]] = info
    elif kind == "ndeath":
        n = st.nodes.get(rec[1])
        if n is not None:
            n["alive"] = False
    elif kind == "lret":
        st.lineage[rec[1]] = rec[2]
        for oid in getattr(rec[2], "return_ids", ()):
            e = st.objects.get(oid)
            if e is not None:
                e.lineage_evicted = False
    elif kind == "levict":
        spec = st.lineage.pop(rec[1], None)
        for oid in getattr(spec, "return_ids", ()):
            e = st.objects.get(oid)
            if e is not None:
                e.lineage_evicted = True
    elif kind == "kvput":
        st.kv[rec[1]] = rec[2]
    elif kind == "kvdel":
        key, by_prefix = rec[1], rec[2]
        if by_prefix:
            for k in [k for k in st.kv if k.startswith(key)]:
                del st.kv[k]
        else:
            st.kv.pop(key, None)
    # unknown kinds are skipped: an older driver can replay a newer
    # dir's known prefix instead of refusing to start


def replay_wal(path: str) -> Tuple[List[tuple], bool, int]:
    """Read records from a WAL file. Returns (records, torn, bytes_read
    of VALID prefix). Stops cleanly at the first torn/corrupt record —
    a partial header, a short payload, or a CRC mismatch ends the
    valid prefix (crash-consistency: the tail record may have been
    half-written when the driver died). A record whose framing+CRC is
    INTACT but whose payload won't deserialize (e.g. a by-reference
    pickle of a driver-script type, or version drift) is SKIPPED, not
    treated as a tear: one unreadable record degrades one entry, it
    must not silently truncate everything after it."""
    records: List[tuple] = []
    torn = False
    valid_bytes = 0
    try:
        f = open(path, "rb")
    except OSError:
        return records, torn, valid_bytes
    with f:
        while True:
            hdr = f.read(_FRAME.size)
            if not hdr:
                break                       # clean EOF
            if len(hdr) < _FRAME.size:
                torn = True
                break
            length, crc = _FRAME.unpack(hdr)
            payload = f.read(length)
            if len(payload) < length or \
                    zlib.crc32(payload) & 0xFFFFFFFF != crc:
                torn = True
                break
            try:
                records.append(pickle.loads(payload))
            except Exception:
                pass                        # intact frame, skip record
            valid_bytes += _FRAME.size + length
    return records, torn, valid_bytes


def load(state_dir: str) -> Optional[RecoveredState]:
    """Rebuild the control-plane tables from `state_dir`; None when the
    dir holds no manifest (nothing to resume)."""
    mpath = os.path.join(state_dir, MANIFEST)
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return None
    st = RecoveredState(
        incarnation=int(manifest.get("incarnation", 0)),
        job_id=manifest.get("job_id", ""),
        node_id=manifest.get("node_id", ""),
        listen=manifest.get("listen"),
        clean=bool(manifest.get("clean", False)),
        snapshot_ts=float(manifest.get("snapshot_ts", 0.0)))
    snap = manifest.get("snapshot")
    if snap:
        try:
            with open(os.path.join(state_dir, snap), "rb") as f:
                tables = pickle.loads(f.read())
            st.objects = tables.get("objects", {})
            st.actors = tables.get("actors", {})
            st.checkpoints = tables.get("checkpoints", {})
            st.named_actors = tables.get("named_actors", {})
            st.nodes = tables.get("nodes", {})
            st.lineage = tables.get("lineage", {})
            st.kv = tables.get("kv", {})
        except Exception:  # noqa: BLE001
            # a missing/corrupt snapshot falls back to pure WAL replay
            # of whatever the manifest's wal still holds
            pass
    wal = manifest.get("wal")
    if wal:
        records, torn, _ = replay_wal(os.path.join(state_dir, wal))
        for rec in records:
            _apply(st, rec)
        st.replayed_records = len(records)
        st.torn_tail = torn
    return st


def wipe(state_dir: str) -> bool:
    """Remove prior persisted state from `state_dir` (fresh `init()`
    over a stale dir). Only this module's files are touched; returns
    True when anything was removed."""
    removed = False
    try:
        names = os.listdir(state_dir)
    except OSError:
        return False
    for name in names:
        if name == MANIFEST or name.startswith(("snapshot-", "wal-")):
            try:
                os.remove(os.path.join(state_dir, name))
                removed = True
            except OSError:
                pass
    return removed


class GCSPersistence:
    """The driver's WAL writer + snapshotter. All append_* methods are
    cheap no-raise calls (telemetry-grade: a persistence failure must
    not take down the dispatcher); `maybe_snapshot` is driven from the
    dispatcher tick."""

    def __init__(self, state_dir: str, *, incarnation: int = 0,
                 job_id: str = "", node_id: str = "",
                 listen: Optional[str] = None, resuming: bool = False):
        self.state_dir = state_dir
        self.incarnation = incarnation
        self.job_id = job_id
        self.node_id = node_id
        self.listen = listen
        self._lock = threading.Lock()
        self._fsync = knobs.get_bool("RAY_TPU_WAL_FSYNC")
        self._interval = knobs.get_float(
            "RAY_TPU_GCS_SNAPSHOT_INTERVAL_S")
        self._wal_cap = knobs.get_int("RAY_TPU_GCS_SNAPSHOT_WAL_BYTES")
        os.makedirs(state_dir, exist_ok=True)
        # counters for the state API / CLI
        self.records_appended = 0
        self.append_seconds = 0.0      # cumulative wall time in _append
        self.wal_bytes = 0
        self.snapshots_taken = 0
        self.last_snapshot_ts = time.time()
        self.replayed_records = 0
        self.torn_tail_recovered = False
        # generation counter: strictly past every file on disk, so a
        # resumed life can never append into (or shadow) a file the
        # crashed life wrote
        self._seq = _max_generation(state_dir) + 1
        self._snap_name: Optional[str] = None
        self._wal_name = f"wal-{self._seq:06d}.log"
        self._wal = open(os.path.join(state_dir, self._wal_name), "ab")
        if resuming:
            # DEFER the manifest swap: the crashed life's manifest must
            # stay authoritative until the restored tables are safely
            # snapshotted (runtime calls snapshot() right after
            # restore) — otherwise a second crash inside the snapshot
            # interval would resume from an empty generation and lose
            # everything the first life persisted
            pass
        else:
            self._write_manifest(clean=False)

    # ---- manifest ---------------------------------------------------------
    def _write_manifest(self, clean: bool) -> None:
        manifest = {
            "version": 1,
            "incarnation": self.incarnation,
            "job_id": self.job_id,
            "node_id": self.node_id,
            "listen": self.listen,
            "snapshot": self._snap_name,
            "wal": self._wal_name,
            "snapshot_ts": self.last_snapshot_ts,
            "clean": clean,
        }
        tmp = os.path.join(self.state_dir, MANIFEST + ".tmp")
        with open(tmp, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.state_dir, MANIFEST))

    # ---- WAL appends ------------------------------------------------------
    def _append(self, rec: tuple) -> None:
        t0 = time.perf_counter()
        try:
            payload = _dumps(rec)
            frame = _FRAME.pack(len(payload),
                                zlib.crc32(payload) & 0xFFFFFFFF)
            with self._lock:
                self._wal.write(frame)
                self._wal.write(payload)
                self._wal.flush()
                if self._fsync:
                    os.fsync(self._wal.fileno())
                self.records_appended += 1
                self.wal_bytes += len(frame) + len(payload)
        except Exception:
            pass  # persistence must never break the control plane
        self.append_seconds += time.perf_counter() - t0

    def object_seal(self, entry) -> None:
        self._append(("oseal", entry))

    def object_free(self, oid: str) -> None:
        self._append(("ofree", oid))

    def actor_create(self, entry) -> None:
        self._append(("acreate", entry))

    def actor_state(self, entry) -> None:
        self._append(("astate", entry.actor_id, entry.state,
                      entry.death_cause, entry.num_restarts))

    def actor_ckpt(self, aid: str, blob: bytes) -> None:
        self._append(("ackpt", aid, blob))

    def node_register(self, info: dict) -> None:
        self._append(("nreg", info))

    def node_death(self, nid: str) -> None:
        self._append(("ndeath", nid))

    def lineage_retain(self, task_id: str, spec) -> None:
        self._append(("lret", task_id, spec))

    def lineage_evict(self, task_id: str) -> None:
        self._append(("levict", task_id))

    def kv_put(self, key: str, value) -> None:
        self._append(("kvput", key, value))

    def kv_del(self, key: str, by_prefix: bool) -> None:
        self._append(("kvdel", key, by_prefix))

    def append(self, rec: tuple) -> None:
        """Append one pre-built record (the runtime routes API-thread
        mutations — internal KV — through the dispatcher to here, so
        every append is serialized with snapshot rotation and a racing
        record can never land in a WAL generation about to be
        deleted)."""
        self._append(rec)

    # ---- snapshots --------------------------------------------------------
    def maybe_snapshot(self, tables_fn) -> bool:
        """Take a snapshot when the interval elapsed or the WAL grew
        past the rotation cap. `tables_fn` builds the table dict (runs
        on the caller's — dispatcher's — thread, so the tables are
        consistent without locks)."""
        if self._interval <= 0:
            return False
        due = (time.time() - self.last_snapshot_ts >= self._interval
               or self.wal_bytes >= self._wal_cap)
        if not due:
            return False
        return self.snapshot(tables_fn)

    def snapshot(self, tables_fn) -> bool:
        """Write snapshot-<n+1>, rotate to wal-<n+1>, swap the manifest
        atomically, then delete the superseded generation. A crash at
        any point leaves the manifest naming one intact
        (snapshot, wal) pair."""
        try:
            # cloudpickle: the tables hold actor create specs and
            # lineage specs whose args may be driver-script objects
            blob = cloudpickle.dumps(tables_fn(), protocol=5)
        except Exception:
            return False
        try:
            with self._lock:
                self._seq += 1
                snap_name = f"snapshot-{self._seq:06d}.bin"
                tmp = os.path.join(self.state_dir, snap_name + ".tmp")
                with open(tmp, "wb") as f:
                    f.write(blob)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp,
                           os.path.join(self.state_dir, snap_name))
                wal_name = f"wal-{self._seq:06d}.log"
                new_wal = open(
                    os.path.join(self.state_dir, wal_name), "ab")
                self._wal.close()
                self._wal = new_wal
                self._snap_name, self._wal_name = snap_name, wal_name
                self.wal_bytes = 0
                self.last_snapshot_ts = time.time()
                self.snapshots_taken += 1
            self._write_manifest(clean=False)
            # the manifest now names the new pair: every OTHER
            # generation file (the rotated-out pair, and any leftovers
            # from the crashed life a resume replayed) is garbage
            keep = {snap_name, wal_name}
            try:
                for name in os.listdir(self.state_dir):
                    if _GEN_RE.match(name) and name not in keep:
                        try:
                            os.remove(
                                os.path.join(self.state_dir, name))
                        except OSError:
                            pass
            except OSError:
                pass
            return True
        except Exception:
            return False

    def close(self, tables_fn=None) -> None:
        """Graceful shutdown: final snapshot (planned restarts replay
        nothing) and a manifest marked clean."""
        try:
            if tables_fn is not None:
                self.snapshot(tables_fn)
            self._write_manifest(clean=True)
            with self._lock:
                self._wal.close()
        except Exception:
            pass

    # ---- introspection ----------------------------------------------------
    def stats(self) -> dict:
        return {
            "state_dir": self.state_dir,
            "driver_incarnation": self.incarnation,
            "wal_records": self.records_appended,
            "wal_bytes": self.wal_bytes,
            "wal_append_seconds": round(self.append_seconds, 6),
            "snapshots_taken": self.snapshots_taken,
            "last_snapshot_age_s": round(
                time.time() - self.last_snapshot_ts, 3),
            "replayed_records": self.replayed_records,
            "torn_tail_recovered": self.torn_tail_recovered,
            "fsync": self._fsync,
        }
