"""Peer-to-peer object transfer plane: direct node-to-node pulls.

Reference parity: src/ray/object_manager/ — the push/pull protocol that
moves sealed plasma objects directly between nodes, with the GCS acting
only as a location directory. In ray_tpu every cross-node payload used
to relay through the driver's control connections, making the
single-controller socket the cluster's bandwidth ceiling; this module
gives each node agent (and the driver) a dedicated data-plane listener
so the HOLDER of an object streams its bytes straight to the REQUESTER:

    requester                driver                holder
        | -- locate(oid) ------> |                    |
        | <----- [(loc, addr)] - |                    |
        | ------------- pull(oid, loc) over TCP ----> |
        | <=== chunk / ack / chunk / ack (data) ===== |

The driver only brokers locations (GCS object table + per-node transfer
addresses); object bytes never touch its sockets except on the
instrumented relay FALLBACK path (ray_tpu_transfer_relay_bytes_total).

Protocol (core/protocol.py raw frames, no pickling on the data path):
    requester -> holder:  pickled ("pull", oid, loc, chunk_size)
    holder -> requester:  pickled ("ok", total_size) | ("err", repr)
    then per chunk:       raw frame (u32 length + bytes), requester
                          answers each with a 1-byte ack before the
                          next chunk is sent (flow control + liveness:
                          a dead requester stalls the holder's sender
                          within one chunk, not one object)

Failure handling: per-pull socket timeouts, retry with exponential
backoff rotating across ALTERNATE holders (ObjectEntry.copies), a
location re-resolve between rounds (stale directory entries after
spill/eviction/node death), and per-node concurrent-pull dedup — one
in-flight pull per object, later requesters block on the first and then
read the local copy.
"""
from __future__ import annotations

import os
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..util import knobs
from .protocol import (ConnectionClosed, read_exact, read_frame, read_obj,
                       tcp_listener, write_frame, write_obj)

ACK = b"\x01"


def chunk_size_default() -> int:
    return knobs.get_int("RAY_TPU_TRANSFER_CHUNK")


def _retries() -> int:
    return knobs.get_int("RAY_TPU_TRANSFER_RETRIES")


def _timeout_s() -> float:
    return knobs.get_float("RAY_TPU_TRANSFER_TIMEOUT_S")


def _backoff_s() -> float:
    return knobs.get_float("RAY_TPU_TRANSFER_BACKOFF_S")


def _deadline_s() -> float:
    """Total wall-clock cap across ALL pull retry rounds: a dead holder
    must not stall a reader for the full retry budget before lineage
    reconstruction can kick in (0 disables the cap)."""
    return knobs.get_float("RAY_TPU_PULL_DEADLINE_S")


def _mcat():
    from ..util import metrics_catalog  # noqa: PLC0415
    return metrics_catalog


def _record(fn: Callable[[Any], None]) -> None:
    """Run a metrics mutation; telemetry must never fail a transfer."""
    try:
        fn(_mcat())
    except Exception:
        pass


class TransferError(Exception):
    """A pull failed against every candidate holder."""


def get_buffer(store, loc):
    """The packed payload of `loc` as a buffer, zero-copy when the
    backing store supports it (shm segment / pinned native-arena view —
    the holder then streams straight out of shared memory), falling
    back to a bytes copy (inline / spill / evicted-with-spill-copy).
    Raises (e.g. ObjectLostError) when the payload is gone — the
    server forwards that as an "err" reply so the requester can retry
    against a fresh directory entry."""
    fn = getattr(store, "get_buffer", None)
    if fn is not None:
        return fn(loc)
    return store.get_bytes(loc)


# ---------------------------------------------------------------------------
# holder side


class TransferServer:
    """Per-node data-plane listener serving pull requests out of the
    local object store. One thread per connection; Connection-free (raw
    frames) so a multi-GB stream never pays pickling."""

    def __init__(self, store, host: str = "0.0.0.0", port: int = 0,
                 advertise_host: Optional[str] = None,
                 on_chunk: Optional[Callable[[int], None]] = None,
                 spill_dirs: Optional[List[str]] = None):
        self.store = store
        # spill reads are confined to this node's own spill directory:
        # the requester's loc comes off the wire, and an unvalidated
        # spill_path would be an arbitrary-file-read primitive
        dirs = spill_dirs if spill_dirs is not None else \
            [d for d in (knobs.get_raw("RAY_TPU_SPILL_DIR"),) if d]
        self._spill_dirs = [os.path.realpath(d) for d in dirs]
        self._listener = tcp_listener(host, port)
        lh, lp = self._listener.getsockname()[:2]
        if advertise_host is None and lh in ("0.0.0.0", "::"):
            from ..util.netutil import routable_ip  # noqa: PLC0415
            advertise_host = routable_ip()
        self.address = f"{advertise_host or lh}:{lp}"
        self.stats = {"serves": 0, "bytes": 0, "chunks": 0, "errors": 0}
        # test hook: called with the chunk offset before each chunk send
        # (failure-injection: a holder dying mid-stream)
        self._on_chunk = on_chunk
        self._closed = threading.Event()
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="rtpu-xfer-server").start()

    def _spill_path_allowed(self, loc) -> bool:
        """Wire-supplied locations may only name spill files under this
        node's own spill dirs (shm/arena names can't traverse; file
        paths can)."""
        paths = [p for p in (getattr(loc, "spill_path", None),
                             loc.name if getattr(loc, "kind", None)
                             == "spill" else None) if p]
        for p in paths:
            rp = os.path.realpath(p)
            if not any(rp == d or rp.startswith(d + os.sep)
                       for d in self._spill_dirs):
                return False
        return True

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                sock, _ = self._listener.accept()
            except OSError:
                if self._closed.is_set():
                    return
                # transient accept failure (e.g. EMFILE under load) must
                # not kill the node's whole transfer plane — back off and
                # keep serving
                self.stats["errors"] += 1
                time.sleep(0.05)
                continue
            threading.Thread(target=self._serve_conn, args=(sock,),
                             daemon=True).start()

    def _serve_conn(self, sock) -> None:
        try:
            sock.settimeout(_timeout_s())
            req = read_obj(sock)
            if not (isinstance(req, tuple) and req[0] == "pull"):
                write_obj(sock, ("err", f"bad request {req!r}"))
                return
            _, oid, loc, chunk = req
            if not self._spill_path_allowed(loc):
                write_obj(sock, ("err", "spill path outside this "
                                        "node's spill directory"))
                return
            try:
                buf = get_buffer(self.store, loc)
            except BaseException as e:  # noqa: BLE001
                self.stats["errors"] += 1
                write_obj(sock, ("err", repr(e)))
                return
            view = memoryview(buf)
            total = view.nbytes
            write_obj(sock, ("ok", total))
            sent = 0
            while sent < total:
                if self._on_chunk is not None:
                    self._on_chunk(sent)
                n = min(chunk, total - sent)
                write_frame(sock, view[sent:sent + n])
                if read_exact(sock, 1) != ACK:
                    raise ConnectionClosed("bad chunk ack")
                sent += n
                self.stats["chunks"] += 1
                _record(lambda m, n=n: (
                    m.get("ray_tpu_transfer_chunks_total").inc(
                        tags={"dir": "out"})))
            self.stats["serves"] += 1
            self.stats["bytes"] += total
            _record(lambda m, total=total: m.get(
                "ray_tpu_transfer_bytes_served_total").inc(total))
        except (ConnectionClosed, OSError):
            self.stats["errors"] += 1
        except BaseException:  # noqa: BLE001
            self.stats["errors"] += 1
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def close(self) -> None:
        self._closed.set()
        try:
            self._listener.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# requester side


def pull_bytes(address: str, oid: str, loc, *,
               chunk_size: Optional[int] = None,
               timeout: Optional[float] = None) -> bytearray:
    """One pull attempt against one holder: returns the packed payload
    (a bytearray — every consumer takes a buffer). Raises TransferError
    / ConnectionClosed / OSError on any failure — retry policy lives in
    PullManager."""
    import socket  # noqa: PLC0415
    chunk_size = chunk_size or chunk_size_default()
    timeout = timeout or _timeout_s()
    host, _, port = address.rpartition(":")
    sock = socket.create_connection((host, int(port)), timeout=timeout)
    try:
        sock.settimeout(timeout)
        write_obj(sock, ("pull", oid, loc, chunk_size))
        hdr = read_obj(sock)
        if hdr[0] != "ok":
            raise TransferError(
                f"holder {address} refused pull of {oid}: {hdr[1]}")
        total = hdr[1]
        buf = bytearray(total)
        got = 0
        while got < total:
            chunk = read_frame(sock, max_len=chunk_size + 1024)
            buf[got:got + len(chunk)] = chunk
            got += len(chunk)
            sock.sendall(ACK)
            _record(lambda m: m.get(
                "ray_tpu_transfer_chunks_total").inc(tags={"dir": "in"}))
        # the bytearray goes straight to put_packed/unpack — a bytes()
        # copy here would double the memcpy on the bandwidth hot path
        return buf
    finally:
        try:
            sock.close()
        except OSError:
            pass


class _Inflight:
    __slots__ = ("event", "result", "error")

    def __init__(self):
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None


class PullManager:
    """Per-node puller: resolves candidates to a local copy with retry,
    alternate-holder failover, and concurrent-pull dedup.

    candidates: [(ObjectLocation, transfer_address|None), ...] — the
    driver-brokered location directory entries for the object, primary
    location first. locate(oid) (optional) re-resolves fresh candidates
    between retry rounds, closing the stale-directory window after a
    spill or holder death."""

    def __init__(self, store, node_id: Optional[str] = None,
                 locate: Optional[Callable[[str], list]] = None,
                 span_sink: Optional[Callable[[dict], None]] = None):
        self.store = store
        self.node_id = node_id
        self._locate = locate
        self._span_sink = span_sink
        self._lock = threading.Lock()
        self._inflight: Dict[str, _Inflight] = {}
        self.stats = {"pulls": 0, "dedup_waits": 0, "local_hits": 0,
                      "retries": 0, "failures": 0, "bytes": 0}

    # -- public ------------------------------------------------------------
    def pull(self, oid: str, candidates: List[Tuple[Any, Optional[str]]],
             *, chunk_size: Optional[int] = None):
        """Make `oid`'s payload local; returns its LOCAL ObjectLocation
        (an existing local copy, or a fresh put_packed of pulled bytes).
        Raises TransferError when every candidate/retry is exhausted."""
        local = self._local_candidate(candidates)
        if local is not None:
            self.stats["local_hits"] += 1
            _record(lambda m: m.get("ray_tpu_transfer_pulls_total").inc(
                tags={"result": "local"}))
            return local
        with self._lock:
            fl = self._inflight.get(oid)
            if fl is None:
                fl = self._inflight[oid] = _Inflight()
                winner = True
            else:
                winner = False
        if not winner:
            # one in-flight pull per object per node: wait for the
            # winner, then serve from its local copy
            self.stats["dedup_waits"] += 1
            _record(lambda m: m.get("ray_tpu_transfer_pulls_total").inc(
                tags={"result": "dedup"}))
            fl.event.wait()
            if fl.error is not None:
                raise fl.error
            return fl.result
        try:
            loc = self._pull_with_retry(oid, candidates, chunk_size)
            fl.result = loc
            return loc
        except BaseException as e:
            fl.error = e
            raise
        finally:
            with self._lock:
                self._inflight.pop(oid, None)
            fl.event.set()

    # -- internals ---------------------------------------------------------
    def _local_candidate(self, candidates):
        for loc, _addr in candidates or ():
            if getattr(loc, "kind", None) == "inline":
                return loc
            if getattr(loc, "node_id", None) == self.node_id \
                    and self.node_id is not None:
                return loc
        return None

    def _pull_with_retry(self, oid, candidates, chunk_size):
        last_err: Optional[BaseException] = None
        t0 = time.monotonic()
        cap = _deadline_s()
        deadline = t0 + cap if cap > 0 else float("inf")
        rounds = 0
        for attempt in range(_retries() + 1):
            if attempt > 0:
                if time.monotonic() >= deadline:
                    break  # total-deadline cap: stop retrying early
                self.stats["retries"] += 1
                _record(lambda m: m.get(
                    "ray_tpu_transfer_pull_retries_total").inc())
                # jittered exponential backoff (retrying peers must not
                # thundering-herd one recovering holder), clipped so the
                # sleep never overruns the deadline
                delay = _backoff_s() * (2 ** (attempt - 1)) \
                    * (0.5 + random.random())
                time.sleep(min(delay,
                               max(0.0, deadline - time.monotonic())))
                if self._locate is not None:
                    try:
                        fresh = self._locate(oid)
                    except Exception as e:  # directory unreachable
                        fresh = None
                        last_err = e
                    if fresh is not None:
                        candidates = fresh
                        local = self._local_candidate(candidates)
                        if local is not None:
                            self.stats["local_hits"] += 1
                            return local
            rounds = attempt + 1
            for loc, addr in candidates or ():
                if addr is None:
                    continue
                # enforce the deadline WITHIN a round too, and clip the
                # socket timeout to the remaining budget — several
                # black-holed holders in one round must not stack full
                # socket timeouts past the cap
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                start = time.monotonic()
                try:
                    data = pull_bytes(
                        addr, oid, loc, chunk_size=chunk_size,
                        timeout=min(_timeout_s(), max(0.5, remaining))
                        if cap > 0 else None)
                except BaseException as e:  # noqa: BLE001
                    last_err = e
                    continue
                newloc = self._host_locally(oid, data)
                dt = time.monotonic() - start
                self.stats["pulls"] += 1
                self.stats["bytes"] += len(data)
                _record(lambda m, n=len(data), dt=dt: (
                    m.get("ray_tpu_transfer_bytes_pulled_total").inc(n),
                    m.get("ray_tpu_transfer_pulls_total").inc(
                        tags={"result": "ok"}),
                    m.get("ray_tpu_transfer_pull_latency_s").observe(dt)))
                self._span(oid, addr, len(data), start, "ok")
                return newloc
        self.stats["failures"] += 1
        _record(lambda m: m.get("ray_tpu_transfer_pulls_total").inc(
            tags={"result": "error"}))
        self._span(oid, None, 0, t0, "error")
        raise TransferError(
            f"pull of {oid} failed against every holder "
            f"({len(candidates or ())} candidates, {rounds} rounds, "
            f"{time.monotonic() - t0:.1f}s elapsed, deadline "
            f"{cap:.0f}s): {last_err!r}")

    def _host_locally(self, oid: str, data):
        """Re-host pulled bytes in the local store so sibling readers on
        this node get zero-copy shm. A full store fails the pull (the
        caller's relay fallback then moves the bytes over the counted
        path) — returning a multi-MB inline location here would smuggle
        the payload through control-plane messages and pin it in the
        directory forever. Tiny payloads stay inline (put_packed's own
        threshold)."""
        from .object_store import INLINE_MAX  # noqa: PLC0415
        try:
            loc = self.store.put_packed(oid, bytes(data)
                                        if len(data) <= INLINE_MAX
                                        else data)
        except BaseException as e:  # noqa: BLE001
            raise TransferError(
                f"pulled {len(data)} B for {oid} but could not re-host "
                f"locally: {e!r}") from e
        if loc.node_id is None:
            # env-less processes (unit tests) still need the directory
            # to know which node this copy lives on
            loc.node_id = self.node_id
        return loc

    def _span(self, oid, addr, nbytes, start, status) -> None:
        if self._span_sink is None:
            return
        from ..util import tracing  # noqa: PLC0415
        try:
            self._span_sink({
                "trace_id": "", "span_id": tracing.new_span_id(),
                "parent_span_id": "", "task_id": "",
                "name": f"transfer.pull {oid}",
                "start": time.time() - (time.monotonic() - start),
                "end": time.time(), "status": status,
                "node_id": self.node_id, "bytes": nbytes,
                "holder": addr})
        except Exception:
            pass
