"""Node agent: joins a host to a running driver over TCP.

Reference parity: src/ray/raylet/node_manager.cc (node registration,
worker leasing) + src/ray/gcs/gcs_server/gcs_node_manager.cc (node table)
— collapsed to the single-controller model: the agent owns this host's
shared-memory object store and spawns workers on the driver's request;
the workers connect straight back to the driver's TCP listener, so the
driver keeps one scheduler for the whole cluster ("multi-host pods are a
transport, not a rewrite").

Run on each additional host:
    python -m ray_tpu.core.node tcp://<driver-host>:<port> \
        [--num-cpus N] [--num-tpus N] [--store-bytes B]

The driver side opens the TCP listener via
`ray_tpu.init(listen="0.0.0.0:6380")` (or RAY_TPU_LISTEN) and exposes the
bound address as `runtime.tcp_address`.
"""
from __future__ import annotations

import argparse
import collections
import os
import select
import subprocess
import sys
import tempfile
import threading
import time
import traceback
from typing import Dict, Optional

from . import resources as res_mod
from .ids import new_node_id
from .object_store import make_store
from .protocol import (Connection, ConnectionClosed, connect_address,
                       unix_listener)
from ..util import knobs

# Cross-node payloads stream in frames well under protocol.MAX_MSG so one
# huge object can never poison the connection with an oversized frame.
FETCH_CHUNK = knobs.get_int("RAY_TPU_FETCH_CHUNK")

# Host-resolvable location kinds: any worker on this node can read them
# out of the shared arena (or a spill file), so they are safe to hand to
# a sibling worker as pre-resolved dependency locations.
_HOST_KINDS = ("shm", "native", "inline", "spill")


class _AgentLease:
    """Agent-side half of one node-level bulk lease (two-level
    scheduling, docs/SCHEDULING.md): a resource shape, the local workers
    the driver assigned to it, and a FIFO of tasks to fan across them.
    Queue entries are `[spec, owner_conn_or_None, enqueue_time]` — owner
    None means the driver granted the task (completions stream back as
    `nlease_done`); a live owner conn means a local worker submitted it
    (`asubmit`) and gets the result directly (`aresult`)."""

    __slots__ = ("lid", "need", "wids", "queue", "standing",
                 "last_activity")

    def __init__(self, lid: str, need: dict, wids: set, standing: bool):
        self.lid = lid
        self.need = need
        self.wids = wids
        self.queue: collections.deque = collections.deque()
        self.standing = standing
        self.last_activity = time.monotonic()


class NodeAgent:
    def __init__(self, driver_address: str, *, num_cpus=None, num_tpus=None,
                 resources=None, store_bytes: Optional[int] = None,
                 node_id: Optional[str] = None):
        self.driver_address = driver_address
        # A pre-chosen id lets a launcher (core/autoscaler.py providers)
        # correlate "the process I started" with "the node that joined".
        self.node_id = node_id or new_node_id()
        # This host's store is its own arena: drop any inherited owner env
        # (tests run agents on the driver's host) and stamp our node id so
        # every ObjectLocation written here names this node.
        os.environ.pop("RAY_TPU_ARENA_NAME", None)
        os.environ["RAY_TPU_NODE_ID"] = self.node_id
        cap = store_bytes \
            or knobs.get_int("RAY_TPU_STORE_BYTES", default=2 << 30)
        self.store = make_store(capacity_bytes=cap, is_owner=True)

        node_res = res_mod.detect_node_resources(num_cpus, num_tpus)
        if resources:
            node_res.update(resources)
        self.resources = node_res
        self.labels = res_mod.detect_tpu_topology(
            int(node_res.get("TPU", 0)))
        node_type = knobs.get_raw("RAY_TPU_NODE_TYPE")
        if node_type:
            self.labels["node-type"] = node_type

        self._tmpdir = tempfile.mkdtemp(prefix="ray_tpu_node_")
        self.log_dir = os.path.join(self._tmpdir, "logs")
        os.makedirs(self.log_dir, exist_ok=True)
        # This node's workers spill put-overflow here (core/spilling.py;
        # the driver-side watermark spiller only covers the driver node).
        # Overrides any env inherited from a same-host driver in tests.
        spill_dir = os.path.join(self._tmpdir, "spill")
        os.environ["RAY_TPU_SPILL_DIR"] = spill_dir
        self.workers: Dict[str, subprocess.Popen] = {}
        self.job_id = "job-default"
        # Fetches run on threads (a multi-GB read must not head-of-line
        # block spawns/frees), bounded so they can't starve the loop.
        self._fetch_sem = threading.Semaphore(4)

        # ---- two-level scheduling: agent-local dispatch plane ----------
        # The driver grants this agent bulk leases (batches of compatible
        # tasks plus a set of local workers); the agent fans them across
        # those workers over a node-local unix socket and refills slots
        # as completions arrive, without driver round trips. Workers also
        # submit their own fan-outs here (`asubmit`) for dependency-local
        # placement. docs/SCHEDULING.md "Two-level scheduling".
        self._nlease_enabled = knobs.get_bool("RAY_TPU_NODE_LEASES")
        self._sched_lock = threading.RLock()
        self._aworkers: Dict[str, Connection] = {}     # wid -> worker conn
        # wid -> deque of (lease_id_or_"", spec, owner_conn_or_None):
        # tasks in flight per worker, FIFO. Depth >1 pipelines the
        # aexec/adone round trip so a worker never idles between
        # sub-millisecond tasks; only the head can have started (the
        # worker executes its backlog strictly in order), which is what
        # the spill accounting relies on.
        self._winflight: Dict[str, collections.deque] = {}
        self._leases: Dict[str, _AgentLease] = {}
        # worker-submitted tasks waiting for lease capacity of their shape
        self._nested_q: collections.deque = collections.deque()
        self._want_last: Dict[tuple, float] = {}
        # host-kind seal locations of recent local results, for stamping
        # pre-resolved dependency locations onto sibling dispatches
        self._oid_locs: collections.OrderedDict = collections.OrderedDict()
        self._agent_listener = None
        self.agent_addr = ""
        self._done_batch = None
        if self._nlease_enabled:
            self._start_agent_plane()

        # Peer-to-peer transfer plane (core/object_transfer.py): this
        # host serves its sealed objects directly to peer nodes, and
        # pulls remote objects into its own arena on the driver's
        # request ("pull_object") — object bytes stop transiting the
        # driver's control connections. Spans per pull buffer here and
        # ship with the metrics heartbeat.
        self._spans: list = []
        self._spans_lock = threading.Lock()
        from .object_transfer import (PullManager,  # noqa: PLC0415
                                      TransferServer)
        self.transfer_server = TransferServer(
            self.store, spill_dirs=[spill_dir])
        self.pull_manager = PullManager(
            self.store, node_id=self.node_id, locate=self._locate,
            span_sink=self._span_sink)
        # locate round-trips: rid -> (Event, box)
        self._locate_lock = threading.Lock()
        self._locate_counter = 0
        self._locate_events: Dict[int, tuple] = {}

        # Bumped on every re-registration after a lost driver connection
        # (network blip, or the driver fenced us after a heartbeat-
        # declared death): the driver fences traffic from older
        # incarnations, so a stalled-then-recovered agent can't corrupt
        # the failover that already happened.
        self.incarnation = 0
        # last acked driver incarnation (bumps when a SIGKILLed driver
        # resumes and this agent reattaches to it)
        self.driver_incarnation = 0
        self.conn = connect_address(driver_address)
        self.conn.send(("register_node", self._register_info()))
        if self._nlease_enabled:
            # Lease completions coalesce into ("batch", ...) frames on
            # the node connection, same codec + cadence discipline as the
            # worker->driver batcher (a fan-out of sub-millisecond tasks
            # costs one frame per batch, not one per completion).
            from .worker import _MsgBatcher  # noqa: PLC0415
            self._done_batch = _MsgBatcher(
                self.conn,
                max_n=knobs.get_int("RAY_TPU_BATCH_FLUSH_N"),
                window=knobs.get_float("RAY_TPU_BATCH_FLUSH_S"),
                enabled=knobs.get_bool("RAY_TPU_BATCH"))
            threading.Thread(target=self._spill_loop, daemon=True,
                             name="node-lease-spill").start()
        # Metrics plane: this agent's registry (node-local store stats,
        # any user metrics recorded here) ships delta snapshots on the
        # node connection; the driver merges them tagged with node_id.
        self._metrics_interval = knobs.get_float(
            "RAY_TPU_METRICS_INTERVAL_S")
        if self._metrics_interval > 0:
            threading.Thread(target=self._metrics_loop, daemon=True,
                             name="node-metrics").start()
        # Liveness pings for the driver's event plane: a stalled (not
        # just disconnected) agent surfaces as node.heartbeat_miss
        # before the socket-level death determination.
        self._heartbeat_interval = knobs.get_float(
            "RAY_TPU_NODE_HEARTBEAT_S")
        if self._heartbeat_interval > 0:
            threading.Thread(target=self._heartbeat_loop, daemon=True,
                             name="node-heartbeat").start()
        # Agent-side mirror of the driver's heartbeat-declared death:
        # the driver acks every heartbeat, so a healthy connection is
        # never silent longer than the heartbeat interval. Total
        # silence past RAY_TPU_DRIVER_SILENCE_S means the driver HOST
        # is gone without a FIN/RST (preemption, partition) — recv()
        # would park until the ~15min TCP retransmit timeout and this
        # host's capacity would stay lost long after the driver
        # restarts. run() treats that as a lost connection and rejoins.
        self._silence_timeout = knobs.get_float("RAY_TPU_DRIVER_SILENCE_S")
        self._last_driver_traffic = time.monotonic()
        # True while run() is parked inside conn.recv(): with the
        # select() gate that only happens when at least a frame HEADER
        # arrived, so a long park here means the driver died mid-frame
        # — the heartbeat loop then closes the conn to unblock the
        # read (the same cross-thread unblock idiom the driver's death
        # determination uses). A socket-level settimeout would be
        # simpler but caps every sendall on this SHARED conn too, and
        # the fetch path streams 64MB frames over it.
        self._in_recv = False

    def _register_info(self) -> dict:
        return {
            "node_id": self.node_id,
            "hostname": os.uname().nodename,
            "resources": dict(self.resources),
            "labels": dict(self.labels),
            "transfer_address": self.transfer_server.address,
            "incarnation": self.incarnation,
            "pid": os.getpid(),
            # capability flag: the driver only grants node-level bulk
            # leases to agents that actually run the local dispatch plane
            "node_leases": self._nlease_enabled,
        }

    def _heartbeat_loop(self) -> None:
        while True:
            time.sleep(self._heartbeat_interval)
            try:
                self.conn.send(("heartbeat", time.time()))
            except (ConnectionClosed, OSError):
                # driver connection down: run() is either reconnecting
                # (self.conn gets swapped) or exiting (daemon thread
                # dies with the process) — keep ticking either way
                continue
            except Exception:
                pass
            # mid-frame silence watchdog: run()'s select() gate cannot
            # fire while recv() is parked on a partial frame
            if (self._silence_timeout > 0 and self._in_recv
                    and time.monotonic() - self._last_driver_traffic
                    > self._silence_timeout):
                from ..util import events as events_mod  # noqa: PLC0415
                events_mod.emit_safe(
                    "sched.hang.suspected",
                    f"driver silent > {self._silence_timeout:.0f}s "
                    "mid-frame (recv parked on a partial frame); "
                    "closing the connection to enter the rejoin loop",
                    node_id=self.node_id, kind="driver_silence",
                    mid_frame=True)
                try:
                    self.conn.close()   # recv raises; run() rejoins
                except Exception:
                    pass

    def _metrics_loop(self) -> None:
        from ..util.metrics import DeltaExporter  # noqa: PLC0415
        from ..util import metrics_catalog as mcat  # noqa: PLC0415
        from ..util import events as events_mod  # noqa: PLC0415
        from ..util import waits as waits_mod  # noqa: PLC0415
        exporter = DeltaExporter()
        # Collected-but-unsent messages: collect()/drain() are
        # DESTRUCTIVE reads, so a send failure during the rejoin window
        # must re-queue them (bounded) rather than drop a blip's worth
        # of deltas and lifecycle events on the floor.
        pending: list = []
        while True:
            time.sleep(self._metrics_interval)
            try:
                mcat.get("ray_tpu_object_store_used_bytes").set(
                    float(self.store.used_bytes()))
                cap = getattr(self.store, "capacity", None)
                if cap:
                    mcat.get(
                        "ray_tpu_object_store_capacity_bytes").set(
                        float(cap))
                payload = exporter.collect()
                if payload:
                    pending.append(("metrics", payload))
                with self._spans_lock:
                    spans, self._spans = self._spans, []
                if spans:
                    pending.append(("spans", spans))
                # event-plane delta batch (anything code on this agent
                # emitted — memory pressure, engine/data events)
                evs = events_mod.drain()
                if evs:
                    pending.append(("events", evs))
                # wait-state plane: lease queues are data structures,
                # not parked threads — re-synthesize the queue heads
                # as lease-slot waits each tick, then ship the aged
                # delta (None steady-state, like the workers)
                try:
                    self._synth_lease_waits(waits_mod)
                    wts = waits_mod.collect()
                    if wts is not None:
                        pending.append(("waits", wts))
                except Exception:  # noqa: BLE001
                    pass
                # one coalesced frame per interval (compact binary
                # codec), not one frame per telemetry kind; a single
                # leftover skips the envelope
                if len(pending) > 1:
                    self.conn.send(("batch", list(pending)))
                    del pending[:]
                elif pending:
                    self.conn.send(pending[0])
                    pending.pop(0)
            except (ConnectionClosed, OSError):
                # reconnecting (or exiting) — see heartbeat loop; keep
                # the backlog bounded while the driver is away
                del pending[:-64]
                continue
            except Exception:
                pass  # telemetry must never kill the agent

    def _synth_lease_waits(self, waits_mod) -> None:
        """Each lease FIFO's parked HEAD (and the nested queue's) is a
        blocking edge: the head task waits on a local worker slot. The
        tail behind it is context, not separate edges — one record per
        queue keeps the table bounded by lease count."""
        if not waits_mod.enabled():
            return
        recs = []
        with self._sched_lock:
            for lease in self._leases.values():
                if not lease.queue:
                    continue
                spec, _owner, ts = lease.queue[0]
                recs.append(("lease-slot", lease.lid, ts,
                             {"task": getattr(spec, "task_id", ""),
                              "name": getattr(spec, "name", ""),
                              "queued": len(lease.queue)}))
            if self._nested_q:
                spec, _owner, ts = self._nested_q[0]
                recs.append(("lease-slot", "nested", ts,
                             {"task": getattr(spec, "task_id", ""),
                              "name": getattr(spec, "name", ""),
                              "queued": len(self._nested_q)}))
        waits_mod.table().replace_synth("agent:", recs)

    # ---- transfer plane ---------------------------------------------------
    def _span_sink(self, span: dict) -> None:
        with self._spans_lock:
            self._spans.append(span)

    def _locate(self, oid: str):
        """Ask the driver for fresh location-directory candidates (the
        PullManager's between-rounds re-resolve). Returns the candidate
        list, or None on timeout/disconnect."""
        with self._locate_lock:
            self._locate_counter += 1
            rid = self._locate_counter
            ev = threading.Event()
            box: dict = {}
            self._locate_events[rid] = (ev, box)
        try:
            self.conn.send(("locate", rid, oid))
        except ConnectionClosed:
            with self._locate_lock:
                self._locate_events.pop(rid, None)
            return None
        if not ev.wait(timeout=10.0):
            with self._locate_lock:
                self._locate_events.pop(rid, None)
            return None
        return box.get("candidates")

    def _serve_pull(self, rid, oid: str, candidates) -> None:
        """Run one driver-requested pull on a thread and report the
        local location back (or the failure, so the driver can fall
        back to its relay path). Bounded by the same semaphore as
        fetches — each pull buffers a whole object, so unbounded
        concurrency would be an unbounded memory spike."""
        with self._fetch_sem:
            try:
                loc = self.pull_manager.pull(oid, candidates)
                self.conn.send(("pulled", rid, oid, loc, None))
            except ConnectionClosed:
                pass
            except BaseException as e:  # noqa: BLE001
                try:
                    self.conn.send(("pulled", rid, oid, None, repr(e)))
                except ConnectionClosed:
                    pass

    # ---- command loop -----------------------------------------------------
    def _await_driver_traffic(self) -> bool:
        """Bounded wait for inbound driver frames. True when the
        connection is readable (or the watchdog is disabled); False
        when total driver silence exceeded RAY_TPU_DRIVER_SILENCE_S —
        the half-open-peer case a blocking recv() can never notice."""
        if self._silence_timeout <= 0 or self._heartbeat_interval <= 0:
            return True   # no acks flowing -> silence proves nothing
        while True:
            try:
                readable, _, _ = select.select(
                    [self.conn.sock], [], [], 1.0)
            except (OSError, ValueError):
                return True   # socket dying: let recv() raise the cause
            if readable:
                return True
            silent = time.monotonic() - self._last_driver_traffic
            if silent > self._silence_timeout:
                return False

    def run(self) -> None:
        try:
            while True:
                try:
                    if not self._await_driver_traffic():
                        print(f"ray_tpu node {self.node_id}: driver "
                              f"silent > {self._silence_timeout:.0f}s "
                              "(no frames or heartbeat acks); treating "
                              "the connection as dead", flush=True)
                        from ..util import \
                            events as events_mod  # noqa: PLC0415
                        events_mod.emit_safe(
                            "sched.hang.suspected",
                            f"driver silent > "
                            f"{self._silence_timeout:.0f}s (no frames "
                            "or heartbeat acks); treating the "
                            "connection as dead and rejoining",
                            node_id=self.node_id,
                            kind="driver_silence")
                        try:
                            self.conn.close()
                        except Exception:
                            pass
                        raise ConnectionClosed("driver silence timeout")
                    self._in_recv = True
                    try:
                        # raylint: disable=RT003 bounded two ways: recv
                        # only runs after _await_driver_traffic saw
                        # readability, and a mid-frame park is closed
                        # out by the heartbeat loop's
                        # RAY_TPU_DRIVER_SILENCE_S watchdog (_in_recv)
                        m = self.conn.recv()
                    finally:
                        self._in_recv = False
                    self._last_driver_traffic = time.monotonic()
                    self._handle(m)
                except ConnectionClosed:
                    # Driver connection lost — noticed at recv OR at a
                    # send inside a handler (e.g. worker_spawn_failed):
                    # a preempted/stalled host (or a network blip) tries
                    # to REJOIN under a new incarnation instead of dying
                    # — the driver already failed our work over;
                    # rejoining just puts this host's capacity back in
                    # the pool.
                    if not self._reconnect():
                        return
                    continue
                if m[0] == "shutdown":
                    break
        finally:
            self._cleanup()

    def _reconnect(self) -> bool:
        """Re-register with the driver under a new incarnation, within
        the RAY_TPU_NODE_REJOIN_S window (0 disables). Old workers are
        terminated first: the driver marked them dead at our death
        determination, and a zombie from the fenced incarnation must
        not double-execute anything."""
        window = knobs.get_float("RAY_TPU_NODE_REJOIN_S")
        if window <= 0:
            return False
        for proc in self.workers.values():
            try:
                proc.terminate()
            except Exception:
                pass
        self.workers.clear()
        # Old bulk leases die with the old incarnation: the driver's
        # death determination already re-pended their ledgers (fenced),
        # and the workers they named were just terminated.
        self._clear_lease_state()
        deadline = time.time() + window
        delay = 0.2
        while time.time() < deadline:
            try:
                conn = connect_address(self.driver_address)
                self.incarnation += 1
                conn.send(("register_node", self._register_info()))
            except Exception:
                time.sleep(min(delay,
                               max(0.05, deadline - time.time())))
                delay = min(delay * 2, 2.0)
                continue
            self.conn = conn
            if self._done_batch is not None:
                self._done_batch.conn = conn
            self._last_driver_traffic = time.monotonic()
            print(f"ray_tpu node {self.node_id} rejoined "
                  f"{self.driver_address} as incarnation "
                  f"{self.incarnation}", flush=True)
            return True
        return False

    def _handle(self, m) -> None:
        mtype = m[0]
        if mtype == "node_registered":
            self.job_id = m[2]
            # a restarted driver acks with a bumped incarnation: this
            # host's capacity (and its surviving object store) is now
            # reattached to the resumed control plane
            inc = m[3] if len(m) > 3 else 0
            if inc and inc != self.driver_incarnation:
                print(f"ray_tpu node {self.node_id} reattached to "
                      f"driver incarnation {inc}", flush=True)
                # the resumed driver rebuilt its lease ledger from
                # scratch; anything granted by the old incarnation is
                # fenced there, so holding it here would only double-run
                self._clear_lease_state()
            self.driver_incarnation = inc
        elif mtype == "heartbeat_ack":
            pass  # run() already stamped _last_driver_traffic
        elif mtype == "pull_object":
            _, rid, oid, candidates = m
            threading.Thread(target=self._serve_pull,
                             args=(rid, oid, candidates),
                             daemon=True).start()
        elif mtype == "locations":
            _, rid, candidates = m
            with self._locate_lock:
                pair = self._locate_events.pop(rid, None)
            if pair is not None:
                ev, box = pair
                box["candidates"] = candidates
                ev.set()
        elif mtype == "spawn_worker":
            _, wid, tpu_capable, job_id = m
            self.job_id = job_id
            try:
                self._spawn(wid, tpu_capable)
            except BaseException as e:  # noqa: BLE001
                self.conn.send(("worker_spawn_failed", wid, repr(e)))
        elif mtype == "fetch_object":
            _, rid, loc = m
            threading.Thread(target=self._serve_fetch, args=(rid, loc),
                             daemon=True).start()
        elif mtype == "free_object":
            _, loc = m
            try:
                if loc.kind in ("shm", "native"):
                    self.store.delete_segment(loc.name, loc.size)
                if loc.spill_path and os.path.exists(loc.spill_path):
                    os.remove(loc.spill_path)
                elif loc.kind == "spill" and os.path.exists(loc.name):
                    os.remove(loc.name)
            except Exception:
                traceback.print_exc()
        elif mtype == "nlease_grant":
            _, lid, need, wids, specs, standing = m
            self._on_nlease_grant(lid, need, wids, specs, standing)
        elif mtype == "nlease_extend":
            self._on_nlease_extend(m[1], m[2])
        elif mtype == "nlease_close":
            self._on_nlease_close(m[1])
        elif mtype == "shutdown":
            pass  # run() breaks and cleans up

    def _serve_fetch(self, rid, loc) -> None:
        """Read from the local store (arena or spill file) and stream the
        payload back in chunks. Connection.send is thread-safe, so
        concurrent fetches interleave at frame granularity."""
        with self._fetch_sem:
            try:
                data = self.store.get_bytes(loc)
            except BaseException as e:  # noqa: BLE001
                try:
                    self.conn.send(("fetched", rid, None, e))
                except ConnectionClosed:
                    pass
                return
            try:
                total = len(data)
                if total <= FETCH_CHUNK:
                    self.conn.send(("fetched", rid, data, None))
                    return
                for off in range(0, total, FETCH_CHUNK):
                    self.conn.send(("fetched_chunk", rid, off, total,
                                    data[off:off + FETCH_CHUNK]))
            except ConnectionClosed:
                pass

    def _spawn(self, wid: str, tpu_capable: bool) -> None:
        env = dict(os.environ)
        env["RAY_TPU_JOB_ID"] = self.job_id
        env["RAY_TPU_LOG_DIR"] = self.log_dir
        env["RAY_TPU_NODE_ID"] = self.node_id
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        agent_paths = [p for p in sys.path
                       if p and os.path.isdir(p) and p != repo_root]
        env["PYTHONPATH"] = os.pathsep.join(
            [repo_root, *agent_paths,
             *[p for p in env.get("PYTHONPATH", "").split(os.pathsep)
               if p]])
        if self.agent_addr:
            # workers join the agent-local dispatch plane (two-level
            # scheduling) before they register with the driver
            env["RAY_TPU_AGENT_ADDR"] = self.agent_addr
        if not tpu_capable:
            from ..util.jaxenv import subprocess_env_cpu  # noqa: PLC0415
            subprocess_env_cpu(env)
        self.workers[wid] = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.worker",
             self.driver_address, wid],
            env=env, cwd=os.getcwd())

    # ---- agent-local dispatch plane (two-level scheduling) ----------------
    def _start_agent_plane(self) -> None:
        path = os.path.join(self._tmpdir, "agent.sock")
        self._agent_listener = unix_listener(path)
        self.agent_addr = path
        threading.Thread(target=self._agent_accept, daemon=True,
                         name="agent-accept").start()

    def _agent_accept(self) -> None:
        while True:
            try:
                sock, _ = self._agent_listener.accept()
            except OSError:
                return   # listener closed: agent shutting down
            conn = Connection(sock)
            threading.Thread(target=self._agent_reader, args=(conn,),
                             daemon=True, name="agent-wreader").start()

    def _agent_reader(self, conn: Connection) -> None:
        """One thread per local worker connection: registration,
        completions, and nested submissions."""
        wid = None
        try:
            while True:
                # raylint: disable=RT003 bounded by worker lifetime: the
                # peer is a local worker process on a unix socket; its
                # exit closes the socket and ends this loop
                m = conn.recv()
                k = m[0]
                if k == "aregister":
                    wid = m[1]
                    with self._sched_lock:
                        self._aworkers[wid] = conn
                    self._pump()
                elif k == "adone":
                    self._on_adone(wid, m[1], m[2], m[3])
                elif k == "asubmit":
                    for spec in m[1]:
                        self._on_asubmit(spec, conn)
                elif k == "batch":
                    # worker-side completion batcher: unwrap in order,
                    # refill once at the end — per-item pumps would
                    # fragment the next aexec refill into tiny frames
                    for bm in m[1]:
                        if bm[0] == "adone":
                            self._on_adone(wid, bm[1], bm[2], bm[3],
                                           pump=False)
                        elif bm[0] == "aregister":
                            wid = bm[1]
                            with self._sched_lock:
                                self._aworkers[wid] = conn
                        elif bm[0] == "asubmit":
                            for spec in bm[1]:
                                self._on_asubmit(spec, conn)
                    self._pump()
        except (ConnectionClosed, OSError):
            pass
        finally:
            if wid is not None:
                self._on_aworker_lost(wid, conn)

    def _clear_lease_state(self) -> None:
        with self._sched_lock:
            self._leases.clear()
            self._winflight.clear()
            self._nested_q.clear()

    def _oid_record(self, oid, loc) -> None:
        with self._sched_lock:
            self._oid_locs[oid] = loc
            self._oid_locs.move_to_end(oid)
            while len(self._oid_locs) > 8192:
                self._oid_locs.popitem(last=False)

    def _lease_for(self, resources) -> Optional[_AgentLease]:
        """An open lease of exactly this resource shape with queue
        capacity left AND a free worker, or None. The free-worker
        requirement matters for nested submissions: queueing a child
        behind the lease's only worker when that worker is its blocked
        PARENT would self-deadlock until the spill timer bails it out
        — park it instead and ask for standing capacity (_pump absorbs
        parked tasks the moment a matching worker frees up). Caller
        holds _sched_lock."""
        key = tuple(sorted(resources.items()))
        slots = max(1, knobs.get_int("RAY_TPU_NODE_LEASE_SLOTS"))
        for lease in self._leases.values():
            if (tuple(sorted(lease.need.items())) == key and lease.wids
                    and len(lease.queue) < len(lease.wids) * slots
                    and any(w in self._aworkers
                            and not self._winflight.get(w)
                            for w in lease.wids)):
                return lease
        return None

    def _maybe_want(self, resources) -> None:
        """Ask the driver for standing-lease capacity of this shape, at
        most once a second per shape. Caller holds _sched_lock (only the
        throttle table; the send is safe on the thread-safe conn)."""
        key = tuple(sorted(resources.items()))
        now = time.monotonic()
        if now - self._want_last.get(key, 0.0) < 1.0:
            return
        self._want_last[key] = now
        try:
            self.conn.send(("nlease_want", dict(resources),
                            max(1, len(self._nested_q))))
        except (ConnectionClosed, OSError):
            pass

    def _forward_to_driver(self, spec, owner) -> None:
        """Spill one worker-submitted task up to the driver queue (deps
        not node-local, or no capacity arrived in time) and tell the
        owner to resolve its result through the driver instead."""
        try:
            self.conn.send(("submit", spec))
        except (ConnectionClosed, OSError):
            return  # driver gone: the rejoin/death path owns recovery
        if owner is not None:
            try:
                owner.send(("aspill", [spec.task_id]))
            except (ConnectionClosed, OSError):
                pass  # owner died; its job's failure handling covers it

    def _on_asubmit(self, spec, owner: Connection) -> None:
        """A local worker submitted a fan-out task. Place it locally when
        every dependency is node-resolvable and a shape-matching lease
        has capacity; otherwise park it (asking the driver for a standing
        lease) and let the spill timer forward it if none arrives."""
        dep_locs = []
        with self._sched_lock:
            for oid in getattr(spec, "dep_object_ids", None) or ():
                loc = self._oid_locs.get(oid)
                if loc is None:
                    dep_locs = None
                    break
                dep_locs.append((oid, loc))
        if dep_locs is None:
            self._forward_to_driver(spec, owner)
            return
        # attached out-of-band at dispatch (the compact spec codec
        # carries pure fields only)
        spec._dep_locs = dep_locs or None
        now = time.monotonic()
        with self._sched_lock:
            lease = self._lease_for(spec.resources)
            if lease is not None:
                lease.queue.append([spec, owner, now])
                lease.last_activity = now
            else:
                self._nested_q.append([spec, owner, now])
                self._maybe_want(spec.resources)
        self._pump()

    def _pump(self) -> None:
        """Fan queued lease tasks across registered workers, keeping up
        to RAY_TPU_NODE_LEASE_DEPTH tasks in flight per worker. Depth
        >1 pipelines the aexec/adone round trip (the worker executes
        its backlog FIFO, so sub-millisecond tasks never leave it idle
        waiting for the next frame). Parked nested tasks are absorbed
        only by a fully-idle worker: queueing a child behind its own
        blocked parent would self-deadlock until the spill timer bails
        it out. Assignment happens under the lock; the sends happen
        outside it."""
        depth = max(1, knobs.get_int("RAY_TPU_NODE_LEASE_DEPTH"))
        dispatch = []
        with self._sched_lock:
            for lease in list(self._leases.values()):
                key = None
                for w in list(lease.wids):
                    conn = self._aworkers.get(w)
                    if conn is None:
                        continue
                    q = self._winflight.setdefault(
                        w, collections.deque())
                    while len(q) < depth:
                        if lease.queue:
                            spec, owner, _t0 = lease.queue.popleft()
                        elif not q:
                            # fully idle: absorb a parked nested task
                            # of this lease's shape (it missed
                            # _lease_for when every worker was
                            # momentarily busy)
                            if key is None:
                                key = tuple(sorted(lease.need.items()))
                            entry = None
                            for e in self._nested_q:
                                if tuple(sorted(
                                        e[0].resources.items())) == key:
                                    entry = e
                                    break
                            if entry is None:
                                break
                            self._nested_q.remove(entry)
                            spec, owner, _t0 = entry
                        else:
                            break
                        q.append((lease.lid, spec, owner))
                        lease.last_activity = time.monotonic()
                        dispatch.append((w, conn, spec, owner))
        # one aexec frame per worker per pump round: a refill of
        # `depth` sub-millisecond tasks costs one syscall + wakeup,
        # not one per task (the 1-core contention profile is frame-
        # dominated, see BENCH_CORE multi_agent_scaling)
        by_worker: Dict[str, list] = {}
        conns = {}
        for w, conn, spec, owner in dispatch:
            conns[w] = conn
            by_worker.setdefault(w, []).append(
                (spec, getattr(spec, "_dep_locs", None),
                 owner is not None))
        for w, batch in by_worker.items():
            try:
                conns[w].send(("aexec", batch))
            except (ConnectionClosed, OSError):
                self._on_aworker_lost(w, conns[w])

    def _on_adone(self, wid, tid, sealed, error,
                  pump: bool = True) -> None:
        with self._sched_lock:
            entry = None
            q = self._winflight.get(wid)
            if q:
                # completions arrive in dispatch order (the worker
                # executes its backlog FIFO) — but a revoked/raced
                # frame can skip, so match by task id
                if q[0][1].task_id == tid:
                    entry = q.popleft()
                else:
                    for e in q:
                        if e[1].task_id == tid:
                            entry = e
                            q.remove(e)
                            break
        if entry is None:
            return
        lid, spec, owner = entry
        # host-kind seals are readable by every worker on this node:
        # remember them so a sibling fan-out task depending on this
        # result dispatches with pre-resolved locations
        for oid, loc in sealed or ():
            if getattr(loc, "kind", None) in _HOST_KINDS:
                self._oid_record(oid, loc)
        if owner is not None:
            try:
                owner.send(("aresult", tid, sealed, error))
            except (ConnectionClosed, OSError):
                pass  # owner died; nothing upstream waits on this
        else:
            with self._sched_lock:
                lease = self._leases.get(lid)
                # flush NOW only when this lease has truly drained
                # (no queued work and no pipelined backlog on any of
                # its workers) — the driver may be waiting on the last
                # ack to extend or settle. Mid-stream completions ride
                # the batch window so acks coalesce.
                urgent = lease is None or (
                    not lease.queue
                    and not any(e[0] == lid
                                for q in self._winflight.values()
                                for e in q))
            try:
                self._done_batch.send(
                    ("nlease_done", lid, [(tid, wid, sealed, error)]),
                    urgent=urgent)
            except (ConnectionClosed, OSError):
                pass  # rejoin path re-pends the ledger driver-side
        if pump:
            self._pump()

    def _on_aworker_lost(self, wid, conn: Connection) -> None:
        """A local worker's agent connection died (process exit or
        crash). Its in-flight task HAD started: driver-granted tasks
        spill back with started=True (the driver applies its normal
        worker-death retry accounting); nested tasks forward to the
        driver for re-execution (at-least-once, like a direct-call
        channel death)."""
        with self._sched_lock:
            if self._aworkers.get(wid) is conn:
                del self._aworkers[wid]
            entries = self._winflight.pop(wid, None) or ()
            for lease in self._leases.values():
                lease.wids.discard(wid)
        # only the head of the worker's FIFO backlog can have started;
        # the pipelined tasks behind it re-queue without burning a retry
        spills: Dict[str, list] = {}
        for i, (lid, spec, owner) in enumerate(entries):
            if owner is None:
                spills.setdefault(lid, []).append(
                    (spec.task_id, i == 0))
            else:
                self._forward_to_driver(spec, owner)
        for lid, batch in spills.items():
            try:
                self.conn.send(("nlease_spill", lid, batch,
                                "worker_death"))
            except (ConnectionClosed, OSError):
                pass
        self._pump()

    def _on_nlease_grant(self, lid, need, wids, specs, standing) -> None:
        now = time.monotonic()
        with self._sched_lock:
            lease = _AgentLease(lid, dict(need), set(wids),
                                bool(standing))
            for spec in specs:
                lease.queue.append([spec, None, now])
            self._leases[lid] = lease
            # parked nested tasks of this shape ride the new capacity
            key = tuple(sorted(lease.need.items()))
            keep: collections.deque = collections.deque()
            for entry in self._nested_q:
                if tuple(sorted(entry[0].resources.items())) == key:
                    lease.queue.append(entry)
                else:
                    keep.append(entry)
            self._nested_q = keep
        self._pump()

    def _on_nlease_extend(self, lid, specs) -> None:
        now = time.monotonic()
        unknown = False
        with self._sched_lock:
            lease = self._leases.get(lid)
            if lease is None:
                unknown = True
            else:
                lease.last_activity = now
                for spec in specs:
                    lease.queue.append([spec, None, now])
        if unknown:
            # closed/fenced lease: hand the batch straight back unstarted
            try:
                self.conn.send(("nlease_spill", lid,
                                [(s.task_id, False) for s in specs],
                                "unknown_lease"))
            except (ConnectionClosed, OSError):
                pass
            return
        self._pump()

    def _on_nlease_close(self, lid) -> None:
        with self._sched_lock:
            lease = self._leases.pop(lid, None)
            if lease is not None:
                for entry in lease.queue:
                    # nested tasks go back to the wait queue; any
                    # driver-owned leftovers were already re-pended
                    # driver-side before the close
                    if entry[1] is not None:
                        self._nested_q.append(entry)
        self._pump()

    def _spill_loop(self) -> None:
        """Ages out unplaceable queued tasks: lease entries that no free
        worker picked up within RAY_TPU_NODE_LEASE_SPILL_S spill back to
        the driver, parked nested tasks forward to it, and drained
        standing leases release after RAY_TPU_NODE_LEASE_IDLE_S."""
        spill_s = knobs.get_float("RAY_TPU_NODE_LEASE_SPILL_S")
        idle_s = knobs.get_float("RAY_TPU_NODE_LEASE_IDLE_S")
        tick = max(0.05, min(0.5, (spill_s or 1.0) / 4))
        while True:
            time.sleep(tick)
            try:
                self._spill_pass(spill_s, idle_s)
            except Exception:
                pass  # the timer must never die

    def _spill_pass(self, spill_s: float, idle_s: float) -> None:
        now = time.monotonic()
        spills = []     # (lid, [(tid, False)])
        forwards = []   # (spec, owner)
        releases = []
        with self._sched_lock:
            for lid, lease in list(self._leases.items()):
                if spill_s > 0 and lease.queue:
                    free = any(w in self._aworkers
                               and not self._winflight.get(w)
                               for w in lease.wids)
                    if not free:
                        aged = []
                        keep: collections.deque = collections.deque()
                        for entry in lease.queue:
                            spec, owner, t0 = entry
                            if now - t0 > spill_s:
                                if owner is None:
                                    aged.append(spec.task_id)
                                else:
                                    forwards.append((spec, owner))
                            else:
                                keep.append(entry)
                        lease.queue = keep
                        if aged:
                            spills.append(
                                (lid, [(t, False) for t in aged]))
                if (lease.standing and idle_s > 0 and not lease.queue
                        and now - lease.last_activity > idle_s
                        and not any(e[0] == lid
                                    for q in self._winflight.values()
                                    for e in q)):
                    releases.append(lid)
                    del self._leases[lid]
            if spill_s > 0:
                keep = collections.deque()
                for entry in self._nested_q:
                    spec, owner, t0 = entry
                    if now - t0 > spill_s:
                        forwards.append((spec, owner))
                    else:
                        keep.append(entry)
                self._nested_q = keep
        for lid, entries in spills:
            try:
                self.conn.send(
                    ("nlease_spill", lid, entries, "placement_timeout"))
            except (ConnectionClosed, OSError):
                pass
        for spec, owner in forwards:
            self._forward_to_driver(spec, owner)
        for lid in releases:
            try:
                self.conn.send(("nlease_release", lid))
            except (ConnectionClosed, OSError):
                pass

    def _cleanup(self) -> None:
        if self._agent_listener is not None:
            try:
                self._agent_listener.close()
            except Exception:
                pass
        try:
            self.transfer_server.close()
        except Exception:
            pass
        for proc in self.workers.values():
            try:
                proc.terminate()
            except Exception:
                pass
        deadline = time.time() + 2.0
        for proc in self.workers.values():
            try:
                proc.wait(timeout=max(0.01, deadline - time.time()))
            except Exception:
                try:
                    proc.kill()
                except Exception:
                    pass
        try:
            self.store.shutdown()
        except Exception:
            traceback.print_exc()
        import shutil
        shutil.rmtree(self._tmpdir, ignore_errors=True)


def main() -> None:
    ap = argparse.ArgumentParser(
        description="ray_tpu node agent: join this host to a driver")
    ap.add_argument("driver_address",
                    help="tcp://<driver-host>:<port> of ray_tpu.init("
                         "listen=...)")
    ap.add_argument("--num-cpus", type=int, default=None)
    ap.add_argument("--num-tpus", type=int, default=None)
    ap.add_argument("--store-bytes", type=int, default=None)
    ap.add_argument("--resources", type=str, default=None,
                    help='extra custom resources as JSON, e.g. '
                         '\'{"my_res": 2}\'')
    ap.add_argument("--node-id", type=str, default=None)
    args = ap.parse_args()
    import json
    extra = json.loads(args.resources) if args.resources else None
    agent = NodeAgent(args.driver_address, num_cpus=args.num_cpus,
                      num_tpus=args.num_tpus, resources=extra,
                      store_bytes=args.store_bytes, node_id=args.node_id)
    print(f"ray_tpu node {agent.node_id} joined {args.driver_address}",
          flush=True)
    agent.run()


if __name__ == "__main__":
    main()
