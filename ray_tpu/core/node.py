"""Node agent: joins a host to a running driver over TCP.

Reference parity: src/ray/raylet/node_manager.cc (node registration,
worker leasing) + src/ray/gcs/gcs_server/gcs_node_manager.cc (node table)
— collapsed to the single-controller model: the agent owns this host's
shared-memory object store and spawns workers on the driver's request;
the workers connect straight back to the driver's TCP listener, so the
driver keeps one scheduler for the whole cluster ("multi-host pods are a
transport, not a rewrite").

Run on each additional host:
    python -m ray_tpu.core.node tcp://<driver-host>:<port> \
        [--num-cpus N] [--num-tpus N] [--store-bytes B]

The driver side opens the TCP listener via
`ray_tpu.init(listen="0.0.0.0:6380")` (or RAY_TPU_LISTEN) and exposes the
bound address as `runtime.tcp_address`.
"""
from __future__ import annotations

import argparse
import os
import select
import subprocess
import sys
import tempfile
import threading
import time
import traceback
from typing import Dict, Optional

from . import resources as res_mod
from .ids import new_node_id
from .object_store import make_store
from .protocol import ConnectionClosed, connect_address
from ..util import knobs

# Cross-node payloads stream in frames well under protocol.MAX_MSG so one
# huge object can never poison the connection with an oversized frame.
FETCH_CHUNK = knobs.get_int("RAY_TPU_FETCH_CHUNK")


class NodeAgent:
    def __init__(self, driver_address: str, *, num_cpus=None, num_tpus=None,
                 resources=None, store_bytes: Optional[int] = None,
                 node_id: Optional[str] = None):
        self.driver_address = driver_address
        # A pre-chosen id lets a launcher (core/autoscaler.py providers)
        # correlate "the process I started" with "the node that joined".
        self.node_id = node_id or new_node_id()
        # This host's store is its own arena: drop any inherited owner env
        # (tests run agents on the driver's host) and stamp our node id so
        # every ObjectLocation written here names this node.
        os.environ.pop("RAY_TPU_ARENA_NAME", None)
        os.environ["RAY_TPU_NODE_ID"] = self.node_id
        cap = store_bytes \
            or knobs.get_int("RAY_TPU_STORE_BYTES", default=2 << 30)
        self.store = make_store(capacity_bytes=cap, is_owner=True)

        node_res = res_mod.detect_node_resources(num_cpus, num_tpus)
        if resources:
            node_res.update(resources)
        self.resources = node_res
        self.labels = res_mod.detect_tpu_topology(
            int(node_res.get("TPU", 0)))
        node_type = knobs.get_raw("RAY_TPU_NODE_TYPE")
        if node_type:
            self.labels["node-type"] = node_type

        self._tmpdir = tempfile.mkdtemp(prefix="ray_tpu_node_")
        self.log_dir = os.path.join(self._tmpdir, "logs")
        os.makedirs(self.log_dir, exist_ok=True)
        # This node's workers spill put-overflow here (core/spilling.py;
        # the driver-side watermark spiller only covers the driver node).
        # Overrides any env inherited from a same-host driver in tests.
        spill_dir = os.path.join(self._tmpdir, "spill")
        os.environ["RAY_TPU_SPILL_DIR"] = spill_dir
        self.workers: Dict[str, subprocess.Popen] = {}
        self.job_id = "job-default"
        # Fetches run on threads (a multi-GB read must not head-of-line
        # block spawns/frees), bounded so they can't starve the loop.
        self._fetch_sem = threading.Semaphore(4)

        # Peer-to-peer transfer plane (core/object_transfer.py): this
        # host serves its sealed objects directly to peer nodes, and
        # pulls remote objects into its own arena on the driver's
        # request ("pull_object") — object bytes stop transiting the
        # driver's control connections. Spans per pull buffer here and
        # ship with the metrics heartbeat.
        self._spans: list = []
        self._spans_lock = threading.Lock()
        from .object_transfer import (PullManager,  # noqa: PLC0415
                                      TransferServer)
        self.transfer_server = TransferServer(
            self.store, spill_dirs=[spill_dir])
        self.pull_manager = PullManager(
            self.store, node_id=self.node_id, locate=self._locate,
            span_sink=self._span_sink)
        # locate round-trips: rid -> (Event, box)
        self._locate_lock = threading.Lock()
        self._locate_counter = 0
        self._locate_events: Dict[int, tuple] = {}

        # Bumped on every re-registration after a lost driver connection
        # (network blip, or the driver fenced us after a heartbeat-
        # declared death): the driver fences traffic from older
        # incarnations, so a stalled-then-recovered agent can't corrupt
        # the failover that already happened.
        self.incarnation = 0
        # last acked driver incarnation (bumps when a SIGKILLed driver
        # resumes and this agent reattaches to it)
        self.driver_incarnation = 0
        self.conn = connect_address(driver_address)
        self.conn.send(("register_node", self._register_info()))
        # Metrics plane: this agent's registry (node-local store stats,
        # any user metrics recorded here) ships delta snapshots on the
        # node connection; the driver merges them tagged with node_id.
        self._metrics_interval = knobs.get_float(
            "RAY_TPU_METRICS_INTERVAL_S")
        if self._metrics_interval > 0:
            threading.Thread(target=self._metrics_loop, daemon=True,
                             name="node-metrics").start()
        # Liveness pings for the driver's event plane: a stalled (not
        # just disconnected) agent surfaces as node.heartbeat_miss
        # before the socket-level death determination.
        self._heartbeat_interval = knobs.get_float(
            "RAY_TPU_NODE_HEARTBEAT_S")
        if self._heartbeat_interval > 0:
            threading.Thread(target=self._heartbeat_loop, daemon=True,
                             name="node-heartbeat").start()
        # Agent-side mirror of the driver's heartbeat-declared death:
        # the driver acks every heartbeat, so a healthy connection is
        # never silent longer than the heartbeat interval. Total
        # silence past RAY_TPU_DRIVER_SILENCE_S means the driver HOST
        # is gone without a FIN/RST (preemption, partition) — recv()
        # would park until the ~15min TCP retransmit timeout and this
        # host's capacity would stay lost long after the driver
        # restarts. run() treats that as a lost connection and rejoins.
        self._silence_timeout = knobs.get_float("RAY_TPU_DRIVER_SILENCE_S")
        self._last_driver_traffic = time.monotonic()
        # True while run() is parked inside conn.recv(): with the
        # select() gate that only happens when at least a frame HEADER
        # arrived, so a long park here means the driver died mid-frame
        # — the heartbeat loop then closes the conn to unblock the
        # read (the same cross-thread unblock idiom the driver's death
        # determination uses). A socket-level settimeout would be
        # simpler but caps every sendall on this SHARED conn too, and
        # the fetch path streams 64MB frames over it.
        self._in_recv = False

    def _register_info(self) -> dict:
        return {
            "node_id": self.node_id,
            "hostname": os.uname().nodename,
            "resources": dict(self.resources),
            "labels": dict(self.labels),
            "transfer_address": self.transfer_server.address,
            "incarnation": self.incarnation,
            "pid": os.getpid(),
        }

    def _heartbeat_loop(self) -> None:
        while True:
            time.sleep(self._heartbeat_interval)
            try:
                self.conn.send(("heartbeat", time.time()))
            except (ConnectionClosed, OSError):
                # driver connection down: run() is either reconnecting
                # (self.conn gets swapped) or exiting (daemon thread
                # dies with the process) — keep ticking either way
                continue
            except Exception:
                pass
            # mid-frame silence watchdog: run()'s select() gate cannot
            # fire while recv() is parked on a partial frame
            if (self._silence_timeout > 0 and self._in_recv
                    and time.monotonic() - self._last_driver_traffic
                    > self._silence_timeout):
                try:
                    self.conn.close()   # recv raises; run() rejoins
                except Exception:
                    pass

    def _metrics_loop(self) -> None:
        from ..util.metrics import DeltaExporter  # noqa: PLC0415
        from ..util import metrics_catalog as mcat  # noqa: PLC0415
        from ..util import events as events_mod  # noqa: PLC0415
        exporter = DeltaExporter()
        # Collected-but-unsent messages: collect()/drain() are
        # DESTRUCTIVE reads, so a send failure during the rejoin window
        # must re-queue them (bounded) rather than drop a blip's worth
        # of deltas and lifecycle events on the floor.
        pending: list = []
        while True:
            time.sleep(self._metrics_interval)
            try:
                mcat.get("ray_tpu_object_store_used_bytes").set(
                    float(self.store.used_bytes()))
                cap = getattr(self.store, "capacity", None)
                if cap:
                    mcat.get(
                        "ray_tpu_object_store_capacity_bytes").set(
                        float(cap))
                payload = exporter.collect()
                if payload:
                    pending.append(("metrics", payload))
                with self._spans_lock:
                    spans, self._spans = self._spans, []
                if spans:
                    pending.append(("spans", spans))
                # event-plane delta batch (anything code on this agent
                # emitted — memory pressure, engine/data events)
                evs = events_mod.drain()
                if evs:
                    pending.append(("events", evs))
                # one coalesced frame per interval (compact binary
                # codec), not one frame per telemetry kind; a single
                # leftover skips the envelope
                if len(pending) > 1:
                    self.conn.send(("batch", list(pending)))
                    del pending[:]
                elif pending:
                    self.conn.send(pending[0])
                    pending.pop(0)
            except (ConnectionClosed, OSError):
                # reconnecting (or exiting) — see heartbeat loop; keep
                # the backlog bounded while the driver is away
                del pending[:-64]
                continue
            except Exception:
                pass  # telemetry must never kill the agent

    # ---- transfer plane ---------------------------------------------------
    def _span_sink(self, span: dict) -> None:
        with self._spans_lock:
            self._spans.append(span)

    def _locate(self, oid: str):
        """Ask the driver for fresh location-directory candidates (the
        PullManager's between-rounds re-resolve). Returns the candidate
        list, or None on timeout/disconnect."""
        with self._locate_lock:
            self._locate_counter += 1
            rid = self._locate_counter
            ev = threading.Event()
            box: dict = {}
            self._locate_events[rid] = (ev, box)
        try:
            self.conn.send(("locate", rid, oid))
        except ConnectionClosed:
            with self._locate_lock:
                self._locate_events.pop(rid, None)
            return None
        if not ev.wait(timeout=10.0):
            with self._locate_lock:
                self._locate_events.pop(rid, None)
            return None
        return box.get("candidates")

    def _serve_pull(self, rid, oid: str, candidates) -> None:
        """Run one driver-requested pull on a thread and report the
        local location back (or the failure, so the driver can fall
        back to its relay path). Bounded by the same semaphore as
        fetches — each pull buffers a whole object, so unbounded
        concurrency would be an unbounded memory spike."""
        with self._fetch_sem:
            try:
                loc = self.pull_manager.pull(oid, candidates)
                self.conn.send(("pulled", rid, oid, loc, None))
            except ConnectionClosed:
                pass
            except BaseException as e:  # noqa: BLE001
                try:
                    self.conn.send(("pulled", rid, oid, None, repr(e)))
                except ConnectionClosed:
                    pass

    # ---- command loop -----------------------------------------------------
    def _await_driver_traffic(self) -> bool:
        """Bounded wait for inbound driver frames. True when the
        connection is readable (or the watchdog is disabled); False
        when total driver silence exceeded RAY_TPU_DRIVER_SILENCE_S —
        the half-open-peer case a blocking recv() can never notice."""
        if self._silence_timeout <= 0 or self._heartbeat_interval <= 0:
            return True   # no acks flowing -> silence proves nothing
        while True:
            try:
                readable, _, _ = select.select(
                    [self.conn.sock], [], [], 1.0)
            except (OSError, ValueError):
                return True   # socket dying: let recv() raise the cause
            if readable:
                return True
            silent = time.monotonic() - self._last_driver_traffic
            if silent > self._silence_timeout:
                return False

    def run(self) -> None:
        try:
            while True:
                try:
                    if not self._await_driver_traffic():
                        print(f"ray_tpu node {self.node_id}: driver "
                              f"silent > {self._silence_timeout:.0f}s "
                              "(no frames or heartbeat acks); treating "
                              "the connection as dead", flush=True)
                        try:
                            self.conn.close()
                        except Exception:
                            pass
                        raise ConnectionClosed("driver silence timeout")
                    self._in_recv = True
                    try:
                        # raylint: disable=RT003 bounded two ways: recv
                        # only runs after _await_driver_traffic saw
                        # readability, and a mid-frame park is closed
                        # out by the heartbeat loop's
                        # RAY_TPU_DRIVER_SILENCE_S watchdog (_in_recv)
                        m = self.conn.recv()
                    finally:
                        self._in_recv = False
                    self._last_driver_traffic = time.monotonic()
                    self._handle(m)
                except ConnectionClosed:
                    # Driver connection lost — noticed at recv OR at a
                    # send inside a handler (e.g. worker_spawn_failed):
                    # a preempted/stalled host (or a network blip) tries
                    # to REJOIN under a new incarnation instead of dying
                    # — the driver already failed our work over;
                    # rejoining just puts this host's capacity back in
                    # the pool.
                    if not self._reconnect():
                        return
                    continue
                if m[0] == "shutdown":
                    break
        finally:
            self._cleanup()

    def _reconnect(self) -> bool:
        """Re-register with the driver under a new incarnation, within
        the RAY_TPU_NODE_REJOIN_S window (0 disables). Old workers are
        terminated first: the driver marked them dead at our death
        determination, and a zombie from the fenced incarnation must
        not double-execute anything."""
        window = knobs.get_float("RAY_TPU_NODE_REJOIN_S")
        if window <= 0:
            return False
        for proc in self.workers.values():
            try:
                proc.terminate()
            except Exception:
                pass
        self.workers.clear()
        deadline = time.time() + window
        delay = 0.2
        while time.time() < deadline:
            try:
                conn = connect_address(self.driver_address)
                self.incarnation += 1
                conn.send(("register_node", self._register_info()))
            except Exception:
                time.sleep(min(delay,
                               max(0.05, deadline - time.time())))
                delay = min(delay * 2, 2.0)
                continue
            self.conn = conn
            self._last_driver_traffic = time.monotonic()
            print(f"ray_tpu node {self.node_id} rejoined "
                  f"{self.driver_address} as incarnation "
                  f"{self.incarnation}", flush=True)
            return True
        return False

    def _handle(self, m) -> None:
        mtype = m[0]
        if mtype == "node_registered":
            self.job_id = m[2]
            # a restarted driver acks with a bumped incarnation: this
            # host's capacity (and its surviving object store) is now
            # reattached to the resumed control plane
            inc = m[3] if len(m) > 3 else 0
            if inc and inc != self.driver_incarnation:
                print(f"ray_tpu node {self.node_id} reattached to "
                      f"driver incarnation {inc}", flush=True)
            self.driver_incarnation = inc
        elif mtype == "heartbeat_ack":
            pass  # run() already stamped _last_driver_traffic
        elif mtype == "pull_object":
            _, rid, oid, candidates = m
            threading.Thread(target=self._serve_pull,
                             args=(rid, oid, candidates),
                             daemon=True).start()
        elif mtype == "locations":
            _, rid, candidates = m
            with self._locate_lock:
                pair = self._locate_events.pop(rid, None)
            if pair is not None:
                ev, box = pair
                box["candidates"] = candidates
                ev.set()
        elif mtype == "spawn_worker":
            _, wid, tpu_capable, job_id = m
            self.job_id = job_id
            try:
                self._spawn(wid, tpu_capable)
            except BaseException as e:  # noqa: BLE001
                self.conn.send(("worker_spawn_failed", wid, repr(e)))
        elif mtype == "fetch_object":
            _, rid, loc = m
            threading.Thread(target=self._serve_fetch, args=(rid, loc),
                             daemon=True).start()
        elif mtype == "free_object":
            _, loc = m
            try:
                if loc.kind in ("shm", "native"):
                    self.store.delete_segment(loc.name, loc.size)
                if loc.spill_path and os.path.exists(loc.spill_path):
                    os.remove(loc.spill_path)
                elif loc.kind == "spill" and os.path.exists(loc.name):
                    os.remove(loc.name)
            except Exception:
                traceback.print_exc()
        elif mtype == "shutdown":
            pass  # run() breaks and cleans up

    def _serve_fetch(self, rid, loc) -> None:
        """Read from the local store (arena or spill file) and stream the
        payload back in chunks. Connection.send is thread-safe, so
        concurrent fetches interleave at frame granularity."""
        with self._fetch_sem:
            try:
                data = self.store.get_bytes(loc)
            except BaseException as e:  # noqa: BLE001
                try:
                    self.conn.send(("fetched", rid, None, e))
                except ConnectionClosed:
                    pass
                return
            try:
                total = len(data)
                if total <= FETCH_CHUNK:
                    self.conn.send(("fetched", rid, data, None))
                    return
                for off in range(0, total, FETCH_CHUNK):
                    self.conn.send(("fetched_chunk", rid, off, total,
                                    data[off:off + FETCH_CHUNK]))
            except ConnectionClosed:
                pass

    def _spawn(self, wid: str, tpu_capable: bool) -> None:
        env = dict(os.environ)
        env["RAY_TPU_JOB_ID"] = self.job_id
        env["RAY_TPU_LOG_DIR"] = self.log_dir
        env["RAY_TPU_NODE_ID"] = self.node_id
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        agent_paths = [p for p in sys.path
                       if p and os.path.isdir(p) and p != repo_root]
        env["PYTHONPATH"] = os.pathsep.join(
            [repo_root, *agent_paths,
             *[p for p in env.get("PYTHONPATH", "").split(os.pathsep)
               if p]])
        if not tpu_capable:
            from ..util.jaxenv import subprocess_env_cpu  # noqa: PLC0415
            subprocess_env_cpu(env)
        self.workers[wid] = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.worker",
             self.driver_address, wid],
            env=env, cwd=os.getcwd())

    def _cleanup(self) -> None:
        try:
            self.transfer_server.close()
        except Exception:
            pass
        for proc in self.workers.values():
            try:
                proc.terminate()
            except Exception:
                pass
        deadline = time.time() + 2.0
        for proc in self.workers.values():
            try:
                proc.wait(timeout=max(0.01, deadline - time.time()))
            except Exception:
                try:
                    proc.kill()
                except Exception:
                    pass
        try:
            self.store.shutdown()
        except Exception:
            traceback.print_exc()
        import shutil
        shutil.rmtree(self._tmpdir, ignore_errors=True)


def main() -> None:
    ap = argparse.ArgumentParser(
        description="ray_tpu node agent: join this host to a driver")
    ap.add_argument("driver_address",
                    help="tcp://<driver-host>:<port> of ray_tpu.init("
                         "listen=...)")
    ap.add_argument("--num-cpus", type=int, default=None)
    ap.add_argument("--num-tpus", type=int, default=None)
    ap.add_argument("--store-bytes", type=int, default=None)
    ap.add_argument("--resources", type=str, default=None,
                    help='extra custom resources as JSON, e.g. '
                         '\'{"my_res": 2}\'')
    ap.add_argument("--node-id", type=str, default=None)
    args = ap.parse_args()
    import json
    extra = json.loads(args.resources) if args.resources else None
    agent = NodeAgent(args.driver_address, num_cpus=args.num_cpus,
                      num_tpus=args.num_tpus, resources=extra,
                      store_bytes=args.store_bytes, node_id=args.node_id)
    print(f"ray_tpu node {agent.node_id} joined {args.driver_address}",
          flush=True)
    agent.run()


if __name__ == "__main__":
    main()
