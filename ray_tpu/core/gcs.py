"""Global Control Store — cluster metadata tables.

Reference parity: src/ray/gcs/gcs_server/ (actor table, node table, job
table, named-actor index, pubsub). In a single-controller runtime these are
in-driver dictionaries mutated only by the runtime dispatcher thread, so no
locks are needed on the hot path; read-only snapshots are exposed to the
state API (ray_tpu/util/state.py).
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional, Tuple


@dataclasses.dataclass
class ObjectEntry:
    object_id: str
    state: str = "pending"            # pending | ready | error
    loc: Any = None                   # ObjectLocation when ready
    error: Any = None                 # serialized TaskError when state=error
    owner_task: str = ""
    created_at: float = 0.0
    pinned: bool = True
    # Additional locations (e.g. the original remote copy after a fetch
    # re-hosted the payload locally); all are freed together.
    copies: List[Any] = dataclasses.field(default_factory=list)
    # The producing task's spec was evicted from the driver's lineage
    # table (RAY_TPU_LINEAGE_BYTES): this object can no longer be
    # reconstructed and loss reports must say so.
    lineage_evicted: bool = False
    # Bumped on every seal (initial + lineage reseals); locations are
    # stamped with it so stale unreachable reports are ignorable.
    seal_seq: int = 0


@dataclasses.dataclass
class ActorEntry:
    actor_id: str
    name: Optional[str]
    namespace: str
    class_name: str
    state: str = "PENDING"            # PENDING|ALIVE|RESTARTING|DEAD
    worker_id: Optional[str] = None
    resources: Dict[str, float] = dataclasses.field(default_factory=dict)
    max_restarts: int = 0
    num_restarts: int = 0
    death_cause: str = ""
    create_spec: Any = None           # retained for restarts


@dataclasses.dataclass
class TaskEntry:
    task_id: str
    name: str
    state: str = "PENDING"            # PENDING|SCHEDULED|RUNNING|FINISHED|FAILED|CANCELLED
    worker_id: Optional[str] = None
    actor_id: Optional[str] = None
    submitted_at: float = 0.0
    started_at: float = 0.0
    finished_at: float = 0.0
    retries_left: int = 0
    # actor-method concurrency group this task dispatched under (None =
    # the default lane); read back to decrement the right counter
    concurrency_group: Optional[str] = None
    # trace linkage (util/tracing.py): the submit span this entry
    # represents in the timeline, and the span it parents to
    trace_id: str = ""
    span_id: str = ""
    parent_span_id: str = ""


@dataclasses.dataclass
class NodeEntry:
    node_id: str
    hostname: str
    resources: Dict[str, float]
    alive: bool = True
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    # Bumped each time the same node_id re-registers after a declared
    # death (preempted host rejoining); messages from older incarnations
    # are fenced by the driver.
    incarnation: int = 0


class GCS:
    def __init__(self) -> None:
        self.objects: Dict[str, ObjectEntry] = {}
        self.actors: Dict[str, ActorEntry] = {}
        self.tasks: Dict[str, TaskEntry] = {}
        self.nodes: Dict[str, NodeEntry] = {}
        self.named_actors: Dict[Tuple[str, str], str] = {}   # (ns, name) -> actor_id
        self._subscribers: Dict[str, List[Callable[[Any], None]]] = {}
        self.kv: Dict[str, bytes] = {}                       # internal KV (jobs, serve)

    # -- objects ------------------------------------------------------------
    def add_pending_object(self, oid: str, owner_task: str = "") -> ObjectEntry:
        e = ObjectEntry(object_id=oid, owner_task=owner_task,
                        created_at=time.time())
        self.objects[oid] = e
        return e

    def seal_object(self, oid: str, loc: Any) -> ObjectEntry:
        e = self.objects.get(oid) or self.add_pending_object(oid)
        e.state, e.loc = "ready", loc
        e.seal_seq += 1
        try:
            loc.seal_seq = e.seal_seq
        except Exception:
            pass
        return e

    def fail_object(self, oid: str, error: Any) -> ObjectEntry:
        e = self.objects.get(oid) or self.add_pending_object(oid)
        e.state, e.error = "error", error
        return e

    # -- actors -------------------------------------------------------------
    def register_named_actor(self, ns: str, name: str, actor_id: str) -> bool:
        key = (ns, name)
        if key in self.named_actors:
            existing = self.actors.get(self.named_actors[key])
            if existing is not None and existing.state != "DEAD":
                return False
        self.named_actors[key] = actor_id
        return True

    def lookup_named_actor(self, ns: str, name: str) -> Optional[str]:
        aid = self.named_actors.get((ns, name))
        if aid is None:
            return None
        entry = self.actors.get(aid)
        if entry is None or entry.state == "DEAD":
            return None
        return aid

    # -- pubsub -------------------------------------------------------------
    def publish(self, channel: str, msg: Any) -> None:
        for cb in self._subscribers.get(channel, []):
            cb(msg)

    def subscribe(self, channel: str, cb: Callable[[Any], None]) -> None:
        self._subscribers.setdefault(channel, []).append(cb)
