"""Reusable object channels for compiled DAGs (docs/DAG.md).

A channel is a fixed (writer process -> reader process) edge resolved
once at compile time. The writer owns one socket to the reader's
ChannelHost and, for same-node edges above the inline threshold, one
shared-memory ChannelSegment that every execution REWRITES in place —
no allocate/seal/free per call, which is the entire point: the dynamic
path pays an object-table seal plus a store segment per intermediate
value, a compiled channel pays one memcpy and one small notify frame.

Frame protocol (all frames ride the compact binary wire,
`protocol.WIRE_KINDS`):

  writer -> reader   ("ch_open", dag_id, ch_id)          once per socket
  writer -> reader   ("ch_notify", ch_id, seq, kind, size, ref)
                     kind "s": ref = shm segment name, payload at [0:size]
                     kind "b": ref = payload bytes inline in the frame
                     kind "e": ref = cloudpickled exception (TaskError)
  reader -> writer   ("ch_ack", ch_id, seq)              after consume
  reader -> writer   ("ch_err", ch_id, seq, reason)      fatal reject

The handshake is an ack window: for inline payloads (kinds "b"/"e")
the writer may run RAY_TPU_DAG_CHANNEL_DEPTH seqnos ahead of the
reader — that slack is what lets pipeline stages overlap instead of
lock-stepping on every hop. A shared-memory payload (kind "s") gates
at depth 1: the segment is rewritten in place, so the writer drains
every outstanding ack before touching it again. Error payloads keep
the seqno cadence: every writer emits every seqno on every
out-channel, value or error, so readers never have to reason about
gaps.
"""
from __future__ import annotations

import pickle
import queue
import select
import threading
import time
from collections import deque
from typing import Any, Dict, Optional, Tuple

import cloudpickle

from ..exceptions import CompiledDagError
from ..util import knobs
from ..util import waits as waits_mod
from . import serialization
from .object_store import INLINE_MAX, ChannelSegment, ChannelSegmentReader
from .protocol import Connection, ConnectionClosed, connect_address


class ChannelClosed(Exception):
    """Reader-side: the channel's writer socket closed (teardown or a
    dead upstream participant)."""


def _mcat():
    from ..util import metrics_catalog  # noqa: PLC0415
    return metrics_catalog


class ChannelWriter:
    """Writer end of one compiled-DAG channel edge."""

    def __init__(self, dag_id: str, ch_id: str, addr: str,
                 same_node: bool, capacity: Optional[int] = None):
        self.dag_id = dag_id
        self.ch_id = ch_id
        self.addr = addr
        self.same_node = same_node
        self._conn: Optional[Connection] = None
        self._seg: Optional[ChannelSegment] = None
        self._capacity = capacity or knobs.get_int(
            "RAY_TPU_DAG_CHANNEL_BYTES")
        self._depth = max(1, knobs.get_int("RAY_TPU_DAG_CHANNEL_DEPTH"))
        self._outstanding: "deque[int]" = deque()
        self._closed = False
        # cumulative seconds this writer spent BLOCKED on the consumer
        # ack window (only time where the window actually forced a
        # wait): the per-stage spans read deltas off it so backpressure
        # stalls are attributed to the stage that paid them
        self.stall_s = 0.0

    def open(self) -> None:
        try:
            self._conn = connect_address(self.addr)
            self._conn.send(("ch_open", self.dag_id, self.ch_id))
        except (ConnectionClosed, OSError) as e:
            raise CompiledDagError(
                f"channel {self.ch_id} failed to open", cause=repr(e)
            ) from e

    def _drain_acks(self, max_outstanding: int) -> None:
        """Block until at most `max_outstanding` seqnos await acks.
        Acks arrive strictly in seqno order (the reader consumes in
        order), so each recv must match the oldest outstanding."""
        if len(self._outstanding) <= max_outstanding:
            return
        t0 = time.monotonic()
        wtok = [0]
        try:
            self._drain_acks_blocking(max_outstanding, wtok)
        finally:
            waits_mod.unpark(wtok[0])
            dt = time.monotonic() - t0
            self.stall_s += dt
            try:
                _mcat().get("ray_tpu_dag_channel_stall_seconds").inc(dt)
            except Exception:
                pass

    def _drain_acks_blocking(self, max_outstanding: int,
                             wtok=None) -> None:
        while len(self._outstanding) > max_outstanding:
            expect = self._outstanding[0]
            if wtok is not None and not wtok[0]:
                # Park only once the ack is genuinely late: in a
                # healthy pipeline it has already arrived (or does
                # within the grace), and a park per windowed send
                # would tax every execution.
                try:
                    r, _, _ = select.select(
                        [self._conn.fileno()], [], [],
                        waits_mod.PARK_GRACE_S)
                except (OSError, ValueError):
                    r = [True]
                if not r:
                    wtok[0] = waits_mod.park(
                        "dag-channel", self.ch_id, op="ack",
                        dag_id=self.dag_id, seq=expect)
            try:
                # raylint: disable=RT003 ack socket: a dead reader
                # closes it (ConnectionClosed below) and teardown
                # closes it from our side; either way the blocked
                # writer unblocks with an error
                m = self._conn.recv()
            except ConnectionClosed as e:
                raise CompiledDagError(
                    f"channel {self.ch_id} reader went away awaiting "
                    f"ack {expect}", cause=repr(e)) from e
            if m[0] == "ch_ack" and m[2] == expect:
                self._outstanding.popleft()
                continue
            raise CompiledDagError(
                f"channel {self.ch_id} protocol error awaiting ack "
                f"{expect}", cause=repr(m[:3]))

    def write_value(self, seq: int, value: Any) -> None:
        """Ship one execution's payload (ack-window gated). `value`
        may be a BaseException — it rides as kind "e" and re-raises at
        the consumer (user errors propagate without killing the
        pipeline)."""
        if self._closed or self._conn is None:
            raise CompiledDagError(
                f"channel {self.ch_id} is closed", cause="teardown")
        if isinstance(value, BaseException):
            kind, data = "e", cloudpickle.dumps(value, protocol=5)
        else:
            try:
                kind, data = "b", serialization.pack(value)
            except Exception as e:  # unpicklable stage result
                from ..exceptions import TaskError  # noqa: PLC0415
                kind = "e"
                data = cloudpickle.dumps(TaskError(
                    f"result not serializable: {e!r}"), protocol=5)
        if kind == "b" and self.same_node and len(data) > INLINE_MAX:
            # the segment is about to be rewritten in place: every
            # in-flight payload (inline or previous segment write)
            # must be consumed first
            self._drain_acks(0)
            if self._seg is None:
                self._seg = ChannelSegment(
                    f"rtpu_dagch_{self.ch_id}", self._capacity)
            ref: Any = self._seg.write(data)
            kind = "s"
        else:
            self._drain_acks(self._depth - 1)
            ref = data
        try:
            self._conn.send(("ch_notify", self.ch_id, seq, kind,
                             len(data), ref))
        except ConnectionClosed as e:
            raise CompiledDagError(
                f"channel {self.ch_id} reader went away", cause=repr(e)
            ) from e
        self._outstanding.append(seq)
        if seq > 1:
            try:
                _mcat().get("ray_tpu_dag_channel_reuse_total").inc()
            except Exception:
                pass

    def close(self) -> None:
        self._closed = True
        if self._conn is not None:
            try:
                self._conn.close()
            except Exception:
                pass
            self._conn = None
        if self._seg is not None:
            self._seg.close()
            self._seg = None


class ChannelReader:
    """Reader end: a queue fed by the host's per-connection pump."""

    def __init__(self, ch_id: str):
        self.ch_id = ch_id
        self.q: "queue.Queue" = queue.Queue()
        self._segr = ChannelSegmentReader()

    def read_value(self, timeout: Optional[float] = None
                   ) -> Tuple[int, Any]:
        """(seq, value) of the next execution; value is the exception
        instance itself for kind-"e" payloads. Consuming acks the seqno
        (the copy out of the shm window happens first, so the writer is
        free to overwrite)."""
        # Park lazily: in a full pipeline the next item arrives within
        # microseconds, and a park/unpark pair would tax every stage
        # hop. Only a read still empty after the grace gets a record.
        grace = waits_mod.PARK_GRACE_S if timeout is None \
            else min(waits_mod.PARK_GRACE_S, timeout)
        tok = 0
        try:
            try:
                item = self.q.get(timeout=grace)
            except queue.Empty:
                if timeout is not None and timeout <= grace:
                    raise
                tok = waits_mod.park("dag-channel", self.ch_id,
                                     op="read")
                item = self.q.get(
                    timeout=None if timeout is None
                    else timeout - grace)
        except queue.Empty:
            raise ChannelClosed(f"channel {self.ch_id} read timeout") \
                from None
        finally:
            waits_mod.unpark(tok)
        if item[0] is None:
            raise ChannelClosed(
                f"channel {self.ch_id}: {item[1]}")
        conn, seq, kind, size, ref = item
        if kind == "s":
            data: Any = bytes(self._segr.view(ref, size))
        else:
            data = ref
        if kind == "e":
            value: Any = pickle.loads(data)
        else:
            value = serialization.unpack(data)
        try:
            conn.send(("ch_ack", self.ch_id, seq))
        except ConnectionClosed:
            pass  # writer died; its driver-side death handling owns this
        return seq, value

    def close(self) -> None:
        self._segr.close()
        self.q.put((None, "channel torn down"))


class ChannelHost:
    """Per-process listener that demuxes inbound channel sockets to
    registered ChannelReaders. One host serves every compiled DAG in
    the process (channel ids are globally unique)."""

    def __init__(self, prefer_tcp: bool, label: str):
        import os  # noqa: PLC0415
        import tempfile  # noqa: PLC0415
        self._readers: Dict[str, ChannelReader] = {}
        self._lock = threading.Lock()
        self._conns: list = []
        self._sock_path = None
        if prefer_tcp:
            from ..util.netutil import routable_ip  # noqa: PLC0415
            from .protocol import tcp_listener  # noqa: PLC0415
            self._listener = tcp_listener("0.0.0.0", 0)
            port = self._listener.getsockname()[1]
            self.address = f"tcp://{routable_ip()}:{port}"
        else:
            from .protocol import unix_listener  # noqa: PLC0415
            base = knobs.get_raw("RAY_TPU_LOG_DIR")
            if not base or not os.path.isdir(base):
                base = tempfile.mkdtemp(prefix="ray_tpu_dagch_")
            self._sock_path = os.path.join(
                base, f"dagch-{label}-{os.getpid()}.sock")
            self._listener = unix_listener(self._sock_path)
            self.address = self._sock_path
        threading.Thread(target=self._accept, daemon=True,
                         name="dagch-accept").start()

    def register(self, ch_id: str) -> ChannelReader:
        r = ChannelReader(ch_id)
        with self._lock:
            self._readers[ch_id] = r
        return r

    def unregister(self, ch_id: str) -> None:
        with self._lock:
            r = self._readers.pop(ch_id, None)
        if r is not None:
            r.close()

    def _accept(self) -> None:
        while True:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            conn = Connection(sock)
            self._conns.append(conn)
            threading.Thread(target=self._pump, args=(conn,),
                             daemon=True, name="dagch-pump").start()

    def _pump(self, conn: Connection) -> None:
        reader: Optional[ChannelReader] = None
        while True:
            try:
                # raylint: disable=RT003 inbound channel socket: the
                # writer's teardown/death closes it, unblocking here
                m = conn.recv()
            except ConnectionClosed:
                if reader is not None:
                    reader.q.put((None, "writer socket closed"))
                return
            if m[0] == "ch_open":
                with self._lock:
                    reader = self._readers.get(m[2])
                if reader is None:
                    try:
                        conn.send(("ch_err", m[2], 0,
                                   "unknown channel (torn down?)"))
                        conn.close()
                    except ConnectionClosed:
                        pass
                    return
            elif m[0] == "ch_notify" and reader is not None:
                reader.q.put((conn, m[2], m[3], m[4], m[5]))

    def close(self) -> None:
        import os  # noqa: PLC0415
        try:
            self._listener.close()
        except Exception:
            pass
        if self._sock_path:
            try:
                os.unlink(self._sock_path)
            except OSError:
                pass
        for c in self._conns:
            try:
                c.close()
            except Exception:
                pass
        with self._lock:
            readers = list(self._readers.values())
            self._readers.clear()
        for r in readers:
            r.close()
