"""runtime_env: per-task/actor environment propagation.

Reference counterpart: python/ray/runtime_env (RuntimeEnv with env_vars,
working_dir, py_modules, conda/pip). In-image scope (SURVEY.md §2.1
C20): env_vars, working_dir, and py_modules path injection — no conda/
pip installers. Applied inside the worker: permanently for dedicated
actor workers, scoped (set/restore) for shared task workers.
"""
from __future__ import annotations

import contextlib
import os
import sys
from typing import Any, Dict, Iterator, List, Optional

_SUPPORTED = ("env_vars", "working_dir", "py_modules")


def validate(runtime_env: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    if not runtime_env:
        return {}
    unknown = set(runtime_env) - set(_SUPPORTED)
    if unknown:
        raise ValueError(
            f"unsupported runtime_env keys {sorted(unknown)}; "
            f"supported: {_SUPPORTED} (conda/pip are documented scope "
            "cuts — no installers in-image)")
    ev = runtime_env.get("env_vars", {})
    if ev and not all(isinstance(k, str) and isinstance(v, str)
                      for k, v in ev.items()):
        raise ValueError("env_vars must be Dict[str, str]")
    wd = runtime_env.get("working_dir")
    if wd is not None and not isinstance(wd, str):
        raise ValueError("working_dir must be a path string")
    return dict(runtime_env)


def apply_permanent(runtime_env: Optional[Dict[str, Any]]) -> None:
    """Apply to this process for good — dedicated actor workers."""
    if not runtime_env:
        return
    for k, v in runtime_env.get("env_vars", {}).items():
        os.environ[k] = v
    wd = runtime_env.get("working_dir")
    if wd:
        os.chdir(wd)
    for p in runtime_env.get("py_modules", []) or []:
        if p not in sys.path:
            sys.path.insert(0, p)


@contextlib.contextmanager
def applied(runtime_env: Optional[Dict[str, Any]]) -> Iterator[None]:
    """Scoped apply/restore — shared task workers run many tasks, each
    task's env must not leak into the next."""
    if not runtime_env:
        yield
        return
    saved_env: Dict[str, Optional[str]] = {}
    saved_cwd = os.getcwd()
    added_paths: List[str] = []
    try:
        for k, v in runtime_env.get("env_vars", {}).items():
            saved_env[k] = os.environ.get(k)
            os.environ[k] = v
        wd = runtime_env.get("working_dir")
        if wd:
            os.chdir(wd)
        for p in runtime_env.get("py_modules", []) or []:
            if p not in sys.path:
                sys.path.insert(0, p)
                added_paths.append(p)
        yield
    finally:
        for k, old in saved_env.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        os.chdir(saved_cwd)
        for p in added_paths:
            with contextlib.suppress(ValueError):
                sys.path.remove(p)
