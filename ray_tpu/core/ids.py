"""Unique identifiers for tasks / actors / objects / nodes.

Reference parity: src/ray/common/id.h defines binary TaskID/ObjectID/ActorID
with lineage encoded in the bytes. We keep ids opaque 16-byte hex strings —
lineage lives in the GCS tables instead, which is simpler and sufficient for
a single-controller runtime.
"""
from __future__ import annotations

import os
import random
import threading

_lock = threading.Lock()
_counter = 0
# Fast per-process PRNG seeded once from the OS: os.urandom per id cost
# more than the rest of spec creation combined (~200us/task of a 1k-task
# fan-out was urandom syscalls). Uniqueness comes from the pid+counter
# prefix; the random suffix only guards against pid reuse, so a seeded
# Mersenne twister is plenty. Re-seeded on fork (pid check).
_rng: random.Random = random.Random()
_rng_pid = 0
_FMT: dict = {}


def rand_hex(nhex: int) -> str:
    """nhex random hex chars from the per-process fast PRNG (also the
    backing generator for trace/span ids in util/tracing.py)."""
    global _rng, _rng_pid
    pid = os.getpid()
    if _rng_pid != pid:
        _rng = random.Random(int.from_bytes(os.urandom(16), "little"))
        _rng_pid = pid
    fmt = _FMT.get(nhex)
    if fmt is None:
        fmt = _FMT[nhex] = "%0" + str(nhex) + "x"
    return fmt % _rng.getrandbits(nhex * 4)


def _rand_hex(nbytes: int = 12) -> str:
    global _counter
    with _lock:
        _counter += 1
        c = _counter
        # pid + counter prefix keeps ids unique across forked workers
        # without coordination; random suffix guards against pid reuse.
        suffix = rand_hex((nbytes - 8) * 2)
    return f"{os.getpid():08x}{c:08x}{suffix}"


def new_object_id() -> str:
    return "obj-" + _rand_hex()


def new_task_id() -> str:
    return "tsk-" + _rand_hex()


def new_actor_id() -> str:
    return "act-" + _rand_hex()


def new_node_id() -> str:
    return "nod-" + _rand_hex()


def new_placement_group_id() -> str:
    return "pgr-" + _rand_hex()
