"""Unique identifiers for tasks / actors / objects / nodes.

Reference parity: src/ray/common/id.h defines binary TaskID/ObjectID/ActorID
with lineage encoded in the bytes. We keep ids opaque 16-byte hex strings —
lineage lives in the GCS tables instead, which is simpler and sufficient for
a single-controller runtime.
"""
from __future__ import annotations

import os
import threading

_lock = threading.Lock()
_counter = 0


def _rand_hex(nbytes: int = 12) -> str:
    global _counter
    with _lock:
        _counter += 1
        c = _counter
    # pid + counter prefix keeps ids unique across forked workers without
    # coordination; random suffix guards against pid reuse.
    return f"{os.getpid():08x}{c:08x}" + os.urandom(nbytes - 8).hex()


def new_object_id() -> str:
    return "obj-" + _rand_hex()


def new_task_id() -> str:
    return "tsk-" + _rand_hex()


def new_actor_id() -> str:
    return "act-" + _rand_hex()


def new_node_id() -> str:
    return "nod-" + _rand_hex()


def new_placement_group_id() -> str:
    return "pgr-" + _rand_hex()
