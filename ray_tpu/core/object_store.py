"""Shared-memory object store (plasma equivalent).

Reference parity: src/ray/object_manager/plasma/ — a per-node shared-memory
arena holding immutable sealed objects, with eviction and zero-copy reads.

Two backends behind one interface:
  * NativeStore — the C++ arena in ray_tpu/_native/object_store.cc (one mmap
    region, allocator + refcounts + LRU in native code), used when the
    compiled library is available.
  * ShmStore — pure-Python fallback using one POSIX shared-memory segment
    per large object.

Small objects (<= INLINE_MAX) never touch shared memory: they ride inline in
control-plane messages and live in the driver's in-memory table, mirroring
the reference's in-band "plasma promotion" threshold
(src/ray/common/ray_config_def.h RAY_CONFIG(int64_t, max_direct_call_object_size)).
"""
from __future__ import annotations

import dataclasses
import os
import threading
from typing import Any, Dict, Optional

from multiprocessing import shared_memory, resource_tracker

from . import serialization
from ..exceptions import ObjectStoreFullError, ObjectLostError
from ..util import knobs

INLINE_MAX = 64 * 1024


_mcat_mod = None


def record_read(result: str) -> None:
    """Count one object read by outcome ("inline" | "hit" | "spill").
    Shared by ShmStore and the native arena binding; never raises — a
    metrics hiccup must not fail a read. The catalog module is cached
    after the first call (reads are per-get hot)."""
    global _mcat_mod
    try:
        if _mcat_mod is None:
            from ..util import metrics_catalog  # noqa: PLC0415
            _mcat_mod = metrics_catalog
        _mcat_mod.get("ray_tpu_object_store_reads_total").inc(
            tags={"result": result})
    except Exception:
        pass


@dataclasses.dataclass
class ObjectLocation:
    """Picklable descriptor of where a sealed object's payload lives."""
    kind: str                      # "inline" | "shm" | "native" | "spill"
    size: int
    data: Optional[bytes] = None   # inline payload
    name: Optional[str] = None     # shm segment name / spill file path
    # Which node's store holds the payload. None = the driver's node (the
    # single-host case and all pre-multihost callers). Cross-node reads go
    # through the driver's fetch path instead of attaching shm.
    node_id: Optional[str] = None
    # Disk copy written by the SpillManager; readers fall back to it when
    # the arena copy has been evicted (core/spilling.py).
    spill_path: Optional[str] = None
    # Which seal GENERATION of the object this location belongs to
    # (stamped by GCS.seal_object): a reader's unreachable report names
    # the generation it failed against, so a report that raced a
    # lineage reseal can't prune the fresh copy.
    seal_seq: Optional[int] = None


def current_node_id() -> Optional[str]:
    """The node this process's store writes into (env-inherited from the
    driver or node agent that spawned it)."""
    return knobs.get_raw("RAY_TPU_NODE_ID")


def _read_spill_loc(loc: "ObjectLocation") -> bytes:
    path = loc.spill_path or (loc.name if loc.kind == "spill" else None)
    if not path:
        raise ObjectLostError(
            f"segment {loc.name} is gone (evicted?) and has no spill copy")
    try:
        with open(path, "rb") as f:
            return f.read()
    except OSError as e:
        raise ObjectLostError(
            f"spill file {path} unreadable: {e}") from e


def _untrack(shm: shared_memory.SharedMemory) -> None:
    # Attachments must not be auto-unlinked by this process's resource
    # tracker: the creator (driver store) owns segment lifecycle.
    try:
        resource_tracker.unregister(shm._name, "shared_memory")  # type: ignore[attr-defined]
    except Exception:
        pass


class ShmStore:
    """Per-process view of the node's shared-memory object space."""

    def __init__(self, capacity_bytes: int = 8 << 30, is_owner: bool = False):
        self.capacity = capacity_bytes
        self.is_owner = is_owner
        self._used = 0
        self._segments: Dict[str, shared_memory.SharedMemory] = {}
        # Names THIS process created via put_value: _used only ever counted
        # those, so deletes must only decrement for them — unlinking a
        # worker-created segment must not corrupt the owner's accounting.
        self._created: set = set()
        self._lock = threading.Lock()
        # put_packed re-host synchronization: names this process is
        # mid-write on; waiters block on the condition until the seal
        # completes (a FileExistsError alone can't distinguish "sealed"
        # from "still being written")
        self._packing: set = set()
        self._pack_cond = threading.Condition(self._lock)

    # -- write path ---------------------------------------------------------
    def put_value(self, oid: str, value: Any) -> ObjectLocation:
        """Serialize and seal a value; choose inline vs shm by size."""
        meta, bufs = serialization.serialize(value)
        size = serialization.packed_size(meta, bufs)
        if size <= INLINE_MAX:
            return ObjectLocation(kind="inline", size=size,
                                  data=serialization.pack_parts(meta, bufs))
        name = "rtpu_" + oid.replace("-", "")
        with self._lock:
            # a reseal of an oid THIS process already holds replaces the
            # stale segment (see the FileExistsError path below), so its
            # size must not count against the new copy's admission
            old_seg = self._segments.get(name)
            stale_sz = old_seg.size \
                if old_seg is not None and name in self._created else 0
            if self._used - stale_sz + size > self.capacity:
                raise ObjectStoreFullError(
                    f"object {oid} ({size} B) exceeds store capacity "
                    f"({self._used}/{self.capacity} B used)")
        try:
            seg = shared_memory.SharedMemory(name=name, create=True,
                                             size=size)
        except FileExistsError:
            # lineage re-execution resealing an oid whose stale segment
            # still lives on this node (same-node re-run after a loss,
            # or a rejoined host): unlink the old copy — readers already
            # attached keep their mappings — and seal fresh
            with self._lock:
                old = self._segments.pop(name, None)
                if name in self._created:
                    self._created.discard(name)
                    self._used -= old.size if old is not None else 0
            # unlink via a FRESH attach handle, never via `old`: the old
            # handle may hold exported zero-copy views whose close()
            # raises BufferError and would skip the unlink. The old
            # mapping (and any readers') stays valid after unlink.
            try:
                stale = shared_memory.SharedMemory(name=name)
                stale.unlink()
                stale.close()
            except Exception:
                pass
            if old is not None:
                try:
                    old.close()   # release this process's stale mmap/fd
                except BufferError:
                    pass  # live zero-copy exports: mapping must stay
            seg = shared_memory.SharedMemory(name=name, create=True,
                                             size=size)
        try:
            serialization.pack_into(seg.buf, meta, bufs)
        except BaseException:
            seg.close()
            seg.unlink()
            raise
        with self._lock:
            self._segments[name] = seg
            self._created.add(name)
            self._used += size
        return ObjectLocation(kind="shm", size=size, name=name,
                              node_id=current_node_id())

    # -- read path ----------------------------------------------------------
    def get_value(self, loc: ObjectLocation) -> Any:
        if loc.kind == "inline":
            record_read("inline")
            return serialization.unpack(loc.data)
        if loc.kind == "spill":
            record_read("spill")
            return serialization.unpack(_read_spill_loc(loc))
        if loc.kind == "shm":
            try:
                seg = self._attach(loc.name)
            except ObjectLostError:
                # evicted from shm, but a spill copy survives on disk
                record_read("spill")
                return serialization.unpack(_read_spill_loc(loc))
            # memoryview aliases the mapped pages -> zero-copy numpy reads.
            record_read("hit")
            return serialization.unpack(seg.buf[:loc.size])
        raise ObjectLostError(f"unknown location kind {loc.kind!r}")

    def get_bytes(self, loc: ObjectLocation) -> bytes:
        """Raw packed payload — the cross-node transfer unit (the remote
        side rebuilds the value with serialization.unpack)."""
        if loc.kind == "inline":
            record_read("inline")
            return loc.data
        if loc.kind == "spill":
            record_read("spill")
            return _read_spill_loc(loc)
        if loc.kind == "shm":
            try:
                seg = self._attach(loc.name)
            except ObjectLostError:
                record_read("spill")
                return _read_spill_loc(loc)
            record_read("hit")
            return bytes(seg.buf[:loc.size])
        raise ObjectLostError(f"unknown location kind {loc.kind!r}")

    def get_buffer(self, loc: ObjectLocation):
        """Packed payload as a buffer for the transfer plane: a
        zero-copy view of the mapped shm pages when the segment is
        resident, bytes otherwise (inline / spill fallback)."""
        if loc.kind == "shm":
            try:
                seg = self._attach(loc.name)
            except ObjectLostError:
                record_read("spill")
                return _read_spill_loc(loc)
            record_read("hit")
            return seg.buf[:loc.size]
        return self.get_bytes(loc)

    def put_packed(self, oid: str, data: bytes) -> ObjectLocation:
        """Seal an already-packed payload (a cross-node fetch re-hosted
        into this node's store, so local readers get zero-copy shm)."""
        size = len(data)
        if size <= INLINE_MAX:
            return ObjectLocation(kind="inline", size=size, data=data)
        # pid-suffixed: two PROCESSES re-hosting one object (driver relay
        # + agent pull on a shared-host topology) must never share a
        # segment name — a FileExistsError there can't distinguish
        # "sealed" from "mid-write", and a torn read is silent corruption
        name = f"rtpu_{oid.replace('-', '')}c{os.getpid():x}"
        loc = ObjectLocation(kind="shm", size=size, name=name,
                             node_id=current_node_id())
        with self._pack_cond:
            # concurrent re-hosts of the same object (two helper threads
            # fetching it for two requesters): wait for the writer, then
            # reuse its sealed segment instead of reading a torn copy —
            # BEFORE the capacity check, or a repeat seal of a large
            # already-hosted object would spuriously report a full store
            while name in self._packing:
                self._pack_cond.wait(timeout=30)
            if name in self._segments:
                return loc
            if self._used + size > self.capacity:
                raise ObjectStoreFullError(
                    f"object {oid} ({size} B) exceeds store capacity")
            try:
                seg = shared_memory.SharedMemory(name=name, create=True,
                                                 size=size)
            except FileExistsError:
                # another PROCESS sealed (or is sealing) it — objects are
                # immutable, so an existing segment is this payload; the
                # cross-process mid-write window only exists when two
                # stores share one host's shm namespace (test topologies)
                return loc
            self._packing.add(name)
        ok = False
        try:
            seg.buf[:size] = data
            ok = True
        finally:
            with self._pack_cond:
                self._packing.discard(name)
                if ok:
                    self._segments[name] = seg
                    self._created.add(name)
                    self._used += size
                self._pack_cond.notify_all()
            if not ok:
                seg.close()
                try:
                    seg.unlink()
                except FileNotFoundError:
                    pass
        return loc

    def _attach(self, name: str) -> shared_memory.SharedMemory:
        with self._lock:
            seg = self._segments.get(name)
            if seg is not None:
                return seg
        try:
            seg = shared_memory.SharedMemory(name=name, create=False)
        except FileNotFoundError as e:
            raise ObjectLostError(f"segment {name} is gone (evicted?)") from e
        _untrack(seg)
        with self._lock:
            self._segments.setdefault(name, seg)
        return self._segments[name]

    # -- lifecycle ----------------------------------------------------------
    def release(self, name: str) -> None:
        """Drop this process's mapping (not the segment itself)."""
        with self._lock:
            seg = self._segments.pop(name, None)
        if seg is not None:
            seg.close()

    def delete_segment(self, name: str, size: int) -> None:
        """Owner-side unlink (eviction / free)."""
        with self._lock:
            seg = self._segments.pop(name, None)
            created_here = name in self._created
            self._created.discard(name)
        if seg is None:
            try:
                seg = shared_memory.SharedMemory(name=name, create=False)
                _untrack(seg)
            except FileNotFoundError:
                return
        seg.close()
        if self.is_owner:
            try:
                seg.unlink()
            except FileNotFoundError:
                pass
            if created_here:
                with self._lock:
                    self._used = max(0, self._used - size)

    def used_bytes(self) -> int:
        return self._used

    def shutdown(self) -> None:
        with self._lock:
            segments = dict(self._segments)
            self._segments.clear()
        for name, seg in segments.items():
            seg.close()
            if self.is_owner:
                try:
                    seg.unlink()
                except FileNotFoundError:
                    pass
        self._used = 0


class ChannelSegment:
    """A reusable shared-memory window for one compiled-DAG channel.

    Unlike store objects (immutable, allocate/seal/free per value), a
    channel segment is REWRITTEN every execution: the writer copies the
    packed payload at offset 0 and notifies the reader with
    (seqno, size, segment_name) over the channel socket; the depth-1
    ack handshake guarantees the reader consumed seqno N before the
    writer overwrites with N+1, so no header or fence lives in the
    segment itself. Growth allocates a fresh generation-suffixed
    segment (the notify frame carries the name, so readers re-attach
    lazily) and unlinks the outgrown one."""

    def __init__(self, base_name: str, capacity: int):
        self.base_name = base_name
        self.capacity = max(int(capacity), 1 << 12)
        self.gen = 0
        self._seg = shared_memory.SharedMemory(
            name=self._name(), create=True, size=self.capacity)

    def _name(self) -> str:
        return f"{self.base_name}g{self.gen}"

    @property
    def name(self) -> str:
        return self._name()

    def write(self, payload) -> str:
        """Copy payload into the segment (growing it if needed);
        returns the segment name the reader should attach."""
        size = len(payload)
        if size > self.capacity:
            old = self._seg
            while self.capacity < size:
                self.capacity *= 2
            self.gen += 1
            self._seg = shared_memory.SharedMemory(
                name=self._name(), create=True, size=self.capacity)
            old.close()
            try:
                old.unlink()
            except FileNotFoundError:
                pass
        self._seg.buf[:size] = payload
        return self._name()

    def close(self) -> None:
        seg, self._seg = self._seg, None
        if seg is not None:
            seg.close()
            try:
                seg.unlink()
            except FileNotFoundError:
                pass


class ChannelSegmentReader:
    """Reader-side attachment cache for a channel's segments. The
    writer's growth protocol changes the segment name at most a few
    times over a channel's life; everything else is one cached-mmap
    memoryview slice per read."""

    def __init__(self):
        self._seg = None
        self._name = None

    def view(self, name: str, size: int) -> memoryview:
        if name != self._name:
            self.close()
            seg = shared_memory.SharedMemory(name=name, create=False)
            _untrack(seg)
            self._seg, self._name = seg, name
        return self._seg.buf[:size]

    def close(self) -> None:
        seg, self._seg = self._seg, None
        self._name = None
        if seg is not None:
            seg.close()


def make_store(capacity_bytes: int, is_owner: bool):
    """Return the best available store backend (native C++ if built)."""
    try:
        from .._native.store_binding import NativeStore  # noqa: PLC0415
        return NativeStore(capacity_bytes=capacity_bytes, is_owner=is_owner)
    except Exception:
        return ShmStore(capacity_bytes=capacity_bytes, is_owner=is_owner)
