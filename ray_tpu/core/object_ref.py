"""ObjectRef — distributed future handle.

Reference parity: ObjectRef in python/ray/_raylet.pyx plus ownership notes
in python/ray/includes/object_ref.pxi. Refs are cheap, picklable, hashable,
awaitable, and resolve through whichever runtime (driver or worker) the
current process hosts.
"""
from __future__ import annotations

import asyncio
from typing import Any


class ObjectRef:
    __slots__ = ("id", "_owner_hint")

    def __init__(self, object_id: str, owner_hint: str = ""):
        self.id = object_id
        self._owner_hint = owner_hint

    def hex(self) -> str:
        return self.id

    def binary(self) -> bytes:
        return self.id.encode()

    def __repr__(self) -> str:
        return f"ObjectRef({self.id})"

    def __hash__(self) -> int:
        return hash(self.id)

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, ObjectRef) and other.id == self.id

    def __reduce__(self):
        # Escape hook for the direct-call plane: serializing a ref means
        # it may reach a reader that resolves through the driver, so a
        # worker holding this oid as a LOCAL direct-call future must
        # publish the value driver-side (WorkerRuntime.on_ref_serialized;
        # no-op on the driver and for ordinary refs).
        from . import runtime  # noqa: PLC0415
        rt = runtime._runtime
        if rt is not None:
            hook = getattr(rt, "on_ref_serialized", None)
            if hook is not None:
                try:
                    hook(self.id)
                except Exception:
                    pass
        return (ObjectRef, (self.id, self._owner_hint))

    # Support `await ref` inside async actors / drivers.
    def __await__(self):
        return self.as_future().__await__()

    def as_future(self) -> "asyncio.Future":
        from . import runtime  # noqa: PLC0415
        loop = asyncio.get_event_loop()
        fut: asyncio.Future = loop.create_future()

        def _resolve():
            rt = runtime.get_runtime()
            try:
                val = rt.get([self], timeout=None)[0]
                loop.call_soon_threadsafe(
                    lambda: fut.done() or fut.set_result(val))
            except BaseException as e:  # noqa: BLE001
                loop.call_soon_threadsafe(
                    lambda: fut.done() or fut.set_exception(e))

        import threading  # noqa: PLC0415
        threading.Thread(target=_resolve, daemon=True).start()
        return fut

    def future(self) -> "asyncio.Future":
        return self.as_future()


class ObjectRefGenerator:
    """Handle to a streaming-generator task (num_returns="streaming").

    Iterating yields ObjectRefs in the order the remote generator yields
    values; each ref resolves via ray_tpu.get. Works from the driver and
    from inside workers, and survives serialization (it carries only the
    task id). Reference parity: ObjectRefGenerator in _raylet.pyx.
    """
    __slots__ = ("_task_id",)

    def __init__(self, task_id: str):
        self._task_id = task_id

    @property
    def task_id(self) -> str:
        return self._task_id

    def __iter__(self) -> "ObjectRefGenerator":
        return self

    def __next__(self) -> ObjectRef:
        from . import runtime  # noqa: PLC0415
        ref = runtime.get_runtime().gen_next(self._task_id, timeout=None)
        if ref is None:
            raise StopIteration
        return ref

    def __aiter__(self) -> "ObjectRefGenerator":
        return self

    async def __anext__(self) -> ObjectRef:
        import asyncio  # noqa: PLC0415
        from . import runtime  # noqa: PLC0415
        rt = runtime.get_runtime()
        ref = await asyncio.get_event_loop().run_in_executor(
            None, lambda: rt.gen_next(self._task_id, timeout=None))
        if ref is None:
            raise StopAsyncIteration
        return ref

    def __reduce__(self):
        return (ObjectRefGenerator, (self._task_id,))

    def __repr__(self):
        return f"ObjectRefGenerator({self._task_id})"
