"""ObjectRef — distributed future handle.

Reference parity: ObjectRef in python/ray/_raylet.pyx plus ownership notes
in python/ray/includes/object_ref.pxi. Refs are cheap, picklable, hashable,
awaitable, and resolve through whichever runtime (driver or worker) the
current process hosts.
"""
from __future__ import annotations

import asyncio
from typing import Any


class ObjectRef:
    __slots__ = ("id", "_owner_hint")

    def __init__(self, object_id: str, owner_hint: str = ""):
        self.id = object_id
        self._owner_hint = owner_hint

    def hex(self) -> str:
        return self.id

    def binary(self) -> bytes:
        return self.id.encode()

    def __repr__(self) -> str:
        return f"ObjectRef({self.id})"

    def __hash__(self) -> int:
        return hash(self.id)

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, ObjectRef) and other.id == self.id

    def __reduce__(self):
        return (ObjectRef, (self.id, self._owner_hint))

    # Support `await ref` inside async actors / drivers.
    def __await__(self):
        return self.as_future().__await__()

    def as_future(self) -> "asyncio.Future":
        from . import runtime  # noqa: PLC0415
        loop = asyncio.get_event_loop()
        fut: asyncio.Future = loop.create_future()

        def _resolve():
            rt = runtime.get_runtime()
            try:
                val = rt.get([self], timeout=None)[0]
                loop.call_soon_threadsafe(
                    lambda: fut.done() or fut.set_result(val))
            except BaseException as e:  # noqa: BLE001
                loop.call_soon_threadsafe(
                    lambda: fut.done() or fut.set_exception(e))

        import threading  # noqa: PLC0415
        threading.Thread(target=_resolve, daemon=True).start()
        return fut

    def future(self) -> "asyncio.Future":
        return self.as_future()
