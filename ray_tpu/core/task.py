"""Task & actor specs plus the user-facing RemoteFunction wrapper.

Reference parity: python/ray/remote_function.py (RemoteFunction, .options),
src/ray/common/task/task_spec.h (TaskSpec fields).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from . import serialization
from .ids import new_task_id, new_object_id


@dataclasses.dataclass
class TaskSpec:
    task_id: str
    name: str
    func_bytes: bytes                  # cloudpickled callable (None for actor methods)
    args: Tuple = ()
    kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    num_returns: int = 1
    return_ids: List[str] = dataclasses.field(default_factory=list)
    resources: Dict[str, float] = dataclasses.field(default_factory=dict)
    max_retries: int = 0
    retry_exceptions: bool = False
    # recycle the executing worker after this many calls of this
    # function (0 = never; reference: @ray.remote(max_calls=N) for
    # leaky native libraries)
    max_calls: int = 0
    # streaming-generator task: yielded items become individually sealed
    # objects announced via "gen_item"; return_ids stays empty
    streaming: bool = False
    # actor fields
    actor_id: Optional[str] = None
    method_name: Optional[str] = None
    # named concurrency group (@ray_tpu.method(concurrency_group=...));
    # None = the actor's default max_concurrency lane
    concurrency_group: Optional[str] = None
    # placement
    placement_group_id: Optional[str] = None
    bundle_index: int = -1
    scheduling_strategy: Optional[Any] = None
    runtime_env: Optional[dict] = None
    # chip indices assigned by the dispatcher at dispatch time
    # (ray_tpu.get_tpu_ids inside the task reads these)
    tpu_ids: List[int] = dataclasses.field(default_factory=list)
    # bookkeeping
    func_id: str = ""                  # cache key for deserialized functions
    dep_object_ids: List[str] = dataclasses.field(default_factory=list)
    # times this task was re-queued by lineage reconstruction (a lost
    # output re-executing its producer; args referenced by ObjectRef
    # stay refs, so the retained spec is cheap unless args are by-value)
    reconstructions: int = 0
    # cross-process tracing (util/tracing.py): span_id names this task's
    # SUBMIT span; the executing worker opens a child execution span
    # parented to it, so the timeline links driver and worker sides
    trace_id: str = ""
    span_id: str = ""
    parent_span_id: str = ""
    # multi-slot lease this spec was granted under (runtime lease
    # dispatch stamps it); the worker's exec span carries it so the
    # timeline links every slot back to its lease-grant span
    lease_id: str = ""


@dataclasses.dataclass
class ActorCreationSpec:
    actor_id: str
    class_bytes: bytes
    class_name: str
    args: Tuple = ()
    kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    resources: Dict[str, float] = dataclasses.field(default_factory=dict)
    max_restarts: int = 0
    max_concurrency: int = 1
    # named method groups with INDEPENDENT concurrency limits
    # (reference: python/ray/actor.py concurrency_groups) — a slow
    # group can't starve e.g. health-check methods in another group
    concurrency_groups: Dict[str, int] = dataclasses.field(
        default_factory=dict)
    name: Optional[str] = None
    namespace: str = "default"
    # min seconds between __ray_save__ checkpoint ships (None = the
    # RAY_TPU_ACTOR_CHECKPOINT_INTERVAL_S default; only actors defining
    # the hook checkpoint at all)
    checkpoint_interval_s: Optional[float] = None
    placement_group_id: Optional[str] = None
    bundle_index: int = -1
    scheduling_strategy: Optional[Any] = None
    runtime_env: Optional[dict] = None
    tpu_ids: List[int] = dataclasses.field(default_factory=list)
    # @ray_tpu.method defaults per method name, carried so handles from
    # get_actor() behave identically to the creation-time handle
    method_opts: Dict[str, Dict[str, Any]] = dataclasses.field(
        default_factory=dict)
    dep_object_ids: List[str] = dataclasses.field(default_factory=list)


def extract_arg_deps(args: Tuple, kwargs: Dict[str, Any]) -> List[str]:
    """Top-level ObjectRef args become scheduling dependencies; the worker
    substitutes their values before invoking the function (same contract as
    the reference: nested refs are passed through un-resolved)."""
    from .object_ref import ObjectRef  # noqa: PLC0415
    if not args and not kwargs:
        return []
    deps = []
    for a in args:
        if isinstance(a, ObjectRef):
            deps.append(a.id)
    for a in kwargs.values():
        if isinstance(a, ObjectRef):
            deps.append(a.id)
    return deps


def make_task_spec(func, args, kwargs, *, name=None, num_returns=1,
                   resources=None, max_retries=0, retry_exceptions=False,
                   max_calls=0, func_bytes=None, func_id="",
                   placement_group_id=None,
                   bundle_index=-1, scheduling_strategy=None,
                   runtime_env=None) -> TaskSpec:
    from ..util import tracing  # noqa: PLC0415
    tid = new_task_id()
    trace_id, span_id, parent_span_id = tracing.submit_context()
    spec = TaskSpec(
        task_id=tid,
        name=name or getattr(func, "__qualname__", "anonymous"),
        func_bytes=func_bytes if func_bytes is not None
        else serialization.dumps_call(func),
        args=tuple(args),
        kwargs=dict(kwargs or {}),
        num_returns=num_returns,
        return_ids=[new_object_id() for _ in range(max(num_returns, 1))],
        resources=dict(resources or {"CPU": 1.0}),
        max_retries=max_retries,
        retry_exceptions=retry_exceptions,
        max_calls=max_calls,
        func_id=func_id,
        placement_group_id=placement_group_id,
        bundle_index=bundle_index,
        scheduling_strategy=scheduling_strategy,
        runtime_env=runtime_env,
        dep_object_ids=extract_arg_deps(args, kwargs or {}),
        trace_id=trace_id, span_id=span_id,
        parent_span_id=parent_span_id,
    )
    return spec
