"""SampleBatch: columnar rollout storage as a dict-of-ndarray pytree.

Reference counterpart: rllib/policy/sample_batch.py (SampleBatch,
concat_samples). Ours is a thin dict wrapper whose values are numpy (host)
or jax arrays — it converts cleanly to a pytree for jitted learner updates.
"""
from __future__ import annotations

from typing import Dict, Iterator, List, Optional

import numpy as np

# Canonical column names (reference: SampleBatch.OBS etc.)
OBS = "obs"
ACTIONS = "actions"
REWARDS = "rewards"
TERMINATEDS = "terminateds"
TRUNCATEDS = "truncateds"
NEXT_OBS = "next_obs"
VALUES = "values"
LOGPS = "logps"
ADVANTAGES = "advantages"
RETURNS = "returns"


class SampleBatch(dict):
    """dict[str, np.ndarray] with equal leading (time/batch) dimension."""

    @property
    def count(self) -> int:
        for v in self.values():
            return len(v)
        return 0

    def shuffle(self, seed: Optional[int] = None) -> "SampleBatch":
        rng = np.random.default_rng(seed)
        perm = rng.permutation(self.count)
        return SampleBatch({k: np.asarray(v)[perm] for k, v in self.items()})

    def slice(self, start: int, end: int) -> "SampleBatch":
        return SampleBatch({k: np.asarray(v)[start:end]
                            for k, v in self.items()})

    def minibatches(self, size: int, *, drop_last: bool = True
                    ) -> Iterator["SampleBatch"]:
        n = self.count
        end = n - (n % size) if drop_last else n
        for i in range(0, end, size):
            yield self.slice(i, min(i + size, n))

    def as_numpy(self) -> Dict[str, np.ndarray]:
        return {k: np.asarray(v) for k, v in self.items()}

    def __repr__(self):
        cols = {k: tuple(np.shape(v)) for k, v in self.items()}
        return f"SampleBatch(count={self.count}, cols={cols})"


def concat_samples(batches: List[SampleBatch]) -> SampleBatch:
    """Reference: SampleBatch.concat_samples."""
    if not batches:
        return SampleBatch()
    keys = batches[0].keys()
    return SampleBatch({k: np.concatenate([np.asarray(b[k]) for b in batches])
                        for k in keys})


def compute_gae(rewards: np.ndarray, values: np.ndarray,
                terminateds: np.ndarray, last_value: np.ndarray,
                *, gamma: float = 0.99, lam: float = 0.95):
    """Generalized Advantage Estimation over a [T, B] rollout.

    Reference: rllib/evaluation/postprocessing.py::compute_advantages.
    Runs on host numpy — rollouts arrive on host anyway; the learner
    update (the hot path) is what's jitted.
    """
    T = rewards.shape[0]
    adv = np.zeros_like(rewards)
    lastgaelam = np.zeros_like(last_value)
    nextvalue = last_value
    nonterminal = 1.0 - terminateds.astype(np.float32)
    for t in reversed(range(T)):
        delta = rewards[t] + gamma * nextvalue * nonterminal[t] - values[t]
        lastgaelam = delta + gamma * lam * nonterminal[t] * lastgaelam
        adv[t] = lastgaelam
        nextvalue = values[t]
    returns = adv + values
    return adv, returns
