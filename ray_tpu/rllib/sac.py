"""Soft Actor-Critic: off-policy continuous control on JAX.

Reference counterpart: rllib/algorithms/sac/ (sac.py, sac_torch_policy
behaviors: twin soft-Q critics, tanh-squashed Gaussian actor, learned
entropy temperature against a target entropy, polyak target updates).
TPU-first shape: ONE jitted update advances actor + both critics +
alpha together (three optax updates fused in a single compiled step);
replay batches are the only host<->device traffic.

Proves the off-policy/Learner stack generalizes beyond policy-gradient
(VERDICT r3 item 10): reuses ReplayBuffer (R6), EnvRunner vec stepping,
and the Algorithm train loop.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..models.mlp import MLP, MLPConfig
from . import sample_batch as sb
from .algorithm import Algorithm, AlgorithmConfig
from .replay import ReplayBuffer
from .sample_batch import SampleBatch

LOG_STD_MIN, LOG_STD_MAX = -5.0, 2.0


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.buffer_size = 100_000
        self.learning_starts = 1500
        self.train_batch_size = 256
        self.num_gradient_steps = 32      # per training iteration
        self.tau = 0.005                  # polyak target coefficient
        self.actor_lr = 3e-4
        self.critic_lr = 3e-4
        self.alpha_lr = 3e-4
        self.initial_alpha = 0.1
        self.target_entropy: Any = "auto"  # "auto" = -act_dim
        self.algo_class = SAC


class SAC(Algorithm):
    def __init__(self, config: SACConfig):
        if config.num_env_runners > 0:
            raise ValueError("SAC collects via its local runner; "
                             "num_env_runners>0 is not supported")
        super().__init__(config)
        if self.module.is_discrete:
            raise ValueError("SAC needs a continuous (Box) action space")
        cfg = config
        space = self.local_runner.vec.envs[0].action_space
        self.act_dim = int(np.prod(space.shape))
        # per-dimension affine map from tanh's [-1, 1] to [low, high]
        # (r4 advice: float(space.high) raised on per-dim bounds, and a
        # symmetric [-s, s] was silently wrong when low != -high)
        high = np.broadcast_to(np.asarray(space.high, np.float32),
                               space.shape).reshape(-1)
        low = np.broadcast_to(np.asarray(space.low, np.float32),
                              space.shape).reshape(-1)
        if not (np.all(np.isfinite(high)) and np.all(np.isfinite(low))):
            raise ValueError(
                f"SAC needs finite Box bounds; got low={space.low} "
                f"high={space.high}")
        if np.any(high <= low):
            # a zero-width dim would make the log|scale| Jacobian term
            # -inf and NaN every update — reject loudly instead
            raise ValueError(
                f"SAC needs high > low on every action dim; got "
                f"low={space.low} high={space.high}")
        self.act_scale = (high - low) / 2.0     # (act_dim,)
        self.act_offset = (high + low) / 2.0    # (act_dim,)
        obs_dim = self.module.spec.obs_dim
        hidden = tuple(cfg.model["hidden"])
        act = cfg.model["activation"]

        # actor outputs (mean, log_std) per action dim; critics score
        # concat(obs, action)
        self.pi_net = MLP(MLPConfig(hidden=hidden,
                                    out_dim=2 * self.act_dim,
                                    activation=act))
        self.q_net = MLP(MLPConfig(hidden=hidden, out_dim=1,
                                   activation=act))
        k = jax.random.split(jax.random.PRNGKey(cfg.seed), 3)
        self.pi_params = self.pi_net.init_params(k[0], obs_dim)
        self.q_params = (
            self.q_net.init_params(k[1], obs_dim + self.act_dim),
            self.q_net.init_params(k[2], obs_dim + self.act_dim))
        self.target_q_params = jax.device_get(self.q_params)
        self.log_alpha = jnp.asarray(np.log(cfg.initial_alpha),
                                     jnp.float32)
        self.target_entropy = (-float(self.act_dim)
                               if cfg.target_entropy == "auto"
                               else float(cfg.target_entropy))
        self.pi_tx = optax.adam(cfg.actor_lr)
        self.q_tx = optax.adam(cfg.critic_lr)
        self.a_tx = optax.adam(cfg.alpha_lr)
        self.pi_opt = self.pi_tx.init(self.pi_params)
        self.q_opt = self.q_tx.init(self.q_params)
        self.a_opt = self.a_tx.init(self.log_alpha)
        self._rng_key = jax.random.PRNGKey(cfg.seed + 1)

        pi_net, q_net = self.pi_net, self.q_net
        scale = jnp.asarray(self.act_scale)
        offset = jnp.asarray(self.act_offset)
        tgt_h, tau, gamma = self.target_entropy, cfg.tau, cfg.gamma

        def squashed(pi_params, obs, key):
            """tanh-squashed Gaussian sample with its log-prob (in the
            ENV action space: the affine a*scale+offset Jacobian is
            part of the change of variables — r4 advice: omitting
            sum(log scale) shifted alpha's effective entropy target)."""
            out = pi_net.apply({"params": pi_params}, obs)
            mean, log_std = jnp.split(out, 2, axis=-1)
            log_std = jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)
            std = jnp.exp(log_std)
            pre = mean + std * jax.random.normal(key, mean.shape)
            a = jnp.tanh(pre)
            # Gaussian logp minus tanh + affine change-of-variables
            logp = (-0.5 * (((pre - mean) / std) ** 2
                            + 2 * log_std + jnp.log(2 * jnp.pi))
                    - jnp.log(1.0 - a ** 2 + 1e-6)).sum(-1) \
                - jnp.log(scale).sum()
            return a * scale + offset, logp

        def q_val(qp, obs, act):
            x = jnp.concatenate([obs, act], axis=-1)
            return q_net.apply({"params": qp}, x).squeeze(-1)

        def update(pi_params, q_params, target_q, log_alpha,
                   pi_opt, q_opt, a_opt, batch, key):
            k1, k2 = jax.random.split(key)
            alpha = jnp.exp(log_alpha)
            obs, acts = batch[sb.OBS], batch[sb.ACTIONS]
            nxt = batch[sb.NEXT_OBS]
            nonterminal = 1.0 - batch[sb.TERMINATEDS].astype(jnp.float32)

            a2, logp2 = squashed(pi_params, nxt, k1)
            tq = jnp.minimum(q_val(target_q[0], nxt, a2),
                             q_val(target_q[1], nxt, a2))
            y = jax.lax.stop_gradient(
                batch[sb.REWARDS] + gamma * nonterminal
                * (tq - alpha * logp2))

            def q_loss_fn(qp):
                l1 = jnp.mean((q_val(qp[0], obs, acts) - y) ** 2)
                l2 = jnp.mean((q_val(qp[1], obs, acts) - y) ** 2)
                return l1 + l2

            q_loss, q_grads = jax.value_and_grad(q_loss_fn)(q_params)
            q_up, q_opt = self.q_tx.update(q_grads, q_opt, q_params)
            q_params = optax.apply_updates(q_params, q_up)

            def pi_loss_fn(pp):
                a, logp = squashed(pp, obs, k2)
                qmin = jnp.minimum(q_val(q_params[0], obs, a),
                                   q_val(q_params[1], obs, a))
                return jnp.mean(alpha * logp - qmin), logp

            (pi_loss, logp), pi_grads = jax.value_and_grad(
                pi_loss_fn, has_aux=True)(pi_params)
            pi_up, pi_opt = self.pi_tx.update(pi_grads, pi_opt,
                                              pi_params)
            pi_params = optax.apply_updates(pi_params, pi_up)

            def a_loss_fn(la):
                return -jnp.mean(
                    la * jax.lax.stop_gradient(logp + tgt_h))

            a_loss, a_grad = jax.value_and_grad(a_loss_fn)(log_alpha)
            a_up, a_opt = self.a_tx.update(a_grad, a_opt, log_alpha)
            log_alpha = optax.apply_updates(log_alpha, a_up)

            target_q = jax.tree_util.tree_map(
                lambda t, q: t * (1.0 - tau) + q * tau, target_q,
                q_params)
            return (pi_params, q_params, target_q, log_alpha,
                    pi_opt, q_opt, a_opt,
                    {"q_loss": q_loss, "pi_loss": pi_loss,
                     "alpha": alpha, "entropy": -jnp.mean(logp)})

        self._update = jax.jit(update)
        self._sample_action = jax.jit(squashed)
        self._mean_action = jax.jit(
            lambda pp, obs: jnp.tanh(jnp.split(
                pi_net.apply({"params": pp}, obs), 2, axis=-1)[0])
            * scale + offset)

    # -- rollouts: squashed-Gaussian exploration on the vec env --
    def _collect(self):
        cfg: SACConfig = self.config
        runner = self.local_runner
        vec = runner.vec
        T = cfg.rollout_fragment_length
        cols = {k: [] for k in (sb.OBS, sb.ACTIONS, sb.REWARDS,
                                sb.TERMINATEDS, sb.NEXT_OBS)}
        obs = runner._obs
        for _ in range(T):
            self._rng_key, k = jax.random.split(self._rng_key)
            if self._timesteps_total < cfg.learning_starts:
                # uniform warmup like the reference's initial random
                # exploration
                acts = np.random.default_rng(
                    int(k[0]) % (1 << 31)).uniform(
                    self.act_offset - self.act_scale,
                    self.act_offset + self.act_scale,
                    size=(vec.num_envs, self.act_dim)).astype(np.float32)
            else:
                a, _ = self._sample_action(self.pi_params, obs, k)
                acts = np.asarray(a, np.float32)
            nxt, r, tm, tr, infos = vec.step(acts)
            runner._ep_ret += r
            runner._ep_len += 1
            nxt_true = nxt.copy()
            for i in np.nonzero(tm | tr)[0]:
                nxt_true[i] = infos[i]["final_obs"]
                runner.completed_returns.append(float(runner._ep_ret[i]))
                runner.completed_lengths.append(int(runner._ep_len[i]))
                runner._ep_ret[i] = 0.0
                runner._ep_len[i] = 0
            cols[sb.OBS].append(obs.copy())
            cols[sb.ACTIONS].append(acts)
            cols[sb.REWARDS].append(r.astype(np.float32))
            cols[sb.TERMINATEDS].append(tm)
            cols[sb.NEXT_OBS].append(nxt_true)
            obs = nxt
        runner._obs = obs
        flat = {k: np.concatenate(v) for k, v in cols.items()}
        return SampleBatch(flat), runner.pop_episode_stats()

    def training_step(self, batch: SampleBatch) -> Dict[str, Any]:
        cfg: SACConfig = self.config
        if not hasattr(self, "buffer"):
            self.buffer = ReplayBuffer(cfg.buffer_size, seed=cfg.seed)
        self.buffer.add(batch)
        if len(self.buffer) < cfg.learning_starts:
            return {"q_loss": None, "buffer_size": len(self.buffer)}
        stats = {}
        for _ in range(cfg.num_gradient_steps):
            mb = self.buffer.sample(cfg.train_batch_size).as_numpy()
            self._rng_key, k = jax.random.split(self._rng_key)
            (self.pi_params, self.q_params, self.target_q_params,
             self.log_alpha, self.pi_opt, self.q_opt, self.a_opt,
             stats) = self._update(
                self.pi_params, self.q_params, self.target_q_params,
                self.log_alpha, self.pi_opt, self.q_opt, self.a_opt,
                mb, k)
        return {**{k: float(v) for k, v in stats.items()},
                "buffer_size": len(self.buffer)}

    # -- evaluation with the squashed deterministic policy --
    def compute_single_action(self, obs, *, explore: bool = False):
        obs = np.asarray(obs, np.float32)[None]
        if explore:
            self._rng_key, k = jax.random.split(self._rng_key)
            a, _ = self._sample_action(self.pi_params, obs, k)
        else:
            a = self._mean_action(self.pi_params, obs)
        return np.asarray(a)[0]

    def evaluate(self) -> Dict[str, float]:
        from .env import make_env
        if not hasattr(self, "_eval_env"):
            self._eval_env = make_env(self.config.env,
                                      **self.config.env_config)
        env = self._eval_env
        rets = []
        for ep in range(self.config.evaluation_num_episodes):
            obs, _ = env.reset(seed=10_000 + ep)
            done, total = False, 0.0
            while not done:
                a = self.compute_single_action(obs)
                obs, r, tm, tr, _ = env.step(a)
                total += r
                done = tm or tr
            rets.append(total)
        return {"episode_return_mean": float(np.mean(rets)),
                "episodes": len(rets)}

    def _save_extra(self):
        return {k: jax.device_get(getattr(self, k)) for k in
                ("pi_params", "q_params", "target_q_params", "log_alpha",
                 "pi_opt", "q_opt", "a_opt")}

    def _restore_extra(self, extra):
        if extra:
            for k, v in extra.items():
                setattr(self, k, v)
