"""Learner / LearnerGroup: jitted gradient updates, optionally dp-sharded
over a TPU mesh.

Reference counterpart: rllib/core/learner/ (Learner, LearnerGroup). The
reference scales learners as one-GPU-per-actor with NCCL allreduce; here
a LearnerGroup is ONE jitted update function whose batch is sharded over
the mesh's dp axis — XLA emits the gradient psum, no comms code.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..parallel.mesh import MeshSpec  # noqa: F401  (re-export convenience)


class Learner:
    """Owns params + optimizer state and a jitted update(loss_fn)."""

    def __init__(self, params, *, loss_fn: Callable, tx: optax.GradientTransformation):
        self.tx = tx
        self.params = params
        self.opt_state = tx.init(params)
        self._loss_fn = loss_fn

        def _update(params, opt_state, batch, extra):
            (loss, stats), grads = jax.value_and_grad(
                self._loss_fn, has_aux=True)(params, batch, extra)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            gnorm = optax.global_norm(grads)
            stats = dict(stats, total_loss=loss, grad_norm=gnorm)
            return params, opt_state, stats

        self._update = jax.jit(_update)

    def update(self, batch: Dict[str, Any],
               extra: Any = 0.0) -> Dict[str, float]:
        self.params, self.opt_state, stats = self._update(
            self.params, self.opt_state, batch, extra)
        return {k: float(v) for k, v in stats.items()}


class LearnerGroup:
    """Data-parallel learner over a jax Mesh.

    Shards every batch column along the mesh dp axis; params are
    replicated. On a single device this degrades to a plain Learner.
    """

    def __init__(self, learner: Learner,
                 mesh: Optional[jax.sharding.Mesh] = None,
                 dp_axis: str = "dp"):
        self.learner = learner
        self.mesh = mesh
        if mesh is not None:
            P = jax.sharding.PartitionSpec
            self.batch_sharding = jax.sharding.NamedSharding(
                mesh, P(dp_axis))
            self.replicated = jax.sharding.NamedSharding(mesh, P())
            self.learner.params = jax.device_put(self.learner.params,
                                                 self.replicated)
            self.learner.opt_state = jax.device_put(self.learner.opt_state,
                                                    self.replicated)

    @property
    def params(self):
        return self.learner.params

    def update(self, batch: Dict[str, Any],
               extra: Any = 0.0) -> Dict[str, float]:
        if self.mesh is not None:
            n = self.mesh.devices.size
            batch = {k: self._pad_to(np.asarray(v), n)
                     for k, v in batch.items()}
            batch = jax.device_put(batch, self.batch_sharding)
        return self.learner.update(batch, extra)

    @staticmethod
    def _pad_to(x: np.ndarray, mult: int) -> np.ndarray:
        rem = len(x) % mult
        if rem == 0:
            return x
        # cycle rows so any batch size pads up, even len(x) < mult
        idx = np.arange(mult - rem) % len(x)
        return np.concatenate([x, x[idx]])
