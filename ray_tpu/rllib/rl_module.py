"""RLModule: flax policy(+value) networks and action distributions.

Reference counterpart: rllib/core/rl_module/ (RLModule, catalog-built
encoder + pi/vf heads) and rllib/models/distributions. TPU-first: the
module is a pure function of (params, obs) so the whole sampling/update
path jits; distributions are jnp-native (no torch.distributions).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.mlp import MLP, MLPConfig
from .env import Space


class Categorical:
    """Discrete action distribution over logits."""

    def __init__(self, logits: jnp.ndarray):
        self.logits = logits

    def sample(self, rng) -> jnp.ndarray:
        return jax.random.categorical(rng, self.logits, axis=-1)

    def mode(self) -> jnp.ndarray:
        return jnp.argmax(self.logits, axis=-1)

    def logp(self, actions: jnp.ndarray) -> jnp.ndarray:
        logp_all = jax.nn.log_softmax(self.logits, axis=-1)
        return jnp.take_along_axis(
            logp_all, actions[..., None].astype(jnp.int32), axis=-1
        ).squeeze(-1)

    def entropy(self) -> jnp.ndarray:
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return -jnp.sum(jnp.exp(logp) * logp, axis=-1)

    def kl(self, other: "Categorical") -> jnp.ndarray:
        lp, lq = (jax.nn.log_softmax(self.logits, -1),
                  jax.nn.log_softmax(other.logits, -1))
        return jnp.sum(jnp.exp(lp) * (lp - lq), axis=-1)


class DiagGaussian:
    """Continuous action distribution: independent normals."""

    def __init__(self, mean: jnp.ndarray, log_std: jnp.ndarray):
        self.mean = mean
        self.log_std = log_std

    def sample(self, rng) -> jnp.ndarray:
        eps = jax.random.normal(rng, self.mean.shape)
        return self.mean + jnp.exp(self.log_std) * eps

    def mode(self) -> jnp.ndarray:
        return self.mean

    def logp(self, actions: jnp.ndarray) -> jnp.ndarray:
        var = jnp.exp(2 * self.log_std)
        ll = -0.5 * ((actions - self.mean) ** 2 / var
                     + 2 * self.log_std + jnp.log(2 * jnp.pi))
        return jnp.sum(ll, axis=-1)

    def entropy(self) -> jnp.ndarray:
        return jnp.sum(self.log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e),
                       axis=-1)

    def kl(self, other: "DiagGaussian") -> jnp.ndarray:
        v0, v1 = jnp.exp(2 * self.log_std), jnp.exp(2 * other.log_std)
        return jnp.sum(other.log_std - self.log_std
                       + (v0 + (self.mean - other.mean) ** 2) / (2 * v1)
                       - 0.5, axis=-1)


@dataclasses.dataclass(frozen=True)
class RLModuleSpec:
    """Reference: rllib/core/rl_module/rl_module.py::RLModuleSpec."""
    obs_dim: int
    action_space: Space
    hidden: Sequence[int] = (64, 64)
    activation: str = "tanh"
    free_log_std: bool = True     # continuous: state-independent log-std


class RLModule:
    """Separate policy and value MLP towers + a dist head.

    forward(params, obs) -> (dist_inputs, value). Pure; everything jits.
    """

    def __init__(self, spec: RLModuleSpec):
        self.spec = spec
        sp = spec.action_space
        if sp.kind == "discrete":
            self.pi_out = sp.n
            self.is_discrete = True
        else:
            self.pi_out = int(np.prod(sp.shape))
            self.is_discrete = False
        self.pi_net = MLP(MLPConfig(hidden=tuple(spec.hidden),
                                    out_dim=self.pi_out,
                                    activation=spec.activation))
        self.vf_net = MLP(MLPConfig(hidden=tuple(spec.hidden), out_dim=1,
                                    activation=spec.activation))

    def init(self, rng) -> Any:
        r1, r2 = jax.random.split(rng)
        params = {
            "pi": self.pi_net.init_params(r1, self.spec.obs_dim),
            "vf": self.vf_net.init_params(r2, self.spec.obs_dim),
        }
        if not self.is_discrete and self.spec.free_log_std:
            params["log_std"] = jnp.zeros((self.pi_out,))
        return params

    def forward(self, params, obs) -> Tuple[Any, jnp.ndarray]:
        dist_in = self.pi_net.apply({"params": params["pi"]}, obs)
        value = self.vf_net.apply({"params": params["vf"]}, obs).squeeze(-1)
        return dist_in, value

    def dist(self, params, dist_in):
        if self.is_discrete:
            return Categorical(dist_in)
        log_std = params.get("log_std", jnp.zeros(dist_in.shape[-1:]))
        return DiagGaussian(dist_in, jnp.broadcast_to(log_std,
                                                      dist_in.shape))

    def explore_action(self, params, obs, rng):
        """One jittable sampling step: obs -> (action, logp, value)."""
        dist_in, value = self.forward(params, obs)
        d = self.dist(params, dist_in)
        a = d.sample(rng)
        return a, d.logp(a), value

    def deterministic_action(self, params, obs):
        dist_in, _ = self.forward(params, obs)
        return self.dist(params, dist_in).mode()


def spec_for_env(env, hidden: Sequence[int] = (64, 64),
                 activation: str = "tanh") -> RLModuleSpec:
    obs_dim = int(np.prod(env.observation_space.shape))
    return RLModuleSpec(obs_dim=obs_dim, action_space=env.action_space,
                        hidden=hidden, activation=activation)
