"""Algorithm / AlgorithmConfig: config-driven RL training loop.

Reference counterpart: rllib/algorithms/algorithm.py +
algorithm_config.py. Fluent config (.environment().env_runners()
.training().evaluation()) -> .build() -> Algorithm with .train()
iterations, .save()/.restore(), periodic deterministic evaluation.

Rollouts run on CPU EnvRunners (in-process, or ray_tpu actors when
num_env_runners > 0 and the runtime is up); the learner update is a
single jitted step — the TPU-facing half.
"""
from __future__ import annotations

import copy
import os
import pickle
import time
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np

from .env_runner import EnvRunner
from .sample_batch import SampleBatch, concat_samples


class AlgorithmConfig:
    """Fluent builder. Subclasses add their hyperparameters in
    .training(**kwargs)."""

    algo_class: Optional[type] = None

    def __init__(self):
        self.env = None
        self.env_config: Dict[str, Any] = {}
        self.num_env_runners = 0
        self.num_envs_per_env_runner = 4
        self.rollout_fragment_length = 128
        self.seed = 0
        self.gamma = 0.99
        self.lr = 3e-4
        self.train_batch_size = 512
        self.model: Dict[str, Any] = {"hidden": (64, 64),
                                      "activation": "tanh"}
        self.evaluation_interval: Optional[int] = None
        self.evaluation_num_episodes = 5

    # -- fluent sections (mirror reference names) --
    def environment(self, env=None, *, env_config=None) -> "AlgorithmConfig":
        if env is not None:
            self.env = env
        if env_config is not None:
            self.env_config = env_config
        return self

    def env_runners(self, *, num_env_runners=None,
                    num_envs_per_env_runner=None,
                    rollout_fragment_length=None) -> "AlgorithmConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise AttributeError(f"unknown training param {k!r}")
            setattr(self, k, v)
        return self

    def rl_module(self, *, model=None) -> "AlgorithmConfig":
        if model is not None:
            self.model.update(model)
        return self

    def evaluation(self, *, evaluation_interval=None,
                   evaluation_num_episodes=None) -> "AlgorithmConfig":
        if evaluation_interval is not None:
            self.evaluation_interval = evaluation_interval
        if evaluation_num_episodes is not None:
            self.evaluation_num_episodes = evaluation_num_episodes
        return self

    def debugging(self, *, seed=None) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def build(self) -> "Algorithm":
        if self.algo_class is None:
            raise ValueError("config has no algo_class; use a subclass "
                             "like PPOConfig")
        return self.algo_class(self)


def _make_runner(cfg: AlgorithmConfig, seed_offset: int) -> EnvRunner:
    return EnvRunner(
        cfg.env, num_envs=cfg.num_envs_per_env_runner,
        rollout_length=cfg.rollout_fragment_length,
        seed=cfg.seed + seed_offset, env_config=cfg.env_config,
        hidden=tuple(cfg.model["hidden"]),
        activation=cfg.model["activation"], gamma=cfg.gamma,
        lam=getattr(cfg, "lambda_", 0.95))


class _RemoteRunner:
    """Actor wrapper so EnvRunner runs over the core runtime
    (reference: RolloutWorker as a ray actor)."""

    def __init__(self, cfg_bytes: bytes, seed_offset: int):
        cfg = pickle.loads(cfg_bytes)
        self.runner = _make_runner(cfg, seed_offset)

    def sample(self, params):
        batch = self.runner.sample(params)
        return batch.as_numpy(), self.runner.pop_episode_stats()


class Algorithm:
    """Base training loop. Subclasses implement training_step(batch)."""

    def __init__(self, config: AlgorithmConfig):
        self.config = config
        if config.env is None:
            raise ValueError("config.environment(env=...) is required")
        # local runner always exists: module spec source + evaluation
        self.local_runner = _make_runner(config, 0)
        self.module = self.local_runner.module
        self.params = self.module.init(jax.random.PRNGKey(config.seed))
        self._remote_runners: List[Any] = []
        if config.num_env_runners > 0:
            import ray_tpu
            if not ray_tpu.is_initialized():
                raise RuntimeError(
                    "num_env_runners>0 needs ray_tpu.init() first")
            RemoteCls = ray_tpu.remote(_RemoteRunner)
            blob = pickle.dumps(config)
            self._remote_runners = [RemoteCls.remote(blob, i + 1)
                                    for i in range(config.num_env_runners)]
        self.iteration = 0
        self._timesteps_total = 0

    # -- rollout collection --
    def _collect(self) -> (SampleBatch, Dict[str, Any]):
        if self._remote_runners:
            import ray_tpu
            host_params = jax.device_get(self.params)
            outs = ray_tpu.get([r.sample.remote(host_params)
                                for r in self._remote_runners])
            batches = [SampleBatch(b) for b, _ in outs]
            stats_list = [s for _, s in outs]
            rets = [s["episode_return_mean"] for s in stats_list
                    if s["episode_return_mean"] is not None]
            lens = [s["episode_len_mean"] for s in stats_list
                    if s["episode_len_mean"] is not None]
            stats = {
                "episodes_this_iter": sum(s["episodes_this_iter"]
                                          for s in stats_list),
                "episode_return_mean": float(np.mean(rets)) if rets
                else None,
                "episode_len_mean": float(np.mean(lens)) if lens else None,
            }
            return concat_samples(batches), stats
        batch = self.local_runner.sample(self.params)
        return batch, self.local_runner.pop_episode_stats()

    def training_step(self, batch: SampleBatch) -> Dict[str, Any]:
        raise NotImplementedError

    def train(self) -> Dict[str, Any]:
        """One iteration: collect -> update -> (maybe) evaluate."""
        t0 = time.monotonic()
        batch, ep_stats = self._collect()
        learner_stats = self.training_step(batch)
        self.iteration += 1
        self._timesteps_total += batch.count
        result = {
            "training_iteration": self.iteration,
            "timesteps_total": self._timesteps_total,
            "time_this_iter_s": time.monotonic() - t0,
            **ep_stats,
            "learner": learner_stats,
        }
        ei = self.config.evaluation_interval
        if ei and self.iteration % ei == 0:
            # through self.evaluate() so algorithms with their own
            # policy nets (SAC's squashed actor) evaluate correctly
            result["evaluation"] = self.evaluate()
        return result

    def evaluate(self) -> Dict[str, float]:
        return self.local_runner.evaluate(
            self.params, num_episodes=self.config.evaluation_num_episodes)

    def compute_single_action(self, obs, *, explore: bool = False):
        obs = np.asarray(obs, np.float32)[None]
        if explore:
            key = jax.random.PRNGKey(int(time.monotonic_ns()) % (1 << 31))
            a, _, _ = self.module.explore_action(self.params, obs, key)
        else:
            a = self.module.deterministic_action(self.params, obs)
        a = np.asarray(a)[0]
        return int(a) if self.module.is_discrete else a

    # -- checkpointing (reference: Algorithm.save/restore) --
    def save(self, path: str) -> str:
        os.makedirs(path, exist_ok=True)
        state = {"params": jax.device_get(self.params),
                 "iteration": self.iteration,
                 "timesteps_total": self._timesteps_total,
                 "extra": self._save_extra()}
        with open(os.path.join(path, "algorithm_state.pkl"), "wb") as f:
            pickle.dump(state, f)
        return path

    def restore(self, path: str) -> None:
        with open(os.path.join(path, "algorithm_state.pkl"), "rb") as f:
            state = pickle.load(f)
        self.params = state["params"]
        self.iteration = state["iteration"]
        self._timesteps_total = state["timesteps_total"]
        self._restore_extra(state.get("extra"))

    def _save_extra(self):
        return None

    def _restore_extra(self, extra):
        pass

    def stop(self):
        for r in self._remote_runners:
            try:
                import ray_tpu
                ray_tpu.kill(r)
            except Exception:
                pass
        self._remote_runners = []
