"""Builtin lightweight RL environments (no gym dependency).

Reference counterpart: rllib/env/ + the gym envs its examples lean on
(rllib/examples/envs/). We ship in-repo numpy envs with the gymnasium
step API — reset() -> (obs, info); step(a) -> (obs, reward, terminated,
truncated, info) — plus a vectorized wrapper and an optional gymnasium
adapter when that package is importable.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np


class Space:
    """Minimal space descriptor (reference: gym.spaces)."""

    def __init__(self, kind: str, *, n: int = 0, shape: Tuple[int, ...] = (),
                 low: float = -np.inf, high: float = np.inf):
        self.kind = kind          # "discrete" | "box"
        self.n = n
        self.shape = shape
        self.low = low
        self.high = high

    @staticmethod
    def discrete(n: int) -> "Space":
        return Space("discrete", n=n, shape=())

    @staticmethod
    def box(shape: Tuple[int, ...], low: float = -np.inf,
            high: float = np.inf) -> "Space":
        return Space("box", shape=shape, low=low, high=high)

    def sample(self, rng: np.random.Generator):
        if self.kind == "discrete":
            return int(rng.integers(self.n))
        lo = self.low if np.isfinite(self.low) else -1.0
        hi = self.high if np.isfinite(self.high) else 1.0
        return rng.uniform(lo, hi, size=self.shape).astype(np.float32)

    def __repr__(self):
        if self.kind == "discrete":
            return f"Discrete({self.n})"
        return f"Box{self.shape}"


class Env:
    """Base env. Subclasses set observation_space / action_space."""

    observation_space: Space
    action_space: Space

    def reset(self, *, seed: Optional[int] = None
              ) -> Tuple[np.ndarray, Dict[str, Any]]:
        raise NotImplementedError

    def step(self, action) -> Tuple[np.ndarray, float, bool, bool,
                                    Dict[str, Any]]:
        raise NotImplementedError

    def close(self):
        pass


class CartPole(Env):
    """Classic cart-pole balance (dynamics per Barto-Sutton-Anderson).

    Matches gym CartPole-v1: 4-dim obs, 2 actions, +1 reward per step,
    500-step horizon, terminate on |x|>2.4 or |theta|>12deg.
    """

    observation_space = Space.box((4,), -4.8, 4.8)
    action_space = Space.discrete(2)
    max_steps = 500

    def __init__(self, seed: Optional[int] = None):
        self._rng = np.random.default_rng(seed)
        self._state = np.zeros(4, np.float32)
        self._t = 0

    def reset(self, *, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._rng.uniform(-0.05, 0.05, size=4).astype(np.float32)
        self._t = 0
        return self._state.copy(), {}

    def step(self, action):
        x, x_dot, th, th_dot = self._state
        force = 10.0 if action == 1 else -10.0
        costh, sinth = np.cos(th), np.sin(th)
        masspole, masscart, length = 0.1, 1.0, 0.5
        total_mass = masspole + masscart
        pml = masspole * length
        temp = (force + pml * th_dot**2 * sinth) / total_mass
        th_acc = (9.8 * sinth - costh * temp) / (
            length * (4.0 / 3.0 - masspole * costh**2 / total_mass))
        x_acc = temp - pml * th_acc * costh / total_mass
        tau = 0.02
        self._state = np.array(
            [x + tau * x_dot, x_dot + tau * x_acc,
             th + tau * th_dot, th_dot + tau * th_acc], np.float32)
        self._t += 1
        terminated = bool(abs(self._state[0]) > 2.4
                          or abs(self._state[2]) > 0.2095)
        truncated = self._t >= self.max_steps
        return self._state.copy(), 1.0, terminated, truncated, {}


class GridWorld(Env):
    """NxN grid; start top-left, goal bottom-right; -0.01/step, +1 at goal."""

    def __init__(self, n: int = 5, max_steps: int = 100,
                 seed: Optional[int] = None):
        self.n = n
        self.max_steps = max_steps
        self.observation_space = Space.box((2,), 0.0, float(n - 1))
        self.action_space = Space.discrete(4)   # up/down/left/right
        self._pos = np.zeros(2, np.int64)
        self._t = 0

    def reset(self, *, seed: Optional[int] = None):
        self._pos = np.zeros(2, np.int64)
        self._t = 0
        return self._pos.astype(np.float32), {}

    def step(self, action):
        d = {0: (-1, 0), 1: (1, 0), 2: (0, -1), 3: (0, 1)}[int(action)]
        self._pos = np.clip(self._pos + d, 0, self.n - 1)
        self._t += 1
        at_goal = bool((self._pos == self.n - 1).all())
        reward = 1.0 if at_goal else -0.01
        return (self._pos.astype(np.float32), reward, at_goal,
                self._t >= self.max_steps, {})


class Pendulum(Env):
    """Classic torque-limited pendulum swing-up (standard dynamics:
    theta'' = 3g/(2l) sin(theta) + 3/(ml^2) u). Continuous action in
    [-2, 2]; obs (cos, sin, theta_dot); reward
    -(angle^2 + 0.1 theta_dot^2 + 0.001 u^2); 200-step episodes. The
    in-repo continuous-control benchmark for SAC (reference:
    Pendulum-v1 used across rllib/algorithms tuned examples)."""

    observation_space = Space.box((3,), -8.0, 8.0)
    action_space = Space.box((1,), -2.0, 2.0)
    max_steps = 200

    def __init__(self, seed: Optional[int] = None):
        self._rng = np.random.default_rng(seed)
        self._th = 0.0
        self._thdot = 0.0
        self._t = 0

    def _obs(self) -> np.ndarray:
        return np.array([np.cos(self._th), np.sin(self._th),
                         self._thdot], np.float32)

    def reset(self, *, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._th = float(self._rng.uniform(-np.pi, np.pi))
        self._thdot = float(self._rng.uniform(-1.0, 1.0))
        self._t = 0
        return self._obs(), {}

    def step(self, action):
        u = float(np.clip(np.asarray(action).reshape(-1)[0], -2.0, 2.0))
        g, m, length, dt = 10.0, 1.0, 1.0, 0.05
        th_norm = ((self._th + np.pi) % (2 * np.pi)) - np.pi
        cost = th_norm ** 2 + 0.1 * self._thdot ** 2 + 0.001 * u ** 2
        self._thdot += (3 * g / (2 * length) * np.sin(self._th)
                        + 3.0 / (m * length ** 2) * u) * dt
        self._thdot = float(np.clip(self._thdot, -8.0, 8.0))
        self._th += self._thdot * dt
        self._t += 1
        return self._obs(), -float(cost), False, \
            self._t >= self.max_steps, {}


class BanditEnv(Env):
    """K-armed stochastic bandit; 1-step episodes (reference: bandit envs
    in rllib/examples)."""

    def __init__(self, k: int = 10, seed: Optional[int] = None):
        self._rng = np.random.default_rng(seed)
        self.means = self._rng.normal(0.0, 1.0, size=k)
        self.observation_space = Space.box((1,), 0.0, 1.0)
        self.action_space = Space.discrete(k)

    def reset(self, *, seed: Optional[int] = None):
        return np.zeros(1, np.float32), {}

    def step(self, action):
        r = float(self._rng.normal(self.means[int(action)], 1.0))
        return np.zeros(1, np.float32), r, True, False, {}


class VectorEnv:
    """N independent env copies stepped in lockstep with auto-reset.

    Reference: rllib/env/vector_env.py. Auto-reset on episode end so the
    batch dimension never shrinks — matches what a jitted policy wants.
    """

    def __init__(self, env_fns: List[Any]):
        self.envs = [fn() for fn in env_fns]
        self.num_envs = len(self.envs)
        self.observation_space = self.envs[0].observation_space
        self.action_space = self.envs[0].action_space

    def reset(self, *, seed: Optional[int] = None):
        obs = []
        for i, e in enumerate(self.envs):
            o, _ = e.reset(seed=None if seed is None else seed + i)
            obs.append(o)
        return np.stack(obs), [{} for _ in self.envs]

    def step(self, actions):
        """On episode end the returned obs is the auto-reset obs; the true
        terminal observation is preserved in infos[i]['final_obs'] so
        callers can bootstrap truncations correctly."""
        obs, rews, terms, truncs = [], [], [], []
        infos = [{} for _ in range(self.num_envs)]
        for i, (e, a) in enumerate(zip(self.envs, actions)):
            o, r, tm, tr, _ = e.step(a)
            if tm or tr:
                infos[i]["final_obs"] = o
                o, _ = e.reset()
            obs.append(o)
            rews.append(r)
            terms.append(tm)
            truncs.append(tr)
        return (np.stack(obs), np.asarray(rews, np.float32),
                np.asarray(terms), np.asarray(truncs), infos)


_REGISTRY = {
    "CartPole-v1": CartPole,
    "CartPole": CartPole,
    "GridWorld": GridWorld,
    "Bandit": BanditEnv,
    "Pendulum-v1": Pendulum,
    "Pendulum": Pendulum,
}


def register_env(name: str, ctor) -> None:
    """Reference: ray.tune.registry.register_env."""
    _REGISTRY[name] = ctor


def make_env(spec, **kwargs) -> Env:
    """Build an env from a name, class, or callable; falls back to a
    gymnasium adapter for unknown string names if gymnasium is present."""
    if callable(spec) and not isinstance(spec, str):
        return spec(**kwargs)
    if spec in _REGISTRY:
        return _REGISTRY[spec](**kwargs)
    try:                                    # optional gymnasium adapter
        import gymnasium
    except ImportError:
        raise ValueError(f"unknown env {spec!r}; known: {list(_REGISTRY)} "
                         "(gymnasium not importable for external names)")
    return _GymAdapter(gymnasium.make(spec, **kwargs))


class _GymAdapter(Env):
    def __init__(self, gym_env):
        self._env = gym_env
        osp, asp = gym_env.observation_space, gym_env.action_space
        if hasattr(asp, "n"):
            self.action_space = Space.discrete(int(asp.n))
        else:
            self.action_space = Space.box(tuple(asp.shape))
        self.observation_space = Space.box(tuple(osp.shape))

    def reset(self, *, seed=None):
        return self._env.reset(seed=seed)

    def step(self, action):
        return self._env.step(action)

    def close(self):
        self._env.close()
