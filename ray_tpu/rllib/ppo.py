"""PPO on JAX: clipped surrogate + GAE + entropy/KL regularization.

Reference counterpart: rllib/algorithms/ppo/ (ppo.py, ppo_learner,
torch policy). TPU-first: the whole minibatch update — forward, ratio,
clip, value loss, entropy, adaptive-KL, grads, adam — is ONE jitted
function; epoch/minibatch iteration happens in Python over fixed shapes
so XLA compiles exactly one program.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from . import sample_batch as sb
from .algorithm import Algorithm, AlgorithmConfig
from .learner import Learner, LearnerGroup
from .sample_batch import SampleBatch


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lambda_ = 0.95
        self.clip_param = 0.2
        self.vf_clip_param = 10.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.0
        self.kl_coeff = 0.2        # adaptive-KL penalty initial coeff
        self.kl_target = 0.01
        self.num_epochs = 8
        self.minibatch_size = 128
        self.grad_clip = 0.5
        self.use_mesh = False      # dp-shard minibatches over a Mesh
        self.algo_class = PPO


class PPO(Algorithm):
    def __init__(self, config: PPOConfig):
        super().__init__(config)
        cfg = config
        module = self.module

        def loss_fn(params, batch, kl_coeff):
            dist_in, values = module.forward(params, batch[sb.OBS])
            dist = module.dist(params, dist_in)
            logp = dist.logp(batch[sb.ACTIONS])
            ratio = jnp.exp(logp - batch[sb.LOGPS])
            adv = batch[sb.ADVANTAGES]
            adv = (adv - adv.mean()) / (adv.std() + 1e-8)
            surr = jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1 - cfg.clip_param,
                         1 + cfg.clip_param) * adv)
            pi_loss = -surr.mean()
            # clipped value loss (reference: ppo_torch_policy vf_clip)
            vf_err = (values - batch[sb.RETURNS]) ** 2
            vf_clipped = batch[sb.VALUES] + jnp.clip(
                values - batch[sb.VALUES],
                -cfg.vf_clip_param, cfg.vf_clip_param)
            vf_err2 = (vf_clipped - batch[sb.RETURNS]) ** 2
            vf_loss = 0.5 * jnp.maximum(vf_err, vf_err2).mean()
            entropy = dist.entropy().mean()
            approx_kl = ((ratio - 1) - jnp.log(ratio)).mean()
            loss = (pi_loss + cfg.vf_loss_coeff * vf_loss
                    - cfg.entropy_coeff * entropy
                    + kl_coeff * approx_kl)
            return loss, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                          "entropy": entropy, "kl": approx_kl}

        tx = optax.chain(optax.clip_by_global_norm(cfg.grad_clip),
                         optax.adam(cfg.lr))
        learner = Learner(self.params, loss_fn=loss_fn, tx=tx)
        mesh = None
        if cfg.use_mesh:
            from ..parallel.mesh import MeshSpec
            mesh = MeshSpec(dp=len(jax.devices())).build()
        self.learner_group = LearnerGroup(learner, mesh=mesh)
        self.kl_coeff = cfg.kl_coeff

    @property
    def params(self):
        # after __init__, params live in the learner (updated in place)
        if hasattr(self, "learner_group"):
            return self.learner_group.params
        return self._init_params

    @params.setter
    def params(self, value):
        if hasattr(self, "learner_group"):
            self.learner_group.learner.params = value
        else:
            self._init_params = value

    def training_step(self, batch: SampleBatch) -> Dict[str, Any]:
        cfg: PPOConfig = self.config
        stats: Dict[str, float] = {}
        kls = []
        for epoch in range(cfg.num_epochs):
            shuffled = batch.shuffle(seed=cfg.seed + self.iteration * 131
                                     + epoch)
            for mb in shuffled.minibatches(min(cfg.minibatch_size,
                                               batch.count)):
                stats = self.learner_group.update(mb.as_numpy(),
                                                  self.kl_coeff)
                kls.append(stats["kl"])
        # adaptive KL coefficient (reference: ppo.py update_kl)
        mean_kl = float(np.mean(kls)) if kls else 0.0
        if mean_kl > 2.0 * cfg.kl_target:
            self.kl_coeff = min(self.kl_coeff * 1.5, 100.0)
        elif mean_kl < 0.5 * cfg.kl_target:
            self.kl_coeff = max(self.kl_coeff * 0.5, 1e-8)
        stats["kl_coeff"] = self.kl_coeff
        stats["mean_kl"] = mean_kl
        return stats

    def _save_extra(self):
        return {"kl_coeff": self.kl_coeff,
                "opt_state": jax.device_get(
                    self.learner_group.learner.opt_state)}

    def _restore_extra(self, extra):
        if extra:
            self.kl_coeff = extra["kl_coeff"]
            self.learner_group.learner.opt_state = extra["opt_state"]
