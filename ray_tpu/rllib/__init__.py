"""ray_tpu.rllib — RL on the TPU-native runtime.

Reference counterpart: rllib/ (Algorithm, PPO, DQN, EnvRunner, RLModule,
SampleBatch, replay buffers, GRPO post-training path). Rollouts run on
CPU env actors; learner updates are single jitted XLA programs,
dp-shardable over a jax Mesh (LearnerGroup).
"""
from .algorithm import Algorithm, AlgorithmConfig
from .dqn import DQN, DQNConfig
from .env import (BanditEnv, CartPole, Env, GridWorld, Pendulum,
                  Space, VectorEnv,
                  make_env, register_env)
from .env_runner import EnvRunner
from .grpo import (EngineSampler, GRPOConfig, GRPOLearner, GRPOTrainer,
                   make_lora_grpo_trainer,
                   group_relative_advantages)
from .learner import Learner, LearnerGroup
from .ppo import PPO, PPOConfig
from .sac import SAC, SACConfig
from .replay import EpisodeReplayBuffer, ReplayBuffer
from .rl_module import (Categorical, DiagGaussian, RLModule, RLModuleSpec,
                        spec_for_env)
from .sample_batch import SampleBatch, compute_gae, concat_samples

__all__ = [
    "Algorithm", "AlgorithmConfig", "PPO", "PPOConfig", "SAC", "SACConfig", "Pendulum", "DQN", "DQNConfig",
    "EngineSampler", "GRPOConfig", "GRPOLearner", "GRPOTrainer",
    "make_lora_grpo_trainer",
    "group_relative_advantages",
    "Env", "Space", "CartPole", "GridWorld", "BanditEnv", "VectorEnv",
    "make_env", "register_env", "EnvRunner", "Learner", "LearnerGroup",
    "ReplayBuffer", "EpisodeReplayBuffer", "RLModule", "RLModuleSpec",
    "spec_for_env", "Categorical", "DiagGaussian", "SampleBatch",
    "concat_samples", "compute_gae",
]
