"""DQN on JAX: replay buffer + double-Q target, jitted TD update.

Reference counterpart: rllib/algorithms/dqn/. Demonstrates the replay
path (R6): EnvRunner fragments feed a ReplayBuffer; updates sample
uniformly; the target net refreshes by period.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from . import sample_batch as sb
from .algorithm import Algorithm, AlgorithmConfig
from .replay import ReplayBuffer
from .sample_batch import SampleBatch


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.buffer_size = 50_000
        self.learning_starts = 1000
        self.target_update_freq = 500     # in gradient steps
        self.train_batch_size = 64
        self.num_gradient_steps = 32      # per training iteration
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_decay_timesteps = 10_000
        self.double_q = True
        self.lr = 1e-3
        self.algo_class = DQN


class DQN(Algorithm):
    def __init__(self, config: DQNConfig):
        if config.num_env_runners > 0:
            raise ValueError("DQN collects via its local epsilon-greedy "
                             "runner; num_env_runners>0 is not supported")
        super().__init__(config)
        if not self.module.is_discrete:
            raise ValueError("DQN needs a discrete action space")
        cfg = config
        module = self.module
        # re-use the pi tower as the Q net: dist_in are Q-values
        self.q_params = self.params["pi"]
        self.target_params = jax.device_get(self.q_params)
        self.tx = optax.adam(cfg.lr)
        self.opt_state = self.tx.init(self.q_params)
        self._grad_steps = 0
        self.buffer = ReplayBuffer(cfg.buffer_size, seed=cfg.seed)

        def td_update(q_params, target_params, opt_state, batch):
            def loss_fn(qp):
                q = module.pi_net.apply({"params": qp}, batch[sb.OBS])
                qa = jnp.take_along_axis(
                    q, batch[sb.ACTIONS][:, None].astype(jnp.int32),
                    axis=-1).squeeze(-1)
                q_next_t = module.pi_net.apply({"params": target_params},
                                               batch[sb.NEXT_OBS])
                if cfg.double_q:
                    q_next_o = module.pi_net.apply({"params": qp},
                                                   batch[sb.NEXT_OBS])
                    a_star = jnp.argmax(q_next_o, axis=-1)
                    q_next = jnp.take_along_axis(
                        q_next_t, a_star[:, None], axis=-1).squeeze(-1)
                else:
                    q_next = q_next_t.max(axis=-1)
                nonterminal = 1.0 - batch[sb.TERMINATEDS].astype(jnp.float32)
                target = (batch[sb.REWARDS]
                          + cfg.gamma * nonterminal * q_next)
                target = jax.lax.stop_gradient(target)
                return jnp.mean((qa - target) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(q_params)
            updates, opt_state = self.tx.update(grads, opt_state, q_params)
            return optax.apply_updates(q_params, updates), opt_state, loss

        self._td_update = jax.jit(td_update)
        self._q_fwd = jax.jit(
            lambda qp, obs: module.pi_net.apply({"params": qp}, obs))
        self._rng = np.random.default_rng(cfg.seed)

    def _epsilon(self) -> float:
        cfg: DQNConfig = self.config
        frac = min(1.0, self._timesteps_total
                   / max(1, cfg.epsilon_decay_timesteps))
        return cfg.epsilon_initial + frac * (cfg.epsilon_final
                                             - cfg.epsilon_initial)

    def _collect(self):
        """Epsilon-greedy rollout via the local runner's vec env."""
        cfg: DQNConfig = self.config
        runner = self.local_runner
        vec = runner.vec
        T, B = cfg.rollout_fragment_length, vec.num_envs
        cols = {k: [] for k in (sb.OBS, sb.ACTIONS, sb.REWARDS,
                                sb.TERMINATEDS, sb.NEXT_OBS)}
        obs = runner._obs
        eps = self._epsilon()
        for _ in range(T):
            q = np.asarray(self._q_fwd(self.q_params, obs))
            greedy = q.argmax(axis=-1)
            rand = self._rng.integers(0, q.shape[-1], size=B)
            explore = self._rng.random(B) < eps
            actions = np.where(explore, rand, greedy).astype(np.int32)
            nxt, r, tm, tr, infos = vec.step(actions)
            runner._ep_ret += r
            runner._ep_len += 1
            # store the TRUE next obs (not the auto-reset obs); truncation
            # keeps terminateds=0 so the target bootstraps through it.
            nxt_true = nxt.copy()
            for i in np.nonzero(tm | tr)[0]:
                nxt_true[i] = infos[i]["final_obs"]
                runner.completed_returns.append(float(runner._ep_ret[i]))
                runner.completed_lengths.append(int(runner._ep_len[i]))
                runner._ep_ret[i] = 0.0
                runner._ep_len[i] = 0
            cols[sb.OBS].append(obs.copy())
            cols[sb.ACTIONS].append(actions)
            cols[sb.REWARDS].append(r)
            cols[sb.TERMINATEDS].append(tm)
            cols[sb.NEXT_OBS].append(nxt_true)
            obs = nxt
        runner._obs = obs
        flat = {k: np.concatenate(v) for k, v in cols.items()}
        return SampleBatch(flat), runner.pop_episode_stats()

    def training_step(self, batch: SampleBatch) -> Dict[str, Any]:
        cfg: DQNConfig = self.config
        self.buffer.add(batch)
        if len(self.buffer) < cfg.learning_starts:
            return {"td_loss": None, "buffer_size": len(self.buffer),
                    "epsilon": self._epsilon()}
        losses = []
        for _ in range(cfg.num_gradient_steps):
            mb = self.buffer.sample(cfg.train_batch_size).as_numpy()
            self.q_params, self.opt_state, loss = self._td_update(
                self.q_params, self.target_params, self.opt_state, mb)
            self._grad_steps += 1
            if self._grad_steps % cfg.target_update_freq == 0:
                self.target_params = jax.device_get(self.q_params)
            losses.append(float(loss))
        # keep the module params in sync so Algorithm-level periodic
        # evaluation (which reads self.params) sees the trained Q net
        self.params = dict(self.params, pi=self.q_params)
        return {"td_loss": float(np.mean(losses)),
                "buffer_size": len(self.buffer),
                "epsilon": self._epsilon()}

    def compute_single_action(self, obs, *, explore: bool = False):
        obs = np.asarray(obs, np.float32)[None]
        q = np.asarray(self._q_fwd(self.q_params, obs))[0]
        if explore and self._rng.random() < self._epsilon():
            return int(self._rng.integers(0, len(q)))
        return int(q.argmax())

    # evaluate() is inherited: training_step syncs params["pi"] = q_params,
    # and Categorical.mode() == argmax Q — the greedy policy.

    def _save_extra(self):
        return {"q_params": jax.device_get(self.q_params),
                "target_params": self.target_params,
                "opt_state": jax.device_get(self.opt_state),
                "grad_steps": self._grad_steps}

    def _restore_extra(self, extra):
        if extra:
            self.q_params = extra["q_params"]
            self.target_params = extra["target_params"]
            self.opt_state = extra["opt_state"]
            self._grad_steps = extra["grad_steps"]
