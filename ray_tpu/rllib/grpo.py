"""GRPO: group-relative policy optimization for LLM post-training.

Reference counterpart: the fork's RLHF/GRPO focus (rllib on LLM policies;
group-relative advantage as in DeepSeekMath). Per prompt we sample a
GROUP of completions, score them with a reward function, and use
within-group normalized rewards as per-sequence advantages — no value
net. The policy update is a token-level clipped surrogate with a k3 KL
penalty against a frozen reference policy, all in one jitted step.

TPU-first notes: sampling batches all groups together ([P*G, T] forward
per step — MXU-friendly); the update runs on padded fixed shapes so XLA
compiles one program regardless of completion lengths.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax


@dataclasses.dataclass
class GRPOConfig:
    group_size: int = 8
    clip_param: float = 0.2
    kl_coeff: float = 0.04
    lr: float = 1e-5
    grad_clip: float = 1.0
    num_epochs: int = 1
    temperature: float = 1.0
    max_new_tokens: int = 32
    seed: int = 0


def group_relative_advantages(rewards: np.ndarray,
                              group_size: int) -> np.ndarray:
    """[P*G] rewards -> [P*G] advantages, normalized within each group."""
    r = rewards.reshape(-1, group_size)
    mean = r.mean(axis=1, keepdims=True)
    std = r.std(axis=1, keepdims=True)
    return ((r - mean) / (std + 1e-6)).reshape(-1).astype(np.float32)


def _token_logps(logits: jnp.ndarray, tokens: jnp.ndarray) -> jnp.ndarray:
    """logits [B,T,V] predicts tokens[:,1:]; returns [B,T-1] log-probs."""
    logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    return jnp.take_along_axis(
        logp, tokens[:, 1:, None].astype(jnp.int32), axis=-1).squeeze(-1)


class GRPOLearner:
    """Jitted GRPO update over padded token batches.

    apply_fn(params, tokens[B,T]) -> logits [B,T,V]  (causal LM).
    Batch columns: tokens [B,T] int32, mask [B,T-1] float32 (1 where
    position t+1 is a completion token to train on), old_logps [B,T-1],
    ref_logps [B,T-1], advantages [B].
    """

    def __init__(self, apply_fn: Callable, params, cfg: GRPOConfig):
        self.cfg = cfg
        self.params = params
        self.tx = optax.chain(optax.clip_by_global_norm(cfg.grad_clip),
                              optax.adamw(cfg.lr))
        self.opt_state = self.tx.init(params)

        def loss_fn(p, batch):
            logits = apply_fn(p, batch["tokens"]) / cfg.temperature
            logps = _token_logps(logits, batch["tokens"])
            mask = batch["mask"]
            ratio = jnp.exp(logps - batch["old_logps"])
            adv = batch["advantages"][:, None]
            surr = jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1 - cfg.clip_param,
                         1 + cfg.clip_param) * adv)
            # k3 KL estimator vs frozen reference (Schulman)
            logr = batch["ref_logps"] - logps
            kl = jnp.exp(logr) - logr - 1.0
            denom = jnp.maximum(mask.sum(), 1.0)
            pg_loss = -(surr * mask).sum() / denom
            kl_loss = (kl * mask).sum() / denom
            loss = pg_loss + cfg.kl_coeff * kl_loss
            return loss, {"pg_loss": pg_loss, "kl": kl_loss}

        def update(params, opt_state, batch):
            (loss, stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, dict(stats, total_loss=loss)

        self._update = jax.jit(update)
        self._apply = jax.jit(lambda p, t: apply_fn(p, t) / cfg.temperature)

    def token_logps(self, params, tokens: np.ndarray) -> np.ndarray:
        return np.asarray(_token_logps(self._apply(params, tokens),
                                       jnp.asarray(tokens)))

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        self.params, self.opt_state, stats = self._update(
            self.params, self.opt_state, batch)
        return {k: float(v) for k, v in stats.items()}


class GRPOTrainer:
    """Sample -> score -> group-normalize -> update loop for a causal LM.

    model: flax module with .apply({'params': p}, tokens)->logits, or any
    apply_fn via the functools path. reward_fn(prompt_ids, completion_ids)
    -> float. For production serving-side sampling, plug the serve LLM
    engine in as `sampler`.
    """

    def __init__(self, apply_fn: Callable, params, reward_fn: Callable,
                 cfg: Optional[GRPOConfig] = None, *,
                 eos_id: Optional[int] = None,
                 sampler: Optional[Callable] = None):
        self.cfg = cfg or GRPOConfig()
        self.learner = GRPOLearner(apply_fn, params, self.cfg)
        self.ref_params = jax.device_get(params)   # frozen reference
        self.reward_fn = reward_fn
        self.eos_id = eos_id
        self.sampler = sampler
        self._rng = jax.random.PRNGKey(self.cfg.seed)
        self._apply = self.learner._apply

        def sample_step(params, tokens, t, key):
            logits = self._apply(params, tokens)
            return jax.random.categorical(key, logits[:, t - 1], axis=-1)

        self._sample_step = jax.jit(sample_step)

    @property
    def params(self):
        return self.learner.params

    def _sample_group(self, prompt_ids: Sequence[int],
                      group: int) -> np.ndarray:
        """[G, len(prompt)+max_new] greedy-temp sampled completions."""
        cfg = self.cfg
        plen = len(prompt_ids)
        T = plen + cfg.max_new_tokens
        toks = np.zeros((group, T), np.int32)
        toks[:, :plen] = np.asarray(prompt_ids, np.int32)
        for t in range(plen, T):
            self._rng, key = jax.random.split(self._rng)
            nxt = np.asarray(self._sample_step(self.params,
                                               jnp.asarray(toks), t, key))
            toks[:, t] = nxt
        return toks

    def step(self, prompts: List[Sequence[int]]) -> Dict[str, Any]:
        """One GRPO iteration over a list of tokenized prompts."""
        cfg = self.cfg
        G = cfg.group_size
        all_toks, all_masks, rewards = [], [], []
        max_t = 0
        for p in prompts:
            if self.sampler is not None:
                toks = np.asarray(self.sampler(p, G))
            else:
                toks = self._sample_group(p, G)
            plen = len(p)
            mask = np.zeros((G, toks.shape[1] - 1), np.float32)
            for g in range(G):
                comp = toks[g, plen:]
                end = len(comp)
                if self.eos_id is not None:
                    hits = np.nonzero(comp == self.eos_id)[0]
                    if len(hits):
                        end = int(hits[0]) + 1
                # mask[t] trains the prediction of token t+1
                mask[g, plen - 1: plen - 1 + end] = 1.0
                rewards.append(float(self.reward_fn(p, comp[:end])))
            all_toks.append(toks)
            all_masks.append(mask)
            max_t = max(max_t, toks.shape[1])
        toks = np.concatenate([
            np.pad(t, ((0, 0), (0, max_t - t.shape[1]))) for t in all_toks])
        masks = np.concatenate([
            np.pad(m, ((0, 0), (0, max_t - 1 - m.shape[1])))
            for m in all_masks])
        rewards = np.asarray(rewards, np.float32)
        adv = group_relative_advantages(rewards, G)
        old_logps = self.learner.token_logps(self.params, toks)
        ref_logps = self.learner.token_logps(self.ref_params, toks)
        batch = {"tokens": toks, "mask": masks, "old_logps": old_logps,
                 "ref_logps": ref_logps, "advantages": adv}
        stats: Dict[str, float] = {}
        for _ in range(cfg.num_epochs):
            stats = self.learner.update(batch)
        return {"reward_mean": float(rewards.mean()),
                "reward_std": float(rewards.std()), **stats}
