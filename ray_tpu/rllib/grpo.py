"""GRPO: group-relative policy optimization for LLM post-training.

Reference counterpart: the fork's RLHF/GRPO focus (rllib on LLM policies;
group-relative advantage as in DeepSeekMath). Per prompt we sample a
GROUP of completions, score them with a reward function, and use
within-group normalized rewards as per-sequence advantages — no value
net. The policy update is a token-level clipped surrogate with a k3 KL
penalty against a frozen reference policy, all in one jitted step.

TPU-first notes: sampling batches all groups together ([P*G, T] forward
per step — MXU-friendly); the update runs on padded fixed shapes so XLA
compiles one program regardless of completion lengths.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax


@dataclasses.dataclass
class GRPOConfig:
    group_size: int = 8
    clip_param: float = 0.2
    kl_coeff: float = 0.04
    lr: float = 1e-5
    grad_clip: float = 1.0
    num_epochs: int = 1
    temperature: float = 1.0
    max_new_tokens: int = 32
    seed: int = 0


def group_relative_advantages(rewards: np.ndarray,
                              group_size: int) -> np.ndarray:
    """[P*G] rewards -> [P*G] advantages, normalized within each group."""
    r = rewards.reshape(-1, group_size)
    mean = r.mean(axis=1, keepdims=True)
    std = r.std(axis=1, keepdims=True)
    return ((r - mean) / (std + 1e-6)).reshape(-1).astype(np.float32)


def _token_logps(logits: jnp.ndarray, tokens: jnp.ndarray) -> jnp.ndarray:
    """logits [B,T,V] predicts tokens[:,1:]; returns [B,T-1] log-probs."""
    logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    return jnp.take_along_axis(
        logp, tokens[:, 1:, None].astype(jnp.int32), axis=-1).squeeze(-1)


class GRPOLearner:
    """Jitted GRPO update over padded token batches.

    apply_fn(params, tokens[B,T]) -> logits [B,T,V]  (causal LM).
    Batch columns: tokens [B,T] int32, mask [B,T-1] float32 (1 where
    position t+1 is a completion token to train on), old_logps [B,T-1],
    ref_logps [B,T-1], advantages [B].
    """

    def __init__(self, apply_fn: Callable, params, cfg: GRPOConfig):
        self.cfg = cfg
        self.params = params
        self.tx = optax.chain(optax.clip_by_global_norm(cfg.grad_clip),
                              optax.adamw(cfg.lr))
        self.opt_state = self.tx.init(params)

        def loss_fn(p, batch):
            logits = apply_fn(p, batch["tokens"]) / cfg.temperature
            logps = _token_logps(logits, batch["tokens"])
            mask = batch["mask"]
            ratio = jnp.exp(logps - batch["old_logps"])
            adv = batch["advantages"][:, None]
            surr = jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1 - cfg.clip_param,
                         1 + cfg.clip_param) * adv)
            # k3 KL estimator vs frozen reference (Schulman)
            logr = batch["ref_logps"] - logps
            kl = jnp.exp(logr) - logr - 1.0
            denom = jnp.maximum(mask.sum(), 1.0)
            pg_loss = -(surr * mask).sum() / denom
            kl_loss = (kl * mask).sum() / denom
            loss = pg_loss + cfg.kl_coeff * kl_loss
            return loss, {"pg_loss": pg_loss, "kl": kl_loss}

        def update(params, opt_state, batch):
            (loss, stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, dict(stats, total_loss=loss)

        self._update = jax.jit(update)
        self._apply = jax.jit(lambda p, t: apply_fn(p, t) / cfg.temperature)

    def token_logps(self, params, tokens: np.ndarray) -> np.ndarray:
        return np.asarray(_token_logps(self._apply(params, tokens),
                                       jnp.asarray(tokens)))

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        self.params, self.opt_state, stats = self._update(
            self.params, self.opt_state, batch)
        return {k: float(v) for k, v in stats.items()}


class EngineSampler:
    """Group sampling through the serve LLM engine (SURVEY R7: "LLM
    policy sampled via serve engine").

    The engine gives GRPO the production decode path — slot KV cache,
    continuous batching, pipelined host loop — instead of the naive
    full-forward sampling loop, so one group of G completions costs G
    cache-decode streams, not G*T full forwards. The trainer pushes the
    freshly-updated policy params into the engine after every step."""

    def __init__(self, model, params, cfg: GRPOConfig, *,
                 eos_id: Optional[int] = None, max_seq_len: int = 512,
                 engine_cfg=None):
        from ..serve.llm import LLMEngine, LLMEngineConfig  # noqa: PLC0415
        if engine_cfg is None:
            engine_cfg = LLMEngineConfig(
                max_slots=min(16, max(2, cfg.group_size)),
                max_seq_len=max_seq_len,
                prefill_buckets=(16, 32, 64, 128, 256),
                max_new_tokens_default=cfg.max_new_tokens,
                eos_token_id=eos_id)
        self.cfg = cfg
        self.engine = LLMEngine(model, params, engine_cfg)

    def __call__(self, prompt_ids: Sequence[int], group: int) -> np.ndarray:
        cfg = self.cfg
        plen = len(prompt_ids)
        if plen + cfg.max_new_tokens > self.engine.cfg.max_seq_len:
            # The engine would silently clamp the budget and the trainer
            # would then score/train phantom pad tokens — fail loud.
            raise ValueError(
                f"prompt ({plen}) + max_new_tokens ({cfg.max_new_tokens})"
                f" exceeds engine max_seq_len "
                f"({self.engine.cfg.max_seq_len}); raise max_seq_len")
        eos = self.engine.cfg.eos_token_id
        rids = [self.engine.submit(prompt_ids,
                                   max_new_tokens=cfg.max_new_tokens,
                                   temperature=max(cfg.temperature, 1e-4))
                for _ in range(group)]
        toks = np.zeros((group, plen + cfg.max_new_tokens), np.int32)
        toks[:, :plen] = np.asarray(prompt_ids, np.int32)
        for g, rid in enumerate(rids):
            comp = list(self.engine.stream(rid))
            toks[g, plen:plen + len(comp)] = comp
            if len(comp) < cfg.max_new_tokens and eos is not None:
                # short (EOS-terminated) completion: pad with EOS so the
                # trainer's mask ends at the true completion length
                toks[g, plen + len(comp):] = eos
        return toks

    def set_params(self, params) -> None:
        # Engine dispatches read self.params per call; swapping the pytree
        # between steps is safe (in-flight steps keep the old tree).
        self.engine.params = params

    def shutdown(self) -> None:
        self.engine.shutdown()


class GRPOTrainer:
    """Sample -> score -> group-normalize -> update loop for a causal LM.

    Pass `model=` (a Llama-family module with the KV-cache apply
    contract) and sampling defaults to the serve LLM engine
    (EngineSampler); `apply_fn` is derived from it when omitted. A custom
    `sampler(prompt_ids, group) -> [G, T] tokens` overrides; with neither
    model nor sampler, a plain jitted full-forward loop samples.
    reward_fn(prompt_ids, completion_ids) -> float.
    """

    def __init__(self, apply_fn: Optional[Callable] = None, params=None,
                 reward_fn: Callable = None,
                 cfg: Optional[GRPOConfig] = None, *,
                 eos_id: Optional[int] = None,
                 sampler: Optional[Callable] = None,
                 model=None, max_seq_len: int = 512):
        self.cfg = cfg or GRPOConfig()
        if apply_fn is None:
            if model is None:
                raise ValueError("need apply_fn or model")
            def apply_fn(p, t, _m=model):  # noqa: E306
                out = _m.apply({"params": p}, t)
                return out[0] if isinstance(out, tuple) else out
        self.learner = GRPOLearner(apply_fn, params, self.cfg)
        self.ref_params = jax.device_get(params)   # frozen reference
        self.reward_fn = reward_fn
        self.eos_id = eos_id
        if sampler is None and model is not None:
            sampler = EngineSampler(model, params, self.cfg, eos_id=eos_id,
                                    max_seq_len=max_seq_len)
        self.sampler = sampler
        self._rng = jax.random.PRNGKey(self.cfg.seed)
        self._apply = self.learner._apply

        def sample_step(params, tokens, t, key):
            logits = self._apply(params, tokens)
            return jax.random.categorical(key, logits[:, t - 1], axis=-1)

        self._sample_step = jax.jit(sample_step)

    @property
    def params(self):
        return self.learner.params

    def _sample_group(self, prompt_ids: Sequence[int],
                      group: int) -> np.ndarray:
        """[G, len(prompt)+max_new] greedy-temp sampled completions."""
        cfg = self.cfg
        plen = len(prompt_ids)
        T = plen + cfg.max_new_tokens
        toks = np.zeros((group, T), np.int32)
        toks[:, :plen] = np.asarray(prompt_ids, np.int32)
        for t in range(plen, T):
            self._rng, key = jax.random.split(self._rng)
            nxt = np.asarray(self._sample_step(self.params,
                                               jnp.asarray(toks), t, key))
            toks[:, t] = nxt
        return toks

    def step(self, prompts: List[Sequence[int]]) -> Dict[str, Any]:
        """One GRPO iteration over a list of tokenized prompts."""
        cfg = self.cfg
        G = cfg.group_size
        all_toks, all_masks, rewards = [], [], []
        max_t = 0
        for p in prompts:
            if self.sampler is not None:
                toks = np.asarray(self.sampler(p, G))
            else:
                toks = self._sample_group(p, G)
            plen = len(p)
            mask = np.zeros((G, toks.shape[1] - 1), np.float32)
            for g in range(G):
                comp = toks[g, plen:]
                end = len(comp)
                if self.eos_id is not None:
                    hits = np.nonzero(comp == self.eos_id)[0]
                    if len(hits):
                        end = int(hits[0]) + 1
                # mask[t] trains the prediction of token t+1
                mask[g, plen - 1: plen - 1 + end] = 1.0
                rewards.append(float(self.reward_fn(p, comp[:end])))
            all_toks.append(toks)
            all_masks.append(mask)
            max_t = max(max_t, toks.shape[1])
        toks = np.concatenate([
            np.pad(t, ((0, 0), (0, max_t - t.shape[1]))) for t in all_toks])
        masks = np.concatenate([
            np.pad(m, ((0, 0), (0, max_t - 1 - m.shape[1])))
            for m in all_masks])
        rewards = np.asarray(rewards, np.float32)
        adv = group_relative_advantages(rewards, G)
        old_logps = self.learner.token_logps(self.params, toks)
        ref_logps = self.learner.token_logps(self.ref_params, toks)
        batch = {"tokens": toks, "mask": masks, "old_logps": old_logps,
                 "ref_logps": ref_logps, "advantages": adv}
        stats: Dict[str, float] = {}
        for _ in range(cfg.num_epochs):
            stats = self.learner.update(batch)
        if self.sampler is not None and hasattr(self.sampler, "set_params"):
            self.sampler.set_params(self.params)  # next group: new policy
        return {"reward_mean": float(rewards.mean()),
                "reward_std": float(rewards.std()), **stats}

    def shutdown(self) -> None:
        if self.sampler is not None and hasattr(self.sampler, "shutdown"):
            self.sampler.shutdown()


def make_lora_grpo_trainer(model, base_params, lora, reward_fn, *,
                           cfg: Optional[GRPOConfig] = None,
                           eos_id: Optional[int] = None,
                           max_seq_len: int = 512) -> GRPOTrainer:
    """GRPO post-training over LoRA ADAPTERS: the policy update touches
    only the adapter pytree (optimizer state O(adapter)), the frozen
    base keeps its shardings, and sampling still runs through the serve
    engine — the engine receives the merged weights after every step.
    The KL reference is the initial (zero-delta) policy.

    Standard recipe composition: train/lora.py provides the adapters;
    this wires them into the GRPO loop end-to-end.
    """
    from ..train.lora import merge_lora  # noqa: PLC0415

    meta = {"rank": lora["rank"], "alpha": lora["alpha"]}

    def apply_fn(adapters, tokens):
        merged = merge_lora(base_params, {**meta, "adapters": adapters})
        out = model.apply({"params": merged}, tokens)
        return out[0] if isinstance(out, tuple) else out

    trainer = GRPOTrainer(apply_fn=apply_fn, params=lora["adapters"],
                          reward_fn=reward_fn, cfg=cfg, eos_id=eos_id)
    sampler = EngineSampler(model, merge_lora(base_params, lora),
                            cfg or trainer.cfg, eos_id=eos_id,
                            max_seq_len=max_seq_len)
    push_merged = sampler.set_params

    def set_params(adapters):
        push_merged(merge_lora(base_params,
                               {**meta, "adapters": adapters}))

    sampler.set_params = set_params
    trainer.sampler = sampler
    return trainer
