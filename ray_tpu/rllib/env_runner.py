"""EnvRunner: vectorized rollout collection.

Reference counterpart: rllib/env/env_runner.py + rllib/evaluation/
rollout_worker.py. Runners step numpy envs on CPU and sample actions
through one jitted policy step; the learner (TPU mesh) never blocks on
env stepping. Runners run in-process (num_env_runners=0) or as
ray_tpu actors over the core runtime.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import numpy as np

from . import sample_batch as sb
from .env import VectorEnv, make_env
from .rl_module import RLModule, spec_for_env
from .sample_batch import SampleBatch, compute_gae


class EnvRunner:
    """Collects fixed-length [T, B] rollout fragments with auto-reset."""

    def __init__(self, env_spec, *, num_envs: int = 1,
                 rollout_length: int = 128, seed: int = 0,
                 env_config: Optional[Dict[str, Any]] = None,
                 hidden=(64, 64), activation: str = "tanh",
                 gamma: float = 0.99, lam: float = 0.95):
        env_config = env_config or {}
        self._env_spec, self._env_config = env_spec, env_config
        self._eval_env = None      # built lazily; never shared with vec
        self.vec = VectorEnv(
            [lambda: make_env(env_spec, **env_config)
             for _ in range(num_envs)])
        self.module = RLModule(spec_for_env(self.vec.envs[0],
                                            hidden=hidden,
                                            activation=activation))
        self.rollout_length = rollout_length
        self.gamma, self.lam = gamma, lam
        self._rng = jax.random.PRNGKey(seed)
        self._obs, _ = self.vec.reset(seed=seed)
        self._explore = jax.jit(self.module.explore_action)
        self._value_only = jax.jit(
            lambda p, o: self.module.forward(p, o)[1])
        # episode-return bookkeeping (per sub-env)
        self._ep_ret = np.zeros(self.vec.num_envs, np.float64)
        self._ep_len = np.zeros(self.vec.num_envs, np.int64)
        self.completed_returns: List[float] = []
        self.completed_lengths: List[int] = []

    def sample(self, params) -> SampleBatch:
        """Roll T steps; returns a flat [T*B] batch with GAE columns."""
        T, B = self.rollout_length, self.vec.num_envs
        obs_buf = np.zeros((T, B) + self._obs.shape[1:], np.float32)
        act_shape = () if self.module.is_discrete else (self.module.pi_out,)
        acts = np.zeros((T, B) + act_shape,
                        np.int32 if self.module.is_discrete else np.float32)
        rews = np.zeros((T, B), np.float32)
        terms = np.zeros((T, B), bool)
        vals = np.zeros((T, B), np.float32)
        logps = np.zeros((T, B), np.float32)

        for t in range(T):
            self._rng, key = jax.random.split(self._rng)
            a, lp, v = self._explore(params, self._obs, key)
            a_np = np.asarray(a)
            obs_buf[t] = self._obs
            acts[t], logps[t], vals[t] = a_np, np.asarray(lp), np.asarray(v)
            nxt, r, tm, tr, infos = self.vec.step(a_np)
            self._ep_ret += r
            self._ep_len += 1
            # Truncation ends the GAE recursion like a termination, but the
            # episode continues value-wise: fold gamma*V(final_obs) into the
            # reward (the auto-reset obs in `nxt` must NOT leak into GAE).
            trunc_only = tr & ~tm
            if trunc_only.any():
                fobs = nxt.copy()
                for i in np.nonzero(trunc_only)[0]:
                    fobs[i] = infos[i]["final_obs"]
                fv = np.asarray(self._value_only(params, fobs))
                r = r + self.gamma * fv * trunc_only
            rews[t], terms[t] = r, tm | tr
            done = tm | tr
            for i in np.nonzero(done)[0]:
                self.completed_returns.append(float(self._ep_ret[i]))
                self.completed_lengths.append(int(self._ep_len[i]))
                self._ep_ret[i] = 0.0
                self._ep_len[i] = 0
            self._obs = nxt

        last_val = np.asarray(self._value_only(params, self._obs))
        adv, ret = compute_gae(rews, vals, terms, last_val,
                               gamma=self.gamma, lam=self.lam)
        flat = lambda x: x.reshape((T * B,) + x.shape[2:])
        return SampleBatch({
            sb.OBS: flat(obs_buf), sb.ACTIONS: flat(acts),
            sb.REWARDS: flat(rews), sb.TERMINATEDS: flat(terms),
            sb.VALUES: flat(vals), sb.LOGPS: flat(logps),
            sb.ADVANTAGES: flat(adv), sb.RETURNS: flat(ret),
        })

    def pop_episode_stats(self) -> Dict[str, Any]:
        rets, lens = self.completed_returns, self.completed_lengths
        self.completed_returns, self.completed_lengths = [], []
        return {
            "episodes_this_iter": len(rets),
            "episode_return_mean": float(np.mean(rets)) if rets else None,
            "episode_len_mean": float(np.mean(lens)) if lens else None,
        }

    def evaluate(self, params, *, num_episodes: int = 5,
                 max_steps: int = 1000) -> Dict[str, float]:
        """Deterministic-policy eval rollouts (reference: evaluation
        workers, rllib/evaluation/)."""
        det = jax.jit(self.module.deterministic_action)
        returns = []
        if self._eval_env is None:
            self._eval_env = make_env(self._env_spec, **self._env_config)
        env = self._eval_env
        for ep in range(num_episodes):
            obs, _ = env.reset()
            total, steps = 0.0, 0
            while steps < max_steps:
                a = np.asarray(det(params, obs[None]))[0]
                obs, r, tm, tr, _ = env.step(a)
                total += r
                steps += 1
                if tm or tr:
                    break
            returns.append(total)
        return {"evaluation_return_mean": float(np.mean(returns)),
                "evaluation_episodes": num_episodes}
