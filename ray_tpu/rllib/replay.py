"""Replay buffers.

Reference counterpart: rllib/utils/replay_buffers/ (ReplayBuffer,
EpisodeReplayBuffer). Uniform-sampling ring buffer over columnar numpy
storage; an episode variant stores whole trajectories for algorithms
that need contiguous sequences.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from .sample_batch import SampleBatch, concat_samples


class ReplayBuffer:
    """Uniform ring buffer over transition columns (DQN-style)."""

    def __init__(self, capacity: int = 100_000, seed: int = 0):
        self.capacity = capacity
        self._cols: Dict[str, np.ndarray] = {}
        self._size = 0
        self._next = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add(self, batch: SampleBatch) -> None:
        n = batch.count
        if not self._cols:       # lazy alloc from first batch's schema
            for k, v in batch.items():
                v = np.asarray(v)
                self._cols[k] = np.zeros((self.capacity,) + v.shape[1:],
                                         v.dtype)
        for start in range(0, n, self.capacity):
            chunk = {k: np.asarray(v)[start:start + self.capacity]
                     for k, v in batch.items()}
            m = len(next(iter(chunk.values())))
            idx = (self._next + np.arange(m)) % self.capacity
            for k, v in chunk.items():
                self._cols[k][idx] = v
            self._next = int((self._next + m) % self.capacity)
            self._size = min(self._size + m, self.capacity)

    def sample(self, batch_size: int) -> SampleBatch:
        idx = self._rng.integers(0, self._size, size=batch_size)
        return SampleBatch({k: v[idx] for k, v in self._cols.items()})


class EpisodeReplayBuffer:
    """Stores whole episodes; samples by episode or as flat transitions."""

    def __init__(self, capacity_episodes: int = 1000, seed: int = 0):
        self.capacity = capacity_episodes
        self._episodes: List[SampleBatch] = []
        self._cumlen: Optional[np.ndarray] = None   # rebuilt when stale
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return len(self._episodes)

    def add_episode(self, episode: SampleBatch) -> None:
        self._episodes.append(episode)
        if len(self._episodes) > self.capacity:
            self._episodes.pop(0)
        self._cumlen = None

    def sample_episodes(self, n: int) -> List[SampleBatch]:
        idx = self._rng.integers(0, len(self._episodes), size=n)
        return [self._episodes[i] for i in idx]

    def sample(self, batch_size: int) -> SampleBatch:
        """Uniform over transitions via a cumulative-length index — no
        full flatten per call."""
        if self._cumlen is None:
            self._cumlen = np.cumsum([e.count for e in self._episodes])
        total = int(self._cumlen[-1])
        gidx = np.sort(self._rng.integers(0, total, size=batch_size))
        ep = np.searchsorted(self._cumlen, gidx, side="right")
        local = gidx - np.concatenate([[0], self._cumlen])[ep]
        keys = self._episodes[0].keys()
        out = {k: [] for k in keys}
        for e, l in zip(ep, local):
            row = self._episodes[e]
            for k in keys:
                out[k].append(np.asarray(row[k])[l])
        return SampleBatch({k: np.stack(v) for k, v in out.items()})
