"""Public ray_tpu API.

Reference parity: python/ray/__init__.py + python/ray/_private/worker.py
(init/shutdown/remote/get/put/wait/kill/cancel, get_actor, is_initialized).
"""
from __future__ import annotations

import functools
import hashlib
import threading
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from .core import runtime as runtime_mod
from .core import serialization
from .core import resources as res_mod
from .core.actor import ActorClass, ActorHandle
from .core.object_ref import ObjectRef
from .core.runtime import DriverRuntime
from .core.task import make_task_spec
from .exceptions import RuntimeNotInitializedError

_init_lock = threading.Lock()

AUTO_PUT_THRESHOLD = 256 * 1024  # large ndarray args go through the store


def init(*, address=None, num_cpus=None, num_tpus=None, resources=None,
         object_store_memory=None, namespace="default",
         max_workers=None, ignore_reinit_error=True, log_to_driver=True,
         listen=None, state_dir=None, resume=False, **_ignored):
    """Start the ray_tpu runtime in this (driver) process.

    address="ray://host:port" instead connects as a THIN CLIENT to a
    remote driver hosting a `ray_tpu.client.server.ClientServer`
    (reference parity: ray.init("ray://...") / python/ray/util/client);
    every API verb then replays on the remote cluster.

    listen="host:port" (port 0 = ephemeral) additionally opens a TCP
    listener so remote hosts can join with
    `python -m ray_tpu.core.node tcp://host:port`; the bound address is
    `init(...).tcp_address`.

    state_dir (or RAY_TPU_STATE_DIR) makes the control plane DURABLE:
    every GCS mutation appends to a write-ahead log with periodic
    snapshots. resume=True rebuilds the cluster from that state after a
    driver crash — node agents reattach, actors restart from their
    `__ray_save__` checkpoints, lost objects reconstruct via lineage —
    under a bumped driver incarnation (resume="auto" resumes when state
    exists and starts fresh otherwise). See docs/FAULT_TOLERANCE.md
    "Driver restart & job resume".
    """
    with _init_lock:
        if runtime_mod.runtime_initialized():
            if ignore_reinit_error:
                return runtime_mod.get_runtime()
            raise RuntimeError("ray_tpu.init() already called")
        if address is not None:
            if not str(address).startswith("ray://"):
                raise ValueError(
                    "init(address=...) expects a 'ray://host:port' client "
                    "address (start one with ray_tpu.client.server)")
            sizing = {"num_cpus": num_cpus, "num_tpus": num_tpus,
                      "resources": resources,
                      "object_store_memory": object_store_memory,
                      "max_workers": max_workers, "listen": listen,
                      "state_dir": state_dir,
                      "resume": resume or None}
            bad = [k for k, v in sizing.items() if v is not None]
            if bad:
                raise ValueError(
                    f"init(address='ray://...') connects to an EXISTING "
                    f"cluster; cluster-sizing options {bad} don't apply "
                    f"(reference semantics: ray.init with a ray:// "
                    f"address rejects local-cluster kwargs)")
            from .client import ClientRuntime  # noqa: PLC0415
            crt = ClientRuntime(address, namespace=namespace)
            runtime_mod.set_runtime(crt)
            return crt
        rt = DriverRuntime(num_cpus=num_cpus, num_tpus=num_tpus,
                           resources=resources,
                           object_store_memory=object_store_memory,
                           namespace=namespace, max_workers=max_workers,
                           log_to_driver=log_to_driver, listen=listen,
                           state_dir=state_dir, resume=resume)
        runtime_mod.set_runtime(rt)
        return rt


def shutdown():
    if runtime_mod.runtime_initialized():
        runtime_mod.get_runtime().shutdown()


def is_initialized() -> bool:
    return runtime_mod.runtime_initialized()


def _ensure_init():
    if not runtime_mod.runtime_initialized():
        init()
    return runtime_mod.get_runtime()


def _auto_put_large_args(rt, args, kwargs):
    """Large array args are placed in the object store and passed by ref
    (reference: put_threshold in core_worker task arg inlining)."""
    if not args and not kwargs:
        return args, kwargs

    def conv(a):
        if isinstance(a, np.ndarray) and a.nbytes > AUTO_PUT_THRESHOLD:
            return rt.put(a)
        return a
    return tuple(conv(a) for a in args), {k: conv(v) for k, v in kwargs.items()}


def _resolve_pg_strategy(opts: Dict[str, Any]) -> Dict[str, Any]:
    """A PlacementGroupSchedulingStrategy is sugar for the
    placement_group/bundle_index options (reference parity: ray accepts
    either form)."""
    from .core.scheduling import PlacementGroupSchedulingStrategy
    strat = opts.get("scheduling_strategy")
    if isinstance(strat, PlacementGroupSchedulingStrategy):
        opts = dict(opts)
        opts["placement_group"] = strat.placement_group
        opts["bundle_index"] = strat.placement_group_bundle_index
        opts["scheduling_strategy"] = None
    return opts


class RemoteFunction:
    def __init__(self, fn, *, num_cpus=None, num_tpus=None, resources=None,
                 num_returns=1, max_retries=0, retry_exceptions=False,
                 max_calls=0, placement_group=None, bundle_index=-1,
                 scheduling_strategy=None, runtime_env=None):
        from .core import runtime_env as renv_mod
        self._fn = fn
        functools.update_wrapper(self, fn)
        self._opts = dict(num_cpus=num_cpus, num_tpus=num_tpus,
                          resources=resources, num_returns=num_returns,
                          max_retries=max_retries,
                          retry_exceptions=retry_exceptions,
                          max_calls=max_calls,
                          placement_group=placement_group,
                          bundle_index=bundle_index,
                          scheduling_strategy=scheduling_strategy,
                          runtime_env=renv_mod.validate(runtime_env) or None)
        self._func_bytes: Optional[bytes] = None
        self._func_id: str = ""

    def options(self, **opts) -> "RemoteFunction":
        merged = dict(self._opts)
        merged.update({k: v for k, v in opts.items() if k in merged})
        rf = RemoteFunction(self._fn, **merged)
        rf._func_bytes, rf._func_id = self._func_bytes, self._func_id
        return rf

    def _make_spec(self, rt, args, kwargs):
        """Build the TaskSpec WITHOUT submitting (compiled DAGs batch
        specs from many nodes into one runtime.submit_many call).
        Returns (spec, streaming)."""
        if self._func_bytes is None:
            self._func_bytes = serialization.dumps_call(self._fn)
            self._func_id = hashlib.sha1(self._func_bytes).hexdigest()
        args, kwargs = _auto_put_large_args(rt, args, kwargs)
        o = _resolve_pg_strategy(self._opts)
        pg = o.get("placement_group")
        streaming = o["num_returns"] in ("streaming", "dynamic")
        spec = make_task_spec(
            self._fn, args, kwargs,
            name=getattr(self._fn, "__qualname__", "task"),
            num_returns=1 if streaming else o["num_returns"],
            resources=res_mod.normalize_task_resources(
                num_cpus=o["num_cpus"], num_tpus=o["num_tpus"],
                resources=o["resources"]),
            max_retries=o["max_retries"],
            retry_exceptions=o["retry_exceptions"],
            max_calls=o.get("max_calls", 0),
            func_bytes=self._func_bytes, func_id=self._func_id,
            placement_group_id=getattr(pg, "pg_id", None),
            bundle_index=o.get("bundle_index", -1),
            scheduling_strategy=o.get("scheduling_strategy"),
            runtime_env=o.get("runtime_env"))
        return spec, streaming

    def remote(self, *args, **kwargs):
        rt = _ensure_init()
        spec, streaming = self._make_spec(rt, args, kwargs)
        o = self._opts
        if streaming:
            # generator task: items become refs as the remote yields
            spec.streaming = True
            spec.return_ids = []
            rt.submit(spec)
            from .core.object_ref import ObjectRefGenerator  # noqa: PLC0415
            return ObjectRefGenerator(spec.task_id)
        refs = rt.submit(spec)
        return refs[0] if o["num_returns"] == 1 else refs

    def bind(self, *args, **kwargs):
        """Record a lazy DAG node (reference: ray.dag f.bind)."""
        from .dag import FunctionNode
        return FunctionNode(self, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote functions must be invoked with `.remote()` "
            f"(got direct call to {self.__name__})")


def remote(*args, **kwargs):
    """`@remote` decorator for tasks and actors, with or without options."""
    def decorate(target, opts):
        if isinstance(target, type):
            if opts.get("max_calls"):
                raise ValueError(
                    "max_calls is not supported for actors (reference "
                    "semantics); use max_restarts or actor_exit()")
            allowed = ("num_cpus", "num_tpus", "resources", "max_restarts",
                       "max_concurrency", "concurrency_groups", "name",
                       "namespace", "lifetime", "runtime_env",
                       "placement_group", "bundle_index",
                       "scheduling_strategy", "get_if_exists",
                       "checkpoint_interval_s")
            return ActorClass(target,
                              **{k: v for k, v in opts.items()
                                 if k in allowed})
        allowed = ("num_cpus", "num_tpus", "resources", "num_returns",
                   "max_retries", "retry_exceptions", "max_calls",
                   "placement_group", "bundle_index",
                   "scheduling_strategy", "runtime_env")
        return RemoteFunction(target,
                              **{k: v for k, v in opts.items()
                                 if k in allowed})

    if len(args) == 1 and not kwargs and (callable(args[0])
                                          or isinstance(args[0], type)):
        return decorate(args[0], {})
    opts = kwargs
    return lambda target: decorate(target, opts)


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None):
    rt = runtime_mod.get_runtime()
    if isinstance(refs, ObjectRef):
        return rt.get([refs], timeout=timeout)[0]
    # compiled-DAG futures (docs/DAG.md): resolved by the pipeline's
    # driver-side controller, never by the object store
    if getattr(refs, "_is_dag_ref", False):
        return refs.get(timeout=timeout)
    refs = list(refs)
    if any(getattr(r, "_is_dag_ref", False) for r in refs):
        return [r.get(timeout=timeout)
                if getattr(r, "_is_dag_ref", False)
                else rt.get([r], timeout=timeout)[0] for r in refs]
    return rt.get(refs, timeout=timeout)


def put(value: Any) -> ObjectRef:
    return _ensure_init().put(value)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True):
    return runtime_mod.get_runtime().wait(
        list(refs), num_returns=num_returns, timeout=timeout,
        fetch_local=fetch_local)


def kill(actor: ActorHandle, *, no_restart: bool = True):
    runtime_mod.get_runtime().kill_actor(actor.actor_id,
                                         no_restart=no_restart)


def cancel(ref, *, force: bool = False, recursive: bool = True):
    from .core.object_ref import ObjectRefGenerator  # noqa: PLC0415
    rt = runtime_mod.get_runtime()
    if isinstance(ref, ObjectRefGenerator):
        rt.cancel_task(ref.task_id, force=force)
    else:
        rt.cancel(ref, force=force)


def get_actor(name: str, namespace: Optional[str] = None, *,
              timeout: float = 2.0) -> ActorHandle:
    rt = runtime_mod.get_runtime()
    ns = namespace or getattr(rt, "namespace", "default")
    # Creation registers the name asynchronously in the dispatcher; poll
    # briefly so `Actor.options(name=...).remote(); get_actor(name)` works.
    import time as _time
    deadline = _time.time() + timeout
    while True:
        if rt.is_driver:
            aid = rt.gcs.lookup_named_actor(ns, name)
            if aid is None:
                found = None
            else:
                ae = rt.gcs.actors[aid]
                found = (aid, ae.class_name,
                         getattr(ae.create_spec, "method_opts", {}) or {})
        else:
            # Workers and clients resolve names through the driver's GCS.
            # A worker has no namespace attribute (None -> the driver
            # substitutes its own default); a ClientRuntime DOES carry the
            # client's namespace, which must win over the host default.
            ns_wire = namespace if namespace is not None \
                else getattr(rt, "namespace", None)
            found = rt.report_sync("sys.lookup_actor", (ns_wire, name),
                                   timeout=5.0)
        if found is not None:
            return ActorHandle(found[0], found[1],
                               method_opts=found[2] if len(found) > 2
                               else {})
        if _time.time() > deadline:
            raise ValueError(f"no actor named {name!r} in namespace {ns!r}")
        _time.sleep(0.01)


def free(refs: Sequence[ObjectRef]):
    runtime_mod.get_runtime().free(list(refs))


def actor_exit():
    """Gracefully shut down the current actor from inside one of its
    methods (reference: ray.actor.exit_actor). The in-flight call
    returns None; the actor dies without restart; subsequent calls
    raise ActorDiedError."""
    from .exceptions import ActorExitRequest  # noqa: PLC0415
    rt = runtime_mod.get_runtime()
    if rt.is_driver or getattr(rt, "current_actor_id", None) is None:
        raise RuntimeError("actor_exit() must be called inside an "
                           "actor method")
    raise ActorExitRequest()


def method(**opts):
    """Per-method actor defaults, e.g. `@ray_tpu.method(num_returns=2)`.

    Reference parity: ray.method (python/ray/actor.py) — the declared
    options become the defaults every time the method is invoked through
    an ActorHandle. num_returns is overridable per call with
    `.options(...)`; concurrency_group is declaration-only (a method
    belongs to one group for the actor's lifetime).
    """
    allowed = {"num_returns", "concurrency_group"}
    bad = set(opts) - allowed
    if bad:
        raise ValueError(f"unsupported @method option(s): {sorted(bad)}")

    def decorate(fn):
        fn.__ray_tpu_method_opts__ = dict(opts)
        return fn

    return decorate


def nodes():
    """Cluster node table. Reference parity: ray.nodes()."""
    from .util.state import list_nodes  # noqa: PLC0415
    return list_nodes(limit=10_000)


def timeline(filename: Optional[str] = None):
    """Export task/actor spans as chrome://tracing JSON.
    Reference parity: ray.timeline()."""
    from .observability.timeline import timeline as _timeline  # noqa: PLC0415
    return _timeline(filename)


def get_tpu_ids():
    """TPU chip indices reserved for the current task/actor (analog of
    ray.get_gpu_ids; chips are indices into the host's jax TPU devices)."""
    rt = runtime_mod.get_runtime()
    return list(getattr(rt, "current_tpu_ids", []) or [])


def cluster_resources() -> Dict[str, float]:
    return runtime_mod.get_runtime().get_resources()


def available_resources() -> Dict[str, float]:
    return runtime_mod.get_runtime().available_resources()


class RuntimeContext:
    """Parity: python/ray/runtime_context.py."""

    def __init__(self, rt):
        self._rt = rt

    @property
    def job_id(self):
        return getattr(self._rt, "job_id", "job-default")

    @property
    def node_id(self):
        return getattr(self._rt, "node_id", "node-local")

    def get_task_id(self):
        return getattr(self._rt, "current_task_id", None)

    def get_actor_id(self):
        return getattr(self._rt, "current_actor_id", None)

    @property
    def was_current_actor_reconstructed(self):
        # True inside an actor whose current life began with a
        # __ray_restore__ (worker-death restart OR driver resume)
        return bool(getattr(self._rt, "actor_restored", False))

    def get_resources(self):
        return self._rt.get_resources() if self._rt.is_driver else {}


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(runtime_mod.get_runtime())
