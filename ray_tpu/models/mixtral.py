"""Mixtral-style sparse-MoE decoder, TPU-first.

The reference serves/trains Mixtral through HF torch (dynamic per-token
expert gather). Here the MoE MLP uses ray_tpu.ops.moe's static-shaped
GShard dispatch so expert compute is batched einsums the MXU likes, and
the stacked expert weights carry a leading expert axis sharded over the
`ep` mesh axis (see parallel/sharding.py DEFAULT_RULES: `experts_*`).

Attention/RoPE/norms reuse the Llama blocks — weight layout stays
`layer_{i}/attention/...` so serve/train tooling treats both families
uniformly.
"""
from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops import rms_norm, rope_frequencies, swiglu
from ..ops.moe import moe_dispatch_combine, expert_capacity
from .llama import LlamaAttention, LlamaConfig


@dataclasses.dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32000
    d_model: int = 2048
    n_layers: int = 16
    n_heads: int = 16
    n_kv_heads: int = 8
    d_ff: int = 5632
    n_experts: int = 8
    experts_per_token: int = 2
    capacity_factor: float = 1.25
    max_seq_len: int = 2048
    rope_theta: float = 1e6
    norm_eps: float = 1e-5
    router_aux_coef: float = 0.01
    router_z_coef: float = 1e-3
    remat: bool = False
    dtype: Any = jnp.bfloat16
    attn_impl: str = "auto"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def attn_cfg(self) -> LlamaConfig:
        """The attention sub-config shared with the Llama blocks."""
        return LlamaConfig(
            vocab_size=self.vocab_size, d_model=self.d_model,
            n_layers=self.n_layers, n_heads=self.n_heads,
            n_kv_heads=self.n_kv_heads, d_ff=self.d_ff,
            max_seq_len=self.max_seq_len, rope_theta=self.rope_theta,
            norm_eps=self.norm_eps, dtype=self.dtype,
            attn_impl=self.attn_impl)

    @staticmethod
    def mixtral_8x7b(**kw) -> "MixtralConfig":
        return MixtralConfig(vocab_size=32000, d_model=4096, n_layers=32,
                             n_heads=32, n_kv_heads=8, d_ff=14336,
                             n_experts=8, experts_per_token=2,
                             max_seq_len=8192, remat=True, **kw)

    @staticmethod
    def debug(**kw) -> "MixtralConfig":
        return MixtralConfig(vocab_size=256, d_model=64, n_layers=2,
                             n_heads=4, n_kv_heads=2, d_ff=128,
                             n_experts=4, experts_per_token=2,
                             max_seq_len=128, **kw)


class MoEMLP(nn.Module):
    """Top-k routed SwiGLU experts with stacked (E, ...) weights."""
    cfg: MixtralConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        b, s, d = x.shape
        router_w = self.param(
            "router_kernel", nn.initializers.normal(0.02),
            (d, cfg.n_experts))
        # Stacked expert weights; names match sharding DEFAULT_RULES so the
        # expert axis lands on `ep` and the ff dims on fsdp/tp.
        init = nn.initializers.lecun_normal()
        w_gate = self.param("experts_gate_kernel", init,
                            (cfg.n_experts, d, cfg.d_ff))
        w_up = self.param("experts_up_kernel", init,
                          (cfg.n_experts, d, cfg.d_ff))
        w_down = self.param("experts_down_kernel", init,
                            (cfg.n_experts, cfg.d_ff, d))

        tokens = x.reshape(b * s, d)
        router_logits = jnp.einsum(
            "gd,de->ge", tokens.astype(jnp.float32),
            router_w.astype(jnp.float32))

        def expert_fn(batch):   # (E, C, d) -> (E, C, d)
            batch = batch.astype(cfg.dtype)
            gate = jnp.einsum("ecd,edf->ecf", batch, w_gate.astype(cfg.dtype))
            up = jnp.einsum("ecd,edf->ecf", batch, w_up.astype(cfg.dtype))
            return jnp.einsum("ecf,efd->ecd", swiglu(gate, up),
                              w_down.astype(cfg.dtype))

        cap = expert_capacity(b * s, cfg.n_experts, cfg.experts_per_token,
                              cfg.capacity_factor)
        out, aux = moe_dispatch_combine(
            tokens, router_logits, expert_fn,
            k=cfg.experts_per_token, capacity=cap)
        self.sow("aux_loss", "router",
                 cfg.router_aux_coef * aux.load_balance_loss
                 + cfg.router_z_coef * aux.router_z_loss)
        return out.reshape(b, s, d).astype(cfg.dtype)


class MixtralBlock(nn.Module):
    cfg: MixtralConfig

    @nn.compact
    def __call__(self, x, cos, sin, cache=None, positions=None):
        cfg = self.cfg
        attn_norm_w = self.param("attn_norm", nn.initializers.ones,
                                 (cfg.d_model,))
        mlp_norm_w = self.param("mlp_norm", nn.initializers.ones,
                                (cfg.d_model,))
        h, new_cache = LlamaAttention(cfg.attn_cfg(), name="attention")(
            rms_norm(x, attn_norm_w, cfg.norm_eps), cos, sin, cache,
            positions)
        x = x + h
        x = x + MoEMLP(cfg, name="moe")(rms_norm(x, mlp_norm_w,
                                                 cfg.norm_eps))
        return x, new_cache


class Mixtral(nn.Module):
    """tokens (B, S) -> (logits, cache). Same calling convention as Llama
    so the serve engine and trainers are model-family agnostic.

    The summed router aux loss is exposed via the "aux_loss" collection:
    `model.apply(vars, tokens, mutable=["aux_loss"])`.
    """
    cfg: MixtralConfig

    @nn.compact
    def __call__(self, tokens, cache=None, positions=None):
        cfg = self.cfg
        embed = nn.Embed(cfg.vocab_size, cfg.d_model, name="token_embed",
                         dtype=cfg.dtype,
                         embedding_init=nn.initializers.normal(0.02))
        x = embed(tokens)
        cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len,
                                    cfg.rope_theta)
        block_cls = (nn.remat(MixtralBlock)
                     if (cfg.remat and cache is None) else MixtralBlock)
        new_cache = []
        for i in range(cfg.n_layers):
            block = block_cls(cfg, name=f"layer_{i}")
            x, c = block(x, cos, sin,
                         None if cache is None else cache[i], positions)
            new_cache.append(c)
        final_w = self.param("final_norm", nn.initializers.ones,
                             (cfg.d_model,))
        x = rms_norm(x, final_w, cfg.norm_eps)
        logits = nn.Dense(cfg.vocab_size, use_bias=False, name="lm_head",
                          dtype=jnp.float32)(x.astype(jnp.float32))
        return logits, (new_cache if cache is not None else None)

    def init_params(self, rng, batch=1, seq=8):
        tokens = jnp.zeros((batch, seq), dtype=jnp.int32)
        return self.init(rng, tokens)["params"]

    def empty_cache(self, batch: int, max_len: int, dtype=jnp.bfloat16):
        cfg = self.cfg
        return [
            (jnp.zeros((batch, max_len, cfg.n_kv_heads, cfg.head_dim),
                       dtype=dtype),
             jnp.zeros((batch, max_len, cfg.n_kv_heads, cfg.head_dim),
                       dtype=dtype),
             jnp.zeros((batch,), dtype=jnp.int32))
            for _ in range(cfg.n_layers)
        ]

    @staticmethod
    def aux_loss(mutables) -> jax.Array:
        """Sum the sown per-layer router losses from `mutable=["aux_loss"]`."""
        leaves = jax.tree_util.tree_leaves(mutables.get("aux_loss", {}))
        if not leaves:
            return jnp.float32(0.0)
        return sum(jnp.sum(leaf) for leaf in leaves)
