"""Small dense nets: MLP and ResNet-lite.

Reference-side counterpart: the torch nn.Sequential policy/value nets in
rllib catalogs (rllib/core/models/) and the tabular models in train/tune
examples. These back ray_tpu.rllib policies and the tune/train smoke
paths, so they stay tiny, fp32, and jit-cheap.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class MLPConfig:
    hidden: Sequence[int] = (64, 64)
    out_dim: int = 1
    activation: str = "tanh"     # "tanh" | "relu" | "gelu"
    dtype: Any = jnp.float32


_ACTS = {"tanh": nn.tanh, "relu": nn.relu, "gelu": nn.gelu}


class MLP(nn.Module):
    cfg: MLPConfig

    @nn.compact
    def __call__(self, x):
        act = _ACTS[self.cfg.activation]
        for i, h in enumerate(self.cfg.hidden):
            x = act(nn.Dense(h, name=f"fc_{i}",
                             dtype=self.cfg.dtype)(x))
        return nn.Dense(self.cfg.out_dim, name="head",
                        dtype=self.cfg.dtype)(x)

    def init_params(self, rng, in_dim: int):
        return self.init(rng, jnp.zeros((1, in_dim)))["params"]


class ResNetLite(nn.Module):
    """Tiny pre-activation residual conv net for 32x32-ish images."""
    num_classes: int = 10
    width: int = 32
    n_blocks: int = 3

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(self.width, (3, 3), name="stem")(x)
        for i in range(self.n_blocks):
            w = self.width * (2 ** i)
            h = nn.relu(nn.GroupNorm(num_groups=8,
                                     name=f"block{i}_gn1")(x))
            h = nn.Conv(w, (3, 3), name=f"block{i}_conv1")(h)
            h = nn.relu(nn.GroupNorm(num_groups=8,
                                     name=f"block{i}_gn2")(h))
            h = nn.Conv(w, (3, 3), name=f"block{i}_conv2")(h)
            if x.shape[-1] != w:
                x = nn.Conv(w, (1, 1), name=f"block{i}_skip")(x)
            x = x + h
            x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.mean(axis=(1, 2))
        return nn.Dense(self.num_classes, name="head")(x)

    def init_params(self, rng, image_size: int = 32, channels: int = 3):
        return self.init(
            rng, jnp.zeros((1, image_size, image_size, channels)))["params"]
