"""Vision Transformer classifier, TPU-first.

Reference-side counterpart: the torchvision/HF image models used across
Ray Train/Serve examples (e.g. doc image-classification examples and
`python/ray/train` vision tutorials). Built on flax.linen with the same
sharding-friendly naming as the decoders (q_proj/.../fc_in/fc_out), so
tp/fsdp rules apply unchanged.

Patchify is a single strided conv (one big MXU matmul after im2col, which
XLA does for free); encoder blocks are pre-norm MHA + GELU MLP.
"""
from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from ..ops import layer_norm, multi_head_attention


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    channels: int = 3
    pool: str = "cls"            # "cls" | "mean"
    dtype: Any = jnp.bfloat16

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @staticmethod
    def base(**kw) -> "ViTConfig":
        return ViTConfig(**kw)

    @staticmethod
    def debug(**kw) -> "ViTConfig":
        return ViTConfig(image_size=32, patch_size=8, num_classes=10,
                         d_model=64, n_layers=2, n_heads=4, d_ff=128, **kw)


class ViTBlock(nn.Module):
    """Pre-norm MHA + GELU MLP. Also serves as the CLIP text block with
    causal=True (the only difference between the towers)."""
    cfg: ViTConfig
    causal: bool = False

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        b, s, d = x.shape
        h = layer_norm(x,
                       self.param("ln1_scale", nn.initializers.ones, (d,)),
                       self.param("ln1_bias", nn.initializers.zeros, (d,)))
        q = nn.Dense(d, name="q_proj", dtype=cfg.dtype)(h)
        k = nn.Dense(d, name="k_proj", dtype=cfg.dtype)(h)
        v = nn.Dense(d, name="v_proj", dtype=cfg.dtype)(h)
        q = q.reshape(b, s, cfg.n_heads, cfg.head_dim)
        k = k.reshape(b, s, cfg.n_heads, cfg.head_dim)
        v = v.reshape(b, s, cfg.n_heads, cfg.head_dim)
        att = multi_head_attention(q, k, v, causal=self.causal)
        x = x + nn.Dense(d, name="o_proj", dtype=cfg.dtype)(
            att.reshape(b, s, d))
        h = layer_norm(x,
                       self.param("ln2_scale", nn.initializers.ones, (d,)),
                       self.param("ln2_bias", nn.initializers.zeros, (d,)))
        h = nn.Dense(cfg.d_ff, name="fc_in", dtype=cfg.dtype)(h)
        h = nn.gelu(h)
        x = x + nn.Dense(d, name="fc_out", dtype=cfg.dtype)(h)
        return x


class ViTTrunk(nn.Module):
    """Patchify -> [cls | patches] + pos -> encoder blocks -> final LN.

    Returns the full (B, n_patches+1, d_model) sequence; classifiers pool
    it, CLIP projects x[:, 0]. Shared by ViT and CLIP so the towers can't
    drift apart.
    """
    cfg: ViTConfig

    @nn.compact
    def __call__(self, images):
        cfg = self.cfg
        b = images.shape[0]
        x = nn.Conv(cfg.d_model,
                    kernel_size=(cfg.patch_size, cfg.patch_size),
                    strides=(cfg.patch_size, cfg.patch_size),
                    name="patch_embed", dtype=cfg.dtype)(
                        images.astype(cfg.dtype))
        x = x.reshape(b, -1, cfg.d_model)
        cls = self.param("cls_token", nn.initializers.zeros,
                         (1, 1, cfg.d_model))
        x = jnp.concatenate(
            [jnp.broadcast_to(cls, (b, 1, cfg.d_model)).astype(cfg.dtype),
             x], axis=1)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, cfg.n_patches + 1, cfg.d_model))
        x = x + pos.astype(cfg.dtype)
        for i in range(cfg.n_layers):
            x = ViTBlock(cfg, name=f"layer_{i}")(x)
        return layer_norm(
            x, self.param("ln_f_scale", nn.initializers.ones,
                          (cfg.d_model,)),
            self.param("ln_f_bias", nn.initializers.zeros, (cfg.d_model,)))


class ViT(nn.Module):
    """images (B, H, W, C) float -> logits (B, num_classes) fp32."""
    cfg: ViTConfig

    @nn.compact
    def __call__(self, images):
        cfg = self.cfg
        x = ViTTrunk(cfg, name="trunk")(images)
        feat = x[:, 0] if cfg.pool == "cls" else x.mean(axis=1)
        return nn.Dense(cfg.num_classes, name="head",
                        dtype=jnp.float32)(feat.astype(jnp.float32))

    def init_params(self, rng, batch=1):
        cfg = self.cfg
        images = jnp.zeros((batch, cfg.image_size, cfg.image_size,
                            cfg.channels), jnp.float32)
        return self.init(rng, images)["params"]
