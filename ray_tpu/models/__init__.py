"""Model zoo (SURVEY.md §2.2 P10).

Registry mirrors what reference users reach for through HF/torch in Ray
Train/Serve/RLlib examples, re-implemented TPU-first.
"""
from .llama import Llama, LlamaConfig
from .gpt2 import GPT2, GPT2Config

_REGISTRY = {
    "llama3-8b": lambda **kw: Llama(LlamaConfig.llama3_8b(**kw)),
    "llama3-1b": lambda **kw: Llama(LlamaConfig.llama3_1b(**kw)),
    "llama-debug": lambda **kw: Llama(LlamaConfig.debug(**kw)),
    "gpt2": lambda **kw: GPT2(GPT2Config.small(**kw)),
    "gpt2-medium": lambda **kw: GPT2(GPT2Config.medium(**kw)),
    "gpt2-large": lambda **kw: GPT2(GPT2Config.large(**kw)),
    "gpt2-debug": lambda **kw: GPT2(GPT2Config.debug(**kw)),
}


def get_model(name: str, **kw):
    if name not in _REGISTRY:
        raise KeyError(f"unknown model {name!r}; have {sorted(_REGISTRY)}")
    return _REGISTRY[name](**kw)


def register_model(name: str, builder) -> None:
    _REGISTRY[name] = builder


__all__ = ["Llama", "LlamaConfig", "GPT2", "GPT2Config", "get_model",
           "register_model"]
