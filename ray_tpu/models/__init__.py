"""Model zoo (SURVEY.md §2.2 P10).

Registry mirrors what reference users reach for through HF/torch in Ray
Train/Serve/RLlib examples, re-implemented TPU-first.
"""
from .llama import Llama, LlamaConfig
from .gpt2 import GPT2, GPT2Config
from .mixtral import Mixtral, MixtralConfig
from .vit import ViT, ViTConfig
from .clip import CLIP, CLIPConfig, contrastive_loss
from .mlp import MLP, MLPConfig, ResNetLite

_REGISTRY = {
    "llama3-8b": lambda **kw: Llama(LlamaConfig.llama3_8b(**kw)),
    "llama3-1b": lambda **kw: Llama(LlamaConfig.llama3_1b(**kw)),
    "llama-debug": lambda **kw: Llama(LlamaConfig.debug(**kw)),
    "gpt2": lambda **kw: GPT2(GPT2Config.small(**kw)),
    "gpt2-medium": lambda **kw: GPT2(GPT2Config.medium(**kw)),
    "gpt2-large": lambda **kw: GPT2(GPT2Config.large(**kw)),
    "gpt2-debug": lambda **kw: GPT2(GPT2Config.debug(**kw)),
    "mixtral-8x7b": lambda **kw: Mixtral(MixtralConfig.mixtral_8x7b(**kw)),
    "mixtral-debug": lambda **kw: Mixtral(MixtralConfig.debug(**kw)),
    "vit-base": lambda **kw: ViT(ViTConfig.base(**kw)),
    "vit-debug": lambda **kw: ViT(ViTConfig.debug(**kw)),
    "clip-debug": lambda **kw: CLIP(CLIPConfig.debug(**kw)),
    "resnet-lite": lambda **kw: ResNetLite(**kw),
}


def get_model(name: str, **kw):
    if name not in _REGISTRY:
        raise KeyError(f"unknown model {name!r}; have {sorted(_REGISTRY)}")
    return _REGISTRY[name](**kw)


def register_model(name: str, builder) -> None:
    _REGISTRY[name] = builder


__all__ = ["Llama", "LlamaConfig", "GPT2", "GPT2Config", "Mixtral",
           "MixtralConfig", "ViT", "ViTConfig", "CLIP", "CLIPConfig",
           "contrastive_loss", "MLP", "MLPConfig", "ResNetLite",
           "get_model", "register_model"]
