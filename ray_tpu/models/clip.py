"""CLIP-style dual encoder (image tower + text tower, contrastive loss).

Reference-side counterpart: HF CLIP used in Ray Data/Serve multimodal
examples (batch inference pipelines). Vision tower reuses the ViT trunk;
text tower is a small causal transformer pooled at EOT; both project into
a shared embedding space with a learnable logit temperature.
"""
from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops import layer_norm
from .vit import ViTConfig, ViTBlock, ViTTrunk


@dataclasses.dataclass(frozen=True)
class CLIPConfig:
    embed_dim: int = 512
    # vision
    image_size: int = 224
    patch_size: int = 32
    vision_d_model: int = 768
    vision_layers: int = 12
    vision_heads: int = 12
    # text
    vocab_size: int = 49408
    max_text_len: int = 77
    text_d_model: int = 512
    text_layers: int = 12
    text_heads: int = 8
    dtype: Any = jnp.bfloat16

    def vision_cfg(self) -> ViTConfig:
        return ViTConfig(image_size=self.image_size,
                         patch_size=self.patch_size,
                         num_classes=self.embed_dim,
                         d_model=self.vision_d_model,
                         n_layers=self.vision_layers,
                         n_heads=self.vision_heads,
                         d_ff=self.vision_d_model * 4,
                         dtype=self.dtype)

    @staticmethod
    def debug(**kw) -> "CLIPConfig":
        return CLIPConfig(embed_dim=32, image_size=32, patch_size=8,
                          vision_d_model=64, vision_layers=2,
                          vision_heads=4, vocab_size=256, max_text_len=16,
                          text_d_model=48, text_layers=2, text_heads=4,
                          **kw)


class CLIP(nn.Module):
    """(images (B,H,W,C), tokens (B,T)) -> (img_emb, txt_emb, logit_scale).

    Embeddings are L2-normalized fp32; `contrastive_loss` gives the
    symmetric InfoNCE objective. Text pools at each row's EOT token —
    `tokens.argmax(-1)`, the OpenAI CLIP convention: EOT must be the
    highest id in the vocab, so right-padded captions pool at content,
    not padding.
    """
    cfg: CLIPConfig

    def text_cfg(self) -> ViTConfig:
        """Shape-only config for the text blocks (reuses ViTBlock)."""
        cfg = self.cfg
        return ViTConfig(d_model=cfg.text_d_model, n_heads=cfg.text_heads,
                         d_ff=cfg.text_d_model * 4, dtype=cfg.dtype)

    @nn.compact
    def __call__(self, images, tokens):
        cfg = self.cfg

        # ---- vision tower: shared ViT trunk + linear projection ----
        x = ViTTrunk(cfg.vision_cfg(), name="vision_trunk")(images)
        img_emb = nn.Dense(cfg.embed_dim, use_bias=False,
                           name="vision_proj",
                           dtype=jnp.float32)(x[:, 0].astype(jnp.float32))

        # ---- text tower: causal ViTBlocks, pooled at EOT ----
        t = nn.Embed(cfg.vocab_size, cfg.text_d_model, name="token_embed",
                     dtype=cfg.dtype,
                     embedding_init=nn.initializers.normal(0.02))(tokens)
        tpos = self.param("text_pos_embed", nn.initializers.normal(0.02),
                          (1, cfg.max_text_len, cfg.text_d_model))
        t = t + tpos[:, :tokens.shape[1]].astype(cfg.dtype)
        tcfg = self.text_cfg()
        for i in range(cfg.text_layers):
            t = ViTBlock(tcfg, causal=True, name=f"text_layer_{i}")(t)
        t = layer_norm(
            t, self.param("text_ln_scale", nn.initializers.ones,
                          (cfg.text_d_model,)),
            self.param("text_ln_bias", nn.initializers.zeros,
                       (cfg.text_d_model,)))
        eot = jnp.argmax(tokens, axis=-1)
        pooled = t[jnp.arange(tokens.shape[0]), eot]
        txt_emb = nn.Dense(cfg.embed_dim, use_bias=False, name="text_proj",
                           dtype=jnp.float32)(pooled.astype(jnp.float32))

        logit_scale = self.param("logit_scale",
                                 nn.initializers.constant(2.6592), ())
        img_emb = img_emb / (jnp.linalg.norm(img_emb, axis=-1,
                                             keepdims=True) + 1e-8)
        txt_emb = txt_emb / (jnp.linalg.norm(txt_emb, axis=-1,
                                             keepdims=True) + 1e-8)
        return img_emb, txt_emb, jnp.exp(logit_scale)

    def init_params(self, rng, batch=1):
        cfg = self.cfg
        images = jnp.zeros((batch, cfg.image_size, cfg.image_size, 3),
                           jnp.float32)
        tokens = jnp.zeros((batch, cfg.max_text_len), jnp.int32)
        return self.init(rng, images, tokens)["params"]


def contrastive_loss(img_emb, txt_emb, logit_scale) -> jax.Array:
    """Symmetric InfoNCE over in-batch negatives (fp32)."""
    logits = logit_scale * img_emb @ txt_emb.T
    labels = jnp.arange(logits.shape[0])
    li = -jnp.mean(jax.nn.log_softmax(logits, axis=-1)[labels, labels])
    lt = -jnp.mean(jax.nn.log_softmax(logits.T, axis=-1)[labels, labels])
    return 0.5 * (li + lt)
