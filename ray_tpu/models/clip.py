"""CLIP-style dual encoder (image tower + text tower, contrastive loss).

Reference-side counterpart: HF CLIP used in Ray Data/Serve multimodal
examples (batch inference pipelines). Vision tower reuses the ViT trunk;
text tower is a small causal transformer pooled at EOT; both project into
a shared embedding space with a learnable logit temperature.
"""
from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops import layer_norm, multi_head_attention
from .vit import ViTConfig, ViTBlock


@dataclasses.dataclass(frozen=True)
class CLIPConfig:
    embed_dim: int = 512
    # vision
    image_size: int = 224
    patch_size: int = 32
    vision_d_model: int = 768
    vision_layers: int = 12
    vision_heads: int = 12
    # text
    vocab_size: int = 49408
    max_text_len: int = 77
    text_d_model: int = 512
    text_layers: int = 12
    text_heads: int = 8
    dtype: Any = jnp.bfloat16

    def vision_cfg(self) -> ViTConfig:
        return ViTConfig(image_size=self.image_size,
                         patch_size=self.patch_size,
                         num_classes=self.embed_dim,
                         d_model=self.vision_d_model,
                         n_layers=self.vision_layers,
                         n_heads=self.vision_heads,
                         d_ff=self.vision_d_model * 4,
                         dtype=self.dtype)

    @staticmethod
    def debug(**kw) -> "CLIPConfig":
        return CLIPConfig(embed_dim=32, image_size=32, patch_size=8,
                          vision_d_model=64, vision_layers=2,
                          vision_heads=4, vocab_size=256, max_text_len=16,
                          text_d_model=48, text_layers=2, text_heads=4,
                          **kw)


class _TextBlock(nn.Module):
    cfg: CLIPConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        b, s, d = x.shape
        hd = d // cfg.text_heads
        h = layer_norm(x,
                       self.param("ln1_scale", nn.initializers.ones, (d,)),
                       self.param("ln1_bias", nn.initializers.zeros, (d,)))
        q = nn.Dense(d, name="q_proj", dtype=cfg.dtype)(h)
        k = nn.Dense(d, name="k_proj", dtype=cfg.dtype)(h)
        v = nn.Dense(d, name="v_proj", dtype=cfg.dtype)(h)
        att = multi_head_attention(
            q.reshape(b, s, cfg.text_heads, hd),
            k.reshape(b, s, cfg.text_heads, hd),
            v.reshape(b, s, cfg.text_heads, hd), causal=True)
        x = x + nn.Dense(d, name="o_proj", dtype=cfg.dtype)(
            att.reshape(b, s, d))
        h = layer_norm(x,
                       self.param("ln2_scale", nn.initializers.ones, (d,)),
                       self.param("ln2_bias", nn.initializers.zeros, (d,)))
        h = nn.gelu(nn.Dense(d * 4, name="fc_in", dtype=cfg.dtype)(h))
        return x + nn.Dense(d, name="fc_out", dtype=cfg.dtype)(h)


class CLIP(nn.Module):
    """(images (B,H,W,C), tokens (B,T)) -> (img_emb, txt_emb, logit_scale).

    Embeddings are L2-normalized fp32; `contrastive_loss` gives the
    symmetric InfoNCE objective.
    """
    cfg: CLIPConfig

    @nn.compact
    def __call__(self, images, tokens):
        cfg = self.cfg

        # ---- vision tower: ViT trunk + linear projection ----
        vcfg = cfg.vision_cfg()
        b = images.shape[0]
        x = nn.Conv(vcfg.d_model,
                    kernel_size=(vcfg.patch_size, vcfg.patch_size),
                    strides=(vcfg.patch_size, vcfg.patch_size),
                    name="patch_embed", dtype=cfg.dtype)(
                        images.astype(cfg.dtype))
        x = x.reshape(b, -1, vcfg.d_model)
        cls = self.param("cls_token", nn.initializers.zeros,
                         (1, 1, vcfg.d_model))
        x = jnp.concatenate(
            [jnp.broadcast_to(cls, (b, 1, vcfg.d_model)).astype(cfg.dtype),
             x], axis=1)
        pos = self.param("vision_pos_embed", nn.initializers.normal(0.02),
                         (1, vcfg.n_patches + 1, vcfg.d_model))
        x = x + pos.astype(cfg.dtype)
        for i in range(vcfg.n_layers):
            x = ViTBlock(vcfg, name=f"vision_layer_{i}")(x)
        x = layer_norm(
            x, self.param("vision_ln_scale", nn.initializers.ones,
                          (vcfg.d_model,)),
            self.param("vision_ln_bias", nn.initializers.zeros,
                       (vcfg.d_model,)))
        img_emb = nn.Dense(cfg.embed_dim, use_bias=False,
                           name="vision_proj",
                           dtype=jnp.float32)(x[:, 0].astype(jnp.float32))

        # ---- text tower: causal transformer, pooled at last token ----
        t = nn.Embed(cfg.vocab_size, cfg.text_d_model, name="token_embed",
                     dtype=cfg.dtype,
                     embedding_init=nn.initializers.normal(0.02))(tokens)
        tpos = self.param("text_pos_embed", nn.initializers.normal(0.02),
                          (1, cfg.max_text_len, cfg.text_d_model))
        t = t + tpos[:, :tokens.shape[1]].astype(cfg.dtype)
        for i in range(cfg.text_layers):
            t = _TextBlock(cfg, name=f"text_layer_{i}")(t)
        t = layer_norm(
            t, self.param("text_ln_scale", nn.initializers.ones,
                          (cfg.text_d_model,)),
            self.param("text_ln_bias", nn.initializers.zeros,
                       (cfg.text_d_model,)))
        txt_emb = nn.Dense(cfg.embed_dim, use_bias=False, name="text_proj",
                           dtype=jnp.float32)(
                               t[:, -1].astype(jnp.float32))

        logit_scale = self.param("logit_scale",
                                 nn.initializers.constant(2.6592), ())
        img_emb = img_emb / (jnp.linalg.norm(img_emb, axis=-1,
                                             keepdims=True) + 1e-8)
        txt_emb = txt_emb / (jnp.linalg.norm(txt_emb, axis=-1,
                                             keepdims=True) + 1e-8)
        return img_emb, txt_emb, jnp.exp(logit_scale)

    def init_params(self, rng, batch=1):
        cfg = self.cfg
        images = jnp.zeros((batch, cfg.image_size, cfg.image_size, 3),
                           jnp.float32)
        tokens = jnp.zeros((batch, cfg.max_text_len), jnp.int32)
        return self.init(rng, images, tokens)["params"]


def contrastive_loss(img_emb, txt_emb, logit_scale) -> jax.Array:
    """Symmetric InfoNCE over in-batch negatives (fp32)."""
    logits = logit_scale * img_emb @ txt_emb.T
    labels = jnp.arange(logits.shape[0])
    li = -jnp.mean(jax.nn.log_softmax(logits, axis=-1)[labels, labels])
    lt = -jnp.mean(jax.nn.log_softmax(logits.T, axis=-1)[labels, labels])
    return 0.5 * (li + lt)
