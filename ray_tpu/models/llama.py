"""Llama-3-style decoder-only transformer, TPU-first.

Built from scratch on flax.linen + ray_tpu.ops (not a port of any torch
implementation; the reference trains Llama via HF torch models inside
TorchTrainer — e.g. python/ray/train/examples and doc/source/train llm
examples). Design notes:
  * GQA attention, RoPE, RMSNorm, SwiGLU — all bf16 compute, fp32 norms.
  * Pure-functional KV cache (pytree in/out) so the serve engine can jit
    prefill/decode separately with static shapes.
  * Optional `remat` applies jax.checkpoint per block (HBM <-> FLOPs trade).
  * Module names line up with ray_tpu.parallel.sharding DEFAULT_RULES, so
    tp/fsdp PartitionSpecs attach without model surgery.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops import (rms_norm, apply_rotary, rope_frequencies,
                   cached_attention,
                   multi_head_attention, swiglu)


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    d_model: int = 2048
    n_layers: int = 16
    n_heads: int = 16
    n_kv_heads: int = 8
    d_ff: int = 5632
    max_seq_len: int = 2048
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    tie_embeddings: bool = False
    remat: bool = False
    # "full": recompute everything in backward (max HBM savings, ~1.33x
    # FLOPs). "dots": jax.checkpoint saves matmul outputs and
    # recomputes only the cheap elementwise ops — most of the memory
    # win at a fraction of the recompute (the >=1B single-chip MFU
    # lever once grad accumulation keeps micro-batches small).
    remat_policy: str = "full"
    dtype: Any = jnp.bfloat16
    # Storage dtype of the big parameter tensors (embeddings + matmul
    # kernels). fp32 default; bf16 halves parameter HBM — the knob that
    # fits >=1B-param training on one 16 GB chip (norm weights stay
    # fp32 regardless: they're tiny and fp32 norms are load-bearing).
    param_dtype: Any = jnp.float32
    attn_impl: str = "auto"         # "auto" | "xla" | "dpa" | "pallas"
    # None = fp weights; "int8" = weight-only quantized projections
    # (ops/quant.py QuantDense; params from quantize_llama_params).
    # Serving-only: int8 kernels are not trained.
    quant: Optional[str] = None

    def __post_init__(self):
        if self.d_model % self.n_heads:
            raise ValueError(
                f"d_model={self.d_model} must be divisible by "
                f"n_heads={self.n_heads}")
        if (self.d_model // self.n_heads) % 2:
            raise ValueError(
                f"head_dim={self.d_model // self.n_heads} must be even "
                f"(RoPE rotates dimension pairs)")
        if self.n_heads % self.n_kv_heads:
            raise ValueError(
                f"n_heads={self.n_heads} must be divisible by "
                f"n_kv_heads={self.n_kv_heads} (GQA groups)")
        if self.quant not in (None, "int8"):
            raise ValueError(f"quant={self.quant!r}; valid: None, "
                             f"'int8'")
        if self.remat_policy not in ("full", "dots"):
            raise ValueError(f"remat_policy={self.remat_policy!r}; "
                             f"valid: 'full', 'dots'")

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    # ---- presets (sizes follow the public Llama-3 family; kwargs
    # override any preset default, e.g. max_seq_len / remat) ----
    @staticmethod
    def llama3_8b(**kw) -> "LlamaConfig":
        return LlamaConfig(**{**dict(
            vocab_size=128256, d_model=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, d_ff=14336, max_seq_len=8192, remat=True),
            **kw})

    @staticmethod
    def llama3_1b(**kw) -> "LlamaConfig":
        return LlamaConfig(**{**dict(
            vocab_size=128256, d_model=2048, n_layers=16, n_heads=32,
            n_kv_heads=8, d_ff=8192, max_seq_len=8192), **kw})

    @staticmethod
    def debug(**kw) -> "LlamaConfig":
        return LlamaConfig(vocab_size=256, d_model=64, n_layers=2,
                           n_heads=4, n_kv_heads=2, d_ff=128,
                           max_seq_len=128, **kw)


def _proj(cfg: LlamaConfig, features: int, name: str):
    """Projection layer: nn.Dense, or QuantDense under quant='int8'
    (same param-tree position; kernel -> kernel_q/scale)."""
    if cfg.quant == "int8":
        from ..ops.quant import QuantDense  # noqa: PLC0415
        return QuantDense(features, name=name, dtype=cfg.dtype)
    return nn.Dense(features, use_bias=False, name=name,
                    dtype=cfg.dtype, param_dtype=cfg.param_dtype)


class LlamaAttention(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, cos, sin, cache=None, positions=None):
        cfg = self.cfg
        hd = cfg.head_dim
        q = _proj(cfg, cfg.n_heads * hd, "q_proj")(x)
        k = _proj(cfg, cfg.n_kv_heads * hd, "k_proj")(x)
        v = _proj(cfg, cfg.n_kv_heads * hd, "v_proj")(x)
        b, s, _ = x.shape
        q = q.reshape(b, s, cfg.n_heads, hd)
        k = k.reshape(b, s, cfg.n_kv_heads, hd)
        v = v.reshape(b, s, cfg.n_kv_heads, hd)
        q = apply_rotary(q, cos, sin, positions)
        k = apply_rotary(k, cos, sin, positions)

        new_cache = None
        if cache is None:
            out = multi_head_attention(q, k, v, causal=True,
                                       impl=cfg.attn_impl)
        else:
            # Decode: write new k/v at `positions`, attend over prefix
            # (shared zoo-wide cached path, ops/attention.py).
            out, new_cache = cached_attention(q, k, v, cache, positions,
                                              impl=cfg.attn_impl)

        out = out.reshape(b, s, cfg.n_heads * hd)
        out = _proj(cfg, cfg.d_model, "o_proj")(out)
        return out, new_cache


class LlamaMLP(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        gate = _proj(cfg, cfg.d_ff, "gate_proj")(x)
        up = _proj(cfg, cfg.d_ff, "up_proj")(x)
        return _proj(cfg, cfg.d_model, "down_proj")(swiglu(gate, up))


class LlamaBlock(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, cos, sin, cache=None, positions=None):
        cfg = self.cfg
        attn_norm_w = self.param("attn_norm", nn.initializers.ones,
                                 (cfg.d_model,))
        mlp_norm_w = self.param("mlp_norm", nn.initializers.ones,
                                (cfg.d_model,))
        h, new_cache = LlamaAttention(cfg, name="attention")(
            rms_norm(x, attn_norm_w, cfg.norm_eps), cos, sin, cache,
            positions)
        x = x + h
        x = x + LlamaMLP(cfg, name="mlp")(
            rms_norm(x, mlp_norm_w, cfg.norm_eps))
        return x, new_cache


class _LMHead(nn.Module):
    """Untied head, kernel stored at params['lm_head']['kernel'] (same
    tree as nn.Dense) in `param_dtype`. Matmul runs bf16-in/fp32-
    accumulate — MXU native — instead of nn.Dense(dtype=fp32)'s
    full-fp32 pass."""
    vocab_size: int
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (x.shape[-1], self.vocab_size),
                            self.param_dtype)
        return jnp.einsum("bsd,dv->bsv", x, kernel.astype(x.dtype),
                          preferred_element_type=jnp.float32)


class Llama(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, tokens, cache=None, positions=None):
        """tokens: (B, S) int32. cache: optional list of per-layer
        (k, v, lengths). Returns (logits, new_cache)."""
        cfg = self.cfg
        embed = nn.Embed(cfg.vocab_size, cfg.d_model, name="token_embed",
                         dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                         embedding_init=nn.initializers.normal(0.02))
        from ..parallel.sharding import constrain_activations  # noqa: PLC0415
        # Pin the residual stream to batch/sequence sharding right at the
        # embed: the (vocab, d) table is (tp, fsdp)-sharded, and without
        # the pin XLA carries the table's d-sharding into the hiddens and
        # the backward re-shards them with a full rematerialization.
        x = constrain_activations(embed(tokens))
        cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len,
                                    cfg.rope_theta)
        new_cache = []
        # remat trades recompute for HBM on the train path only; the decode
        # path (cache is not None) never checkpoints. Param paths stay
        # "layer_{i}/..." under both classes, so one weight pytree serves
        # train and serve.
        if cfg.remat and cache is None:
            policy = (jax.checkpoint_policies
                      .dots_with_no_batch_dims_saveable
                      if cfg.remat_policy == "dots" else None)
            block_cls = nn.remat(LlamaBlock, policy=policy)
        else:
            block_cls = LlamaBlock
        for i in range(cfg.n_layers):
            block = block_cls(cfg, name=f"layer_{i}")
            x, c = block(x, cos, sin,
                         None if cache is None else cache[i], positions)
            new_cache.append(c)
        final_w = self.param("final_norm", nn.initializers.ones,
                             (cfg.d_model,))
        x = rms_norm(x, final_w, cfg.norm_eps)
        if cfg.tie_embeddings:
            # bf16 operands + fp32 accumulation: fp32-quality logits at
            # bf16 MXU speed (casting both sides to fp32 would force slow
            # fp32 passes on the biggest matmul in the model).
            logits = jnp.einsum("bsd,vd->bsv", x,
                                embed.embedding.astype(x.dtype),
                                preferred_element_type=jnp.float32)
        else:
            logits = _LMHead(cfg.vocab_size, cfg.param_dtype,
                             name="lm_head")(x)
        return logits, (new_cache if cache is not None else None)

    # ---- convenience ----
    def init_params(self, rng, batch=1, seq=8):
        tokens = jnp.zeros((batch, seq), dtype=jnp.int32)
        return self.init(rng, tokens)["params"]

    def empty_cache(self, batch: int, max_len: int,
                    dtype=jnp.bfloat16):
        cfg = self.cfg
        return [
            (jnp.zeros((batch, max_len, cfg.n_kv_heads, cfg.head_dim),
                       dtype=dtype),
             jnp.zeros((batch, max_len, cfg.n_kv_heads, cfg.head_dim),
                       dtype=dtype),
             jnp.zeros((batch,), dtype=jnp.int32))
            for _ in range(cfg.n_layers)
        ]
