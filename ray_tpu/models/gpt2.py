"""GPT-2 family (124M "small" is the BASELINE.json reference config).

From-scratch flax implementation: learned positional embeddings, pre-LN
blocks, GELU MLP, tied LM head. HF weight import lives in
ray_tpu/train/adapters.py.
"""
from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops import layer_norm, multi_head_attention, cached_attention


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    max_seq_len: int = 1024
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = False
    attn_impl: str = "auto"         # "auto" | "xla" | "pallas"

    @property
    def d_ff(self) -> int:
        return 4 * self.d_model

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def n_kv_heads(self) -> int:
        return self.n_heads   # MHA: the serving engine sizes KV by this

    @staticmethod
    def small(**kw) -> "GPT2Config":      # 124M
        return GPT2Config(**kw)

    @staticmethod
    def medium(**kw) -> "GPT2Config":     # 350M
        return GPT2Config(d_model=1024, n_layers=24, n_heads=16, **kw)

    @staticmethod
    def large(**kw) -> "GPT2Config":      # 774M
        return GPT2Config(d_model=1280, n_layers=36, n_heads=20, **kw)

    @staticmethod
    def debug(**kw) -> "GPT2Config":
        return GPT2Config(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                          max_seq_len=64, **kw)


class GPT2Block(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, x, cache=None, positions=None):
        cfg = self.cfg
        ln1_w = self.param("ln_1_scale", nn.initializers.ones, (cfg.d_model,))
        ln1_b = self.param("ln_1_bias", nn.initializers.zeros, (cfg.d_model,))
        ln2_w = self.param("ln_2_scale", nn.initializers.ones, (cfg.d_model,))
        ln2_b = self.param("ln_2_bias", nn.initializers.zeros, (cfg.d_model,))

        h = layer_norm(x, ln1_w, ln1_b, cfg.norm_eps)
        qkv = nn.Dense(3 * cfg.d_model, name="qkv", dtype=cfg.dtype)(h)
        b, s, _ = x.shape
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, cfg.n_heads, cfg.head_dim)
        k = k.reshape(b, s, cfg.n_heads, cfg.head_dim)
        v = v.reshape(b, s, cfg.n_heads, cfg.head_dim)
        new_cache = None
        if cache is None:
            att = multi_head_attention(q, k, v, causal=True,
                                       impl=cfg.attn_impl)
        else:
            att, new_cache = cached_attention(q, k, v, cache, positions,
                                              impl=cfg.attn_impl)
        att = att.reshape(b, s, cfg.d_model)
        x = x + nn.Dense(cfg.d_model, name="attn_out", dtype=cfg.dtype)(att)

        h = layer_norm(x, ln2_w, ln2_b, cfg.norm_eps)
        h = nn.Dense(cfg.d_ff, name="fc_in", dtype=cfg.dtype)(h)
        h = jax.nn.gelu(h)
        x = x + nn.Dense(cfg.d_model, name="fc_out", dtype=cfg.dtype)(h)
        return x, new_cache


class GPT2(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, tokens, cache=None, positions=None):
        """tokens (B, S) -> (logits, cache) when cache is given, plain
        logits otherwise (training callers predate the serving
        contract). With cache: same per-layer (k, v, lengths) pytree as
        Llama, so the LLM engine serves GPT-2 too; `positions` also
        select the learned positional embeddings."""
        cfg = self.cfg
        wte = nn.Embed(cfg.vocab_size, cfg.d_model, name="wte",
                       dtype=cfg.dtype,
                       embedding_init=nn.initializers.normal(0.02))
        wpe = nn.Embed(cfg.max_seq_len, cfg.d_model, name="wpe",
                       dtype=cfg.dtype,
                       embedding_init=nn.initializers.normal(0.01))
        b, s = tokens.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
        x = wte(tokens) + wpe(jnp.clip(positions, 0, cfg.max_seq_len - 1))
        block_cls = (nn.remat(GPT2Block)
                     if (cfg.remat and cache is None) else GPT2Block)
        new_cache = []
        for i in range(cfg.n_layers):
            x, c = block_cls(cfg, name=f"h_{i}")(
                x, None if cache is None else cache[i], positions)
            new_cache.append(c)
        lnf_w = self.param("ln_f_scale", nn.initializers.ones, (cfg.d_model,))
        lnf_b = self.param("ln_f_bias", nn.initializers.zeros, (cfg.d_model,))
        x = layer_norm(x, lnf_w, lnf_b, cfg.norm_eps)
        # Tied head: bf16 operands + fp32 accumulation. Casting both sides
        # to fp32 would force fp32 MXU passes on the single biggest matmul
        # (d_model x vocab); preferred_element_type gives fp32 logits at
        # bf16 matmul speed (Embed.attend would demote the ACCUMULATION to
        # bf16, which does hurt the loss).
        logits = jnp.einsum("bsd,vd->bsv", x,
                            wte.embedding.astype(x.dtype),
                            preferred_element_type=jnp.float32)
        if cache is None:
            return logits
        return logits, new_cache

    def init_params(self, rng, batch=1, seq=8):
        tokens = jnp.zeros((batch, seq), dtype=jnp.int32)
        return self.init(rng, tokens)["params"]
