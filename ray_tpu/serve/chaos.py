"""Deterministic serve-plane fault injection.

The chaos tests (tests/test_serve_fault_tolerance.py) and the
`bench.py --phase serve_ft` MTTR measurement need to break a CHOSEN
replica at a CHOSEN moment — not wait for entropy. This module is the
one sanctioned way to do that: every helper targets one replica of one
deployment through the controller's routing table, so a test reads as
"kill replica 0 mid-stream; assert the failover chain".

Fault modes (see Replica.chaos for the replica-side halves):

- ``kill_replica``     — hard-kill the replica actor (preemption /
  OOM-kill stand-in); in-flight calls raise ActorDiedError.
- ``crash_replica``    — the replica process os._exit()s itself
  (segfault stand-in; exercises the same death path from inside).
- ``wedge_replica``    — stall the hosted LLM engine's loop thread so
  the REAL watchdog declares it wedged (hung device call stand-in).
- ``hang_health``      — health probes block until the controller's
  probe timeout fires.
- ``fail_health``      — health probes raise (generic sickness).
- ``delay_replica``    — every request sleeps first (slow replica).
- ``reset``            — clear injected delay/health modes.

All helpers are no-ops on deployments they can't find — chaos should
fail tests through ASSERTIONS, not through tooling errors.
"""
from __future__ import annotations

import time
from typing import Any, List, Optional, Tuple


def _controller():
    import ray_tpu
    from .controller import CONTROLLER_NAME
    return ray_tpu.get_actor(CONTROLLER_NAME)


def list_replicas(app_name: str, deployment_name: str) -> List[dict]:
    """Controller-side replica snapshots (all states, health counters)."""
    import ray_tpu
    return ray_tpu.get(_controller().list_replicas.remote(
        app_name, deployment_name))


def running_replicas(app_name: str,
                     deployment_name: str) -> List[Tuple[str, Any]]:
    """[(replica_id, actor_handle)] for RUNNING replicas."""
    import ray_tpu
    return ray_tpu.get(_controller().get_replicas.remote(
        app_name, deployment_name))


def _pick(app_name: str, deployment_name: str,
          replica_id: Optional[str], index: int) -> Tuple[str, Any]:
    reps = running_replicas(app_name, deployment_name)
    if not reps:
        raise LookupError(
            f"no RUNNING replicas for {app_name}/{deployment_name}")
    if replica_id is not None:
        for rid, handle in reps:
            if rid == replica_id:
                return rid, handle
        raise LookupError(f"replica {replica_id!r} not RUNNING")
    return reps[index % len(reps)]


def kill_replica(app_name: str = "default",
                 deployment_name: str = "", *,
                 replica_id: Optional[str] = None,
                 index: int = 0) -> str:
    """Hard-kill one RUNNING replica actor (external preemption).
    Returns the killed replica id; in-flight requests on it raise
    ActorDiedError and fail over."""
    import ray_tpu
    rid, handle = _pick(app_name, deployment_name, replica_id, index)
    ray_tpu.kill(handle)
    return rid


def crash_replica(app_name: str = "default",
                  deployment_name: str = "", *,
                  replica_id: Optional[str] = None,
                  index: int = 0) -> str:
    """The replica process exits itself (os._exit) — same death path as
    a segfault, observed from inside rather than via ray_tpu.kill."""
    rid, handle = _pick(app_name, deployment_name, replica_id, index)
    handle.chaos.remote("die")   # never completes; the process is gone
    return rid


def wedge_replica(app_name: str = "default",
                  deployment_name: str = "", *,
                  seconds: float = 3600.0,
                  replica_id: Optional[str] = None,
                  index: int = 0) -> str:
    """Stall the replica's LLM engine loop for `seconds` so the real
    watchdog path fires (llm_engine.wedged -> health fail `wedged` ->
    replacement). Only valid on replicas hosting an engine."""
    import ray_tpu
    rid, handle = _pick(app_name, deployment_name, replica_id, index)
    ray_tpu.get(handle.chaos.remote("wedge", seconds))
    return rid


def hang_health(app_name: str = "default", deployment_name: str = "", *,
                replica_id: Optional[str] = None, index: int = 0) -> str:
    """Health probes on the chosen replica block until the controller's
    probe timeout (RAY_TPU_SERVE_HEALTH_TIMEOUT_S) declares failure."""
    import ray_tpu
    rid, handle = _pick(app_name, deployment_name, replica_id, index)
    ray_tpu.get(handle.chaos.remote("health_hang"))
    return rid


def fail_health(app_name: str = "default", deployment_name: str = "", *,
                replica_id: Optional[str] = None, index: int = 0) -> str:
    """Health probes on the chosen replica raise immediately."""
    import ray_tpu
    rid, handle = _pick(app_name, deployment_name, replica_id, index)
    ray_tpu.get(handle.chaos.remote("health_fail"))
    return rid


def delay_replica(app_name: str = "default",
                  deployment_name: str = "", *, seconds: float,
                  replica_id: Optional[str] = None,
                  index: int = 0) -> str:
    """Every request admitted by the chosen replica sleeps `seconds`
    before running (slow-replica / deadline-pressure scenarios)."""
    import ray_tpu
    rid, handle = _pick(app_name, deployment_name, replica_id, index)
    ray_tpu.get(handle.chaos.remote("delay", seconds))
    return rid


def reset(app_name: str = "default", deployment_name: str = "") -> None:
    """Clear injected delay/health chaos on every RUNNING replica."""
    import ray_tpu
    for _rid, handle in running_replicas(app_name, deployment_name):
        try:
            ray_tpu.get(handle.chaos.remote("reset"))
        except Exception:  # noqa: BLE001
            pass


def wait_for_replacement(app_name: str, deployment_name: str,
                         dead_replica_id: str,
                         timeout_s: float = 30.0) -> List[str]:
    """Block until the controller runs a replacement RUNNING replica
    that is not `dead_replica_id`; returns the RUNNING ids."""
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        ids = [rid for rid, _h in running_replicas(
            app_name, deployment_name)]
        if ids and dead_replica_id not in ids:
            return ids
        time.sleep(0.05)
    raise TimeoutError(
        f"no replacement for {dead_replica_id} after {timeout_s}s")


__all__ = ["list_replicas", "running_replicas", "kill_replica",
           "crash_replica", "wedge_replica", "hang_health",
           "fail_health", "delay_replica", "reset",
           "wait_for_replacement"]
