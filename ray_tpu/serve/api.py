"""serve.run / shutdown / status — the user-facing control API.

Reference parity: python/ray/serve/api.py (run, delete, status,
get_deployment_handle, get_app_handle) + _private/api.py (controller
bootstrap). The application graph from `.bind()` is flattened here:
nested Applications in init args are deployed too and replaced with
DeploymentHandles before the args ship to replicas.
"""
from __future__ import annotations

import time
from typing import Any, Dict, Optional

from .config import HTTPOptions
from .controller import CONTROLLER_NAME, ServeController
from .deployment import Application, Deployment
from .handle import DeploymentHandle

_DEFAULT_APP = "default"


def _get_or_start_controller(http_options: Optional[HTTPOptions] = None):
    import ray_tpu
    ray_tpu.init()
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:  # noqa: BLE001  not started yet
        opts = http_options or HTTPOptions()
        # checkpoint_interval_s throttles the controller's __ray_save__
        # (deployment-target persistence for driver restart): without
        # it every routing-table RPC would ship a checkpoint blob
        ctrl = ray_tpu.remote(ServeController).options(
            name=CONTROLLER_NAME, max_concurrency=16,
            checkpoint_interval_s=0.5).remote(
            {"host": opts.host, "port": opts.port,
             "root_path": opts.root_path})
        ray_tpu.get(ctrl.ping.remote())
        return ctrl


def start(http_options: Optional[HTTPOptions] = None, **_kw):
    """Explicitly start the serve instance (reference: serve.start)."""
    return _get_or_start_controller(http_options)


def _flatten_app(app: Application, app_name: str,
                 out: Dict[str, dict], is_ingress: bool) -> DeploymentHandle:
    """DFS the bound graph; returns the handle standing in for `app`."""
    d = app.deployment

    def convert(v):
        if isinstance(v, Application):
            return _flatten_app(v, app_name, out, is_ingress=False)
        return v

    args = tuple(convert(a) for a in app._args)
    kwargs = {k: convert(v) for k, v in app._kwargs.items()}
    if d.name in out:
        prev = out[d.name]
        if (prev["version"] != d.version_hash()
                or prev["init_args"] != args
                or prev["init_kwargs"] != kwargs):
            raise ValueError(
                f"two deployments named {d.name!r} with different code or "
                f"init args in one app; use .options(name=...) to "
                f"disambiguate")
    else:
        out[d.name] = {
            "name": d.name,
            "callable_bytes": d.callable_bytes(),
            "init_args": args,
            "init_kwargs": kwargs,
            "config": d.config.to_dict(),
            "version": d.version_hash(),
            "route_prefix": d.route_prefix if is_ingress else None,
            "is_ingress": is_ingress,
            "is_asgi": d.is_asgi,
        }
    return DeploymentHandle(d.name, app_name)


_ROUTE_UNSET = object()


def run(target: Application, *, name: str = _DEFAULT_APP,
        route_prefix=_ROUTE_UNSET, blocking: bool = False,
        _local_testing_mode: bool = False,
        wait_for_ready_timeout_s: float = 60.0) -> DeploymentHandle:
    """Deploy an application; returns a handle to its ingress.

    route_prefix overrides the ingress deployment's own prefix only when
    passed explicitly — apps built with a baked-in prefix (e.g.
    build_openai_deployment's "/v1") keep it by default.
    """
    import ray_tpu
    if isinstance(target, Deployment):
        target = target.bind()
    if not isinstance(target, Application):
        raise TypeError(f"serve.run expects an Application (from .bind()); "
                        f"got {type(target)}")
    if route_prefix is not _ROUTE_UNSET and route_prefix is not None:
        ingress_d = target.deployment
        if ingress_d.route_prefix != route_prefix:
            target = Application(
                ingress_d.options(route_prefix=route_prefix),
                target._args, target._kwargs)
    ctrl = _get_or_start_controller()
    deployments: Dict[str, dict] = {}
    ingress_handle = _flatten_app(target, name, deployments, is_ingress=True)
    ray_tpu.get(ctrl.deploy_application.remote(
        name, list(deployments.values())))
    _wait_healthy(ctrl, name, wait_for_ready_timeout_s)
    if blocking:
        try:
            while True:
                time.sleep(1)
        except KeyboardInterrupt:
            pass
    return ingress_handle


def _wait_healthy(ctrl, app_name: str, timeout_s: float):
    import ray_tpu
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        status = ray_tpu.get(ctrl.get_app_status.remote(app_name))
        if status["status"] == "RUNNING" or (
                status["deployments"]
                and all(d["replicas"] >= d["target"] and d["target"] > 0
                        for d in status["deployments"].values())):
            return
        if status["status"] == "DEPLOY_FAILED":
            raise RuntimeError(f"deploy failed: {status}")
        time.sleep(0.05)
    raise TimeoutError(
        f"app {app_name!r} not healthy after {timeout_s}s")


def status() -> Dict[str, Any]:
    import ray_tpu
    ctrl = _get_or_start_controller()
    apps = ray_tpu.get(ctrl.list_applications.remote())
    return {"applications": {
        a: ray_tpu.get(ctrl.get_app_status.remote(a)) for a in apps}}


def delete(name: str, _blocking: bool = True):
    import ray_tpu
    ctrl = _get_or_start_controller()
    ray_tpu.get(ctrl.delete_application.remote(name))


def get_app_handle(name: str = _DEFAULT_APP) -> DeploymentHandle:
    import ray_tpu
    ctrl = _get_or_start_controller()
    routes = ray_tpu.get(ctrl.get_routes.remote())
    for _prefix, target in routes.items():
        if target[0] == name:
            return DeploymentHandle(target[1], target[0])
    apps = ray_tpu.get(ctrl.list_applications.remote())
    if name in apps and apps[name]:
        return DeploymentHandle(apps[name][0], name)
    raise KeyError(f"no application named {name!r}")


def get_deployment_handle(deployment_name: str,
                          app_name: str = _DEFAULT_APP) -> DeploymentHandle:
    return DeploymentHandle(deployment_name, app_name)


def register_prefix(prefix, *, key: Optional[str] = None,
                    app_name: str = _DEFAULT_APP,
                    deployment_name: Optional[str] = None) -> str:
    """Register a shared prompt prefix (e.g. a system prompt) against a
    deployment for warm-KV affinity routing.

    The controller pre-fills it on the replica that owns the returned
    affinity key on the routing hash ring, and on every replica started
    later (replacements / scale-ups). Requests whose prompt starts with
    the prefix are then sticky-routed to the warm replica by every
    handle and proxy (serve/router.py). The deployment's callable must
    expose a `register_prefix` method (LLMServer does). Returns the
    affinity key."""
    import ray_tpu
    ctrl = _get_or_start_controller()
    if deployment_name is None:
        ingress = ray_tpu.get(ctrl.get_ingress_targets.remote())
        deployment_name = ingress.get(app_name)
        if deployment_name is None:
            raise KeyError(f"no application named {app_name!r}")
    return ray_tpu.get(ctrl.register_prefix.remote(
        app_name, deployment_name, prefix, key))


def shutdown():
    """Tear down all serve apps and the controller."""
    import ray_tpu
    if not ray_tpu.is_initialized():
        return
    try:
        ctrl = ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:  # noqa: BLE001
        return
    try:
        # generous bound: graceful_shutdown itself waits up to the
        # longest per-deployment graceful_shutdown_timeout_s for
        # in-flight work to drain (returns immediately when idle)
        ray_tpu.get(ctrl.graceful_shutdown.remote(), timeout=30)
    except Exception:  # noqa: BLE001
        pass
    try:
        # kill must run even when the drain wait timed out above — a
        # surviving named controller with a stopped reconcile loop
        # would be silently reused by the next serve.run()
        ray_tpu.kill(ctrl)
    except Exception:  # noqa: BLE001
        pass
