"""@serve.batch — coalesce concurrent requests into one handler call.

Reference parity: python/ray/serve/batching.py (_BatchQueue semantics:
max_batch_size, batch_wait_timeout_s; the wrapped fn receives a list and
must return a list of equal length). TPU relevance: batching is what keeps
the MXU fed — a replica handling N concurrent requests runs ONE forward
pass of batch N instead of N singleton passes.
"""
from __future__ import annotations

import asyncio
import functools
from typing import Any, Callable, List, Optional


class _BatchQueue:
    def __init__(self, fn, max_batch_size: int, batch_wait_timeout_s: float):
        self._fn = fn
        self._max = max_batch_size
        self._timeout = batch_wait_timeout_s
        self._queue: Optional[asyncio.Queue] = None
        self._runner_task = None

    def _ensure_started(self):
        if self._queue is None:
            self._queue = asyncio.Queue()
            self._runner_task = asyncio.ensure_future(self._runner())

    async def submit(self, item) -> Any:
        self._ensure_started()
        fut = asyncio.get_running_loop().create_future()
        await self._queue.put((item, fut))
        return await fut

    async def _runner(self):
        while True:
            item, fut = await self._queue.get()
            batch = [(item, fut)]
            if self._timeout > 0:
                deadline = asyncio.get_running_loop().time() + self._timeout
                while len(batch) < self._max:
                    remaining = deadline - asyncio.get_running_loop().time()
                    if remaining <= 0:
                        break
                    try:
                        batch.append(await asyncio.wait_for(
                            self._queue.get(), timeout=remaining))
                    except asyncio.TimeoutError:
                        break
            else:
                while len(batch) < self._max and not self._queue.empty():
                    batch.append(self._queue.get_nowait())
            items = [b[0] for b in batch]
            try:
                results = self._fn(items)
                if asyncio.iscoroutine(results):
                    results = await results
                if len(results) != len(items):
                    raise ValueError(
                        f"@serve.batch fn returned {len(results)} results "
                        f"for {len(items)} inputs")
                for (_, f), r in zip(batch, results):
                    if not f.done():
                        f.set_result(r)
            except BaseException as e:  # noqa: BLE001
                for _, f in batch:
                    if not f.done():
                        f.set_exception(e)


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: the wrapped handler receives List[request] and returns
    List[response]. Callers invoke it with a single request."""

    def deco(fn):
        queues = {}  # per-instance queue for methods; single for functions

        @functools.wraps(fn)
        async def wrapper(*args):
            if len(args) == 2:  # bound method: (self, item)
                self_obj, item = args
                key = id(self_obj)
                if key not in queues:
                    queues[key] = _BatchQueue(
                        functools.partial(fn, self_obj),
                        max_batch_size, batch_wait_timeout_s)
                return await queues[key].submit(item)
            (item,) = args
            if None not in queues:
                queues[None] = _BatchQueue(fn, max_batch_size,
                                           batch_wait_timeout_s)
            return await queues[None].submit(item)

        def _set(**kw):
            nonlocal max_batch_size, batch_wait_timeout_s
            max_batch_size = kw.get("max_batch_size", max_batch_size)
            batch_wait_timeout_s = kw.get("batch_wait_timeout_s",
                                          batch_wait_timeout_s)
            queues.clear()
        wrapper.set_max_batch_size = \
            lambda v: _set(max_batch_size=v)
        wrapper.set_batch_wait_timeout_s = \
            lambda v: _set(batch_wait_timeout_s=v)
        return wrapper

    if _fn is not None:
        return deco(_fn)
    return deco
