"""gRPC ingress actor.

Reference parity: python/ray/serve/_private/proxy.py gRPC path +
grpc_util.py — the reference proxy serves user-defined gRPC services
next to HTTP, selecting the target application from the `application`
request metadata. TPU-first simplification: one generic byte-level
service (no protoc step),

    /ray_tpu.serve.ServeAPI/Predict        unary   -> unary
    /ray_tpu.serve.ServeAPI/PredictStream  unary   -> server stream

with JSON payloads in/out. The target application comes from the
`application` metadata key (same convention as the reference); with a
single running application the metadata may be omitted.
"""
from __future__ import annotations

import json
import threading
from typing import Dict, Optional

from .handle import DeploymentHandle

GRPC_PROXY_NAME = "_SERVE_GRPC_PROXY"
_SERVICE = "ray_tpu.serve.ServeAPI"


class GrpcProxy:
    """Actor: owns the grpc.server; refreshes routes from the controller."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        import grpc

        self._routes: Dict[str, DeploymentHandle] = {}   # app -> handle
        self._routes_lock = threading.Lock()
        proxy = self

        def _resolve(context) -> DeploymentHandle:
            md = dict(context.invocation_metadata())
            app = md.get("application")
            with proxy._routes_lock:
                routes = dict(proxy._routes)
            if app is not None:
                handle = routes.get(app)
                if handle is None:
                    context.abort(grpc.StatusCode.NOT_FOUND,
                                  f"no application {app!r}; running: "
                                  f"{sorted(routes)}")
                return handle
            if len(routes) == 1:
                return next(iter(routes.values()))
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"{len(routes)} applications running; pass "
                          f"'application' metadata to pick one")

        def _decode(request: bytes):
            return json.loads(request) if request else None

        def _encode(result) -> bytes:
            if isinstance(result, bytes):
                return result
            if isinstance(result, str):
                return result.encode()
            return json.dumps(result).encode()

        def predict(request: bytes, context) -> bytes:
            handle = _resolve(context)
            try:
                # ValueError covers JSONDecodeError AND the
                # UnicodeDecodeError non-UTF-8 bytes raise first
                body = _decode(request)
            except ValueError as e:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, repr(e))
            try:
                return _encode(handle.remote(body).result(timeout_s=60))
            except Exception as e:  # noqa: BLE001
                context.abort(grpc.StatusCode.INTERNAL, repr(e))

        def predict_stream(request: bytes, context):
            handle = _resolve(context)
            try:
                body = _decode(request)
            except ValueError as e:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, repr(e))
            gen = handle.options(stream=True).remote(body)
            try:
                for chunk in gen:
                    yield _encode(chunk)
            except Exception as e:  # noqa: BLE001
                context.abort(grpc.StatusCode.INTERNAL, repr(e))
            finally:
                # client cancellation raises GeneratorExit here (not
                # Exception): release the stream's replica accounting
                gen.close()

        class Handler(grpc.GenericRpcHandler):
            def service(self, call_details):
                if call_details.method == f"/{_SERVICE}/Predict":
                    return grpc.unary_unary_rpc_method_handler(predict)
                if call_details.method == f"/{_SERVICE}/PredictStream":
                    return grpc.unary_stream_rpc_method_handler(
                        predict_stream)
                return None

        from concurrent import futures
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=8))
        self._server.add_generic_rpc_handlers((Handler(),))
        self._port = self._server.add_insecure_port(f"{host}:{port}")
        self._server.start()
        threading.Thread(target=self._route_refresh_loop, daemon=True,
                         name="serve-grpc-routes").start()

    def _route_refresh_loop(self):
        from ._proxy_util import rebuild_handles, refresh_routes_forever

        def apply(targets):
            # get_ingress_targets includes route_prefix=None apps:
            # gRPC routing is by application NAME, no HTTP prefix needed
            with self._routes_lock:
                self._routes = rebuild_handles(
                    self._routes,
                    {app: (app, dep) for app, dep in targets.items()})

        refresh_routes_forever(
            lambda ctrl: ctrl.get_ingress_targets.remote(), apply)

    def ready(self) -> int:
        return self._port

    def ping(self) -> bool:
        return True


def start_grpc_proxy(host: str = "127.0.0.1", port: int = 0):
    """Start (or fetch) the gRPC proxy actor; returns (handle, port)."""
    from ._proxy_util import get_or_create_proxy
    return get_or_create_proxy(GRPC_PROXY_NAME, GrpcProxy, host, port)


__all__ = ["GrpcProxy", "start_grpc_proxy", "GRPC_PROXY_NAME"]
