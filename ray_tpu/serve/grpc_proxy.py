"""gRPC ingress actor.

Reference parity: python/ray/serve/_private/proxy.py gRPC path +
grpc_util.py — the reference proxy serves user-defined gRPC services
next to HTTP, selecting the target application from the `application`
request metadata. TPU-first simplification: one generic byte-level
service (no protoc step),

    /ray_tpu.serve.ServeAPI/Predict        unary   -> unary
    /ray_tpu.serve.ServeAPI/PredictStream  unary   -> server stream

with JSON payloads in/out. The target application comes from the
`application` metadata key (same convention as the reference); with a
single running application the metadata may be omitted.
"""
from __future__ import annotations

import json
import threading
from typing import Dict, Optional

from .handle import DeploymentHandle

GRPC_PROXY_NAME = "_SERVE_GRPC_PROXY"
_SERVICE = "ray_tpu.serve.ServeAPI"


class GrpcProxy:
    """Actor: owns the grpc.server; refreshes routes from the controller."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        import grpc

        self._routes: Dict[str, DeploymentHandle] = {}   # app -> handle
        self._routes_lock = threading.Lock()
        proxy = self

        def _resolve(context) -> DeploymentHandle:
            md = dict(context.invocation_metadata())
            app = md.get("application")
            with proxy._routes_lock:
                routes = dict(proxy._routes)
            if app is not None:
                handle = routes.get(app)
                if handle is None:
                    context.abort(grpc.StatusCode.NOT_FOUND,
                                  f"no application {app!r}; running: "
                                  f"{sorted(routes)}")
                return handle
            if len(routes) == 1:
                return next(iter(routes.values()))
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"{len(routes)} applications running; pass "
                          f"'application' metadata to pick one")

        def _decode(request: bytes):
            return json.loads(request) if request else None

        def _encode(result) -> bytes:
            if isinstance(result, bytes):
                return result
            if isinstance(result, str):
                return result.encode()
            return json.dumps(result).encode()

        def _status_for(e):
            """Typed serve-FT failures map to retriable gRPC codes —
            one shared classifier with the HTTP ingress, per-protocol
            code table here."""
            from ..exceptions import classify_request_failure
            return {
                "backpressure": grpc.StatusCode.RESOURCE_EXHAUSTED,
                "no_capacity": grpc.StatusCode.RESOURCE_EXHAUSTED,
                "shed": grpc.StatusCode.UNAVAILABLE,        # retriable
                "interrupted": grpc.StatusCode.UNAVAILABLE,  # retriable
                "timeout": grpc.StatusCode.DEADLINE_EXCEEDED,
                "error": grpc.StatusCode.INTERNAL,
            }[classify_request_failure(e)]

        def _deadline(context):
            """Absolute deadline from the client's gRPC timeout, else
            the proxy default (shared with the HTTP ingress)."""
            import time as _time

            from .config import default_request_timeout_s as \
                _default_timeout_s
            budget = context.time_remaining()
            if budget is None or budget > 86400:
                # no client deadline: grpc reports None or a huge
                # sentinel (which would overflow downstream waits).
                # Only the OPERATOR default may disable the bound.
                budget = _default_timeout_s()
                if budget <= 0:
                    return None
            elif budget <= 0:
                # client deadline ALREADY expired at read time: stamp
                # a now-deadline so the request is shed downstream, not
                # executed unbounded for a caller that is already gone
                budget = 1e-4
            return _time.time() + budget

        def _affinity_kw(context):
            """Session affinity from `session-id` request metadata —
            the gRPC twin of the HTTP X-Serve-Session-Id header."""
            sid = dict(context.invocation_metadata()).get("session-id")
            return {"__serve_affinity_key": sid} if sid else {}

        def predict(request: bytes, context) -> bytes:
            import time as _time
            handle = _resolve(context)
            try:
                # ValueError covers JSONDecodeError AND the
                # UnicodeDecodeError non-UTF-8 bytes raise first
                body = _decode(request)
            except ValueError as e:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, repr(e))
            deadline_ts = _deadline(context)
            try:
                return _encode(handle.remote(
                    body, __serve_deadline_ts=deadline_ts,
                    **_affinity_kw(context)).result(
                    timeout_s=(None if deadline_ts is None
                               else max(0.1,
                                        deadline_ts - _time.time()))))
            except Exception as e:  # noqa: BLE001
                context.abort(_status_for(e), repr(e))

        def predict_stream(request: bytes, context):
            handle = _resolve(context)
            try:
                body = _decode(request)
            except ValueError as e:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, repr(e))
            gen = handle.options(stream=True).remote(
                body, __serve_deadline_ts=_deadline(context),
                **_affinity_kw(context))
            try:
                for chunk in gen:
                    yield _encode(chunk)
            except Exception as e:  # noqa: BLE001
                context.abort(_status_for(e), repr(e))
            finally:
                # client cancellation raises GeneratorExit here (not
                # Exception): release the stream's replica accounting
                gen.close()

        class Handler(grpc.GenericRpcHandler):
            def service(self, call_details):
                if call_details.method == f"/{_SERVICE}/Predict":
                    return grpc.unary_unary_rpc_method_handler(predict)
                if call_details.method == f"/{_SERVICE}/PredictStream":
                    return grpc.unary_stream_rpc_method_handler(
                        predict_stream)
                return None

        from concurrent import futures
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=8))
        self._server.add_generic_rpc_handlers((Handler(),))
        self._port = self._server.add_insecure_port(f"{host}:{port}")
        self._server.start()
        threading.Thread(target=self._route_refresh_loop, daemon=True,
                         name="serve-grpc-routes").start()

    def _route_refresh_loop(self):
        from ._proxy_util import rebuild_handles, refresh_routes_forever

        def apply(targets):
            # get_ingress_targets includes route_prefix=None apps:
            # gRPC routing is by application NAME, no HTTP prefix needed
            with self._routes_lock:
                self._routes = rebuild_handles(
                    self._routes,
                    {app: (app, dep) for app, dep in targets.items()})

        refresh_routes_forever(
            lambda ctrl: ctrl.get_ingress_targets.remote(), apply)

    def ready(self) -> int:
        return self._port

    def ping(self) -> bool:
        return True


def start_grpc_proxy(host: str = "127.0.0.1", port: int = 0):
    """Start (or fetch) the gRPC proxy actor; returns (handle, port)."""
    from ._proxy_util import get_or_create_proxy
    return get_or_create_proxy(GRPC_PROXY_NAME, GrpcProxy, host, port)


__all__ = ["GrpcProxy", "start_grpc_proxy", "GRPC_PROXY_NAME"]
